"""Replica process entry: one policy-server worker in the router's pool.

`replica_main` is the spawn target. It stays deliberately light at
import time — the heavy stack (specs -> jax -> XLA) loads only inside
`policy_server_factory`, so a mock-backend replica (tests, bench
plumbing smoke) boots in fractions of a second while a real one pays
the jax import exactly once.

A replica owns: its request queue (router -> replica), the shared
response queue (replica -> router), and the shared free-slot queue of
the request shm ring (names go back as soon as a payload is copied
out). The protocol is at the bottom of this docstring; the router is
the only peer.

Chaos scope: each replica declares `r<index>` (testing/chaos.py), so a
plan can target one replica of a fleet ("r0/predict:3:kill") while its
siblings stay healthy — which is exactly the partial-failure regime the
router's retry/hedge/eviction logic exists for.

Wire protocol (all tuples, pickled by multiprocessing):

  router -> replica (request queue):
    ("req", req_id, attempt, deadline_wall_s, payload[, policy_id])
                                                         payload: transport.py
    ("health", probe_id)
    ("swap", swap_id, deadline_wall_s[, policy_id])
    ("stop",)

The optional trailing policy_id targets one policy of a MULTI-POLICY
backend (serving/policies.py, `multi_policy = True`); absent or None
means the backend's default. A single-policy backend receiving a
policy-addressed request replies with a typed PolicyUnknown error —
never silently serving the wrong weights.

  replica -> router (shared response queue):
    ("started", index, version, pid)
    ("rsp", index, req_id, attempt, crc, blob)     blob: ("ok", outputs,
                                                   version, spans) |
                                                   ("error", class, message)
    ("health", index, probe_id, snapshot, t_wall)
    ("swapped", index, swap_id, ok, version)
    ("stopped", index)
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import socket
import time
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import numpy as np

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.serving import transport
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "ReplicaCore",
    "ReplicaSpec",
    "replica_main",
    "policy_server_factory",
    "multi_policy_store_factory",
    "mock_server_factory",
    "multi_policy_mock_factory",
]


@dataclasses.dataclass
class ReplicaSpec:
    """How a replica process builds its server.

    `factory` must be a module-level (picklable-by-name) callable
    returning a started server-like object: `submit(features,
    deadline_ms) -> future` (future: `add_done_callback`, `error()`,
    `result()`), `snapshot()`, `hot_swap(wait)`, `stop()`. `env` entries
    are applied in the child before the factory runs — `T2R_*` keys go
    through the flags registry (validated), everything else through the
    raw environment; this is the route chaos plans take into a replica.
    """

    factory: Callable
    factory_args: Tuple = ()
    factory_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    scope: Optional[str] = None  # chaos scope override (default r<index>)


def _apply_env(env: Mapping[str, str]) -> None:
    for key, value in env.items():
        if key.startswith("T2R_"):
            t2r_flags.write_env(key, value)
        else:
            os.environ[key] = value


def _server_version(server) -> int:
    version = getattr(server, "model_version", None)
    if version is not None:
        return int(version)
    try:
        return int(server.snapshot().get("model_version", -1))
    except Exception:
        return -1


class ReplicaCore:
    """Transport-agnostic replica message core.

    One instance owns a started server and answers the router protocol
    (module docstring) — `handle(message)` for each inbound tuple,
    `tick(now)` between messages so an async hot-swap still resolves,
    `close()` on the way out. Replies leave through the injected `post`
    callable, which is the ONLY transport-specific piece: the local
    fabric passes `response_q.put` (mp queue), the socket fabric
    (serving/fabric.py) passes the duplex frame-writer. Everything the
    router depends on — typed error replies, CRC'd response bodies,
    swap one-in-flight discipline, deadline-at-dequeue shedding — lives
    here exactly once, so the two fabrics cannot diverge in behavior
    any more than they can in wire bytes.

    `post` may be called from the server's compute thread (the reply
    callback) concurrently with the message loop's thread; it must be
    thread-safe. Both existing posts are: mp.Queue.put and the
    send-lock-guarded frame writer.
    """

    def __init__(self, index: int, server, post: Callable[[tuple], None],
                 free_q=None):
        self._index = index
        self._server = server
        self._post = post
        self._free_q = free_q
        self._cache = transport.ReplicaSlotCache()
        # id, old_version, deadline, policy_id (None = whole-backend swap)
        self._pending_swap: Optional[
            Tuple[int, int, float, Optional[str]]
        ] = None

    def started_message(self) -> tuple:
        return (
            "started", self._index, _server_version(self._server), os.getpid()
        )

    def _host_identity(self) -> dict:
        """This replica's host/AOT key, folded into every health
        snapshot: on a cross-host fleet the router's per-replica rows
        then SHOW which platform/topology each host resolved the
        artifact's `aot/` executables against — a transplanted topology
        is visible at the fleet surface, not just in the replica's
        logs."""
        identity = {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
        }
        # Topology only when this process ALREADY runs jax (any real
        # policy backend does): importing it here would block the first
        # health reply for seconds on a lightweight backend — long
        # enough for the router to evict the replica as silent.
        import sys

        def topology():
            from tensor2robot_tpu.export import aot as aot_lib

            return aot_lib.device_topology()

        identity["topology"] = (
            best_effort(topology) if "jax" in sys.modules else None
        )
        return identity

    def _version_of(self, policy_id: Optional[str]) -> int:
        server = self._server
        if policy_id is not None and getattr(server, "multi_policy", False):
            try:
                return int(server.policy_version(policy_id))
            except Exception:
                return -1
        return _server_version(server)

    def _post_reply(self, req_id: int, attempt: int, body) -> None:
        crc, blob = transport.pack(body)
        fault = chaos.maybe_fire("reply")
        if fault is not None and fault.action == "corrupt" and blob:
            # Flip one byte AFTER the checksum: the router must detect
            # the mismatch and treat this replica reply as a failure.
            blob = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        # Router gone -> best effort; our process is about to be reaped.
        best_effort(
            self._post, ("rsp", self._index, req_id, attempt, crc, blob)
        )

    def _on_request(self, req_id: int, attempt: int, deadline_wall: float,
                    payload, policy_id: Optional[str] = None) -> None:
        chaos.maybe_fire("recv")
        server = self._server
        try:
            features = transport.decode_request(
                payload, self._free_q, self._cache
            )
        except transport.IntegrityError as err:
            self._post_reply(
                req_id, attempt, ("error", "RequestCorrupt", str(err))
            )
            return
        remaining_ms = (deadline_wall - time.time()) * 1e3
        if remaining_ms <= 0:
            self._post_reply(
                req_id, attempt,
                ("error", "DeadlineExceeded",
                 "deadline passed before the replica dequeued the request"),
            )
            return
        if policy_id is not None and not getattr(server, "multi_policy", False):
            self._post_reply(
                req_id, attempt,
                ("error", "PolicyUnknown",
                 f"request names policy {policy_id!r} but this replica "
                 "runs a single-policy backend"),
            )
            return
        try:
            if policy_id is None:
                future = server.submit(features, deadline_ms=remaining_ms)
            else:
                future = server.submit(
                    features, deadline_ms=remaining_ms, policy_id=policy_id
                )
        except Exception as err:  # typed submit failures (queue full,
            # closed, PolicyUnknown/PolicyEvicted residency refusals)
            self._post_reply(
                req_id, attempt, ("error", type(err).__name__, str(err))
            )
            return

        def on_done(f, req_id=req_id, attempt=attempt):
            err = f.error()
            if err is not None:
                self._post_reply(
                    req_id, attempt, ("error", type(err).__name__, str(err))
                )
                return
            response = f.result(0)
            outputs = {
                k: np.asarray(v) for k, v in response.outputs.items()
            }
            self._post_reply(
                req_id, attempt,
                ("ok", outputs, response.model_version,
                 dict(response.spans)),
            )

        future.add_done_callback(on_done)

    def tick(self, now_wall: float) -> None:
        """Resolve a pending async hot-swap (success on version flip,
        failure on deadline). Called between messages and on idle."""
        if self._pending_swap is None:
            return
        swap_id, old_version, deadline, swap_policy = self._pending_swap
        version = self._version_of(swap_policy)
        if version != old_version:
            self._pending_swap = None
            self._post(("swapped", self._index, swap_id, True, version))
        elif now_wall > deadline:
            self._pending_swap = None
            self._post(("swapped", self._index, swap_id, False, version))

    def _on_swap(self, message: tuple) -> None:
        chaos.maybe_fire("swap")
        server = self._server
        swap_policy = message[3] if len(message) > 3 else None
        is_multi = getattr(server, "multi_policy", False)
        if swap_policy is not None and not is_multi:
            self._post(
                ("swapped", self._index, message[1], False,
                 _server_version(server))
            )
            return
        if (
            swap_policy is not None
            and is_multi
            and not server.is_resident(swap_policy)
        ):
            # Nothing resident to swap: trivially done — the next cold
            # load materializes whatever the store now publishes for
            # this policy.
            self._post(
                ("swapped", self._index, message[1], True,
                 self._version_of(swap_policy))
            )
            return
        old_version = self._version_of(swap_policy)
        if self._pending_swap is not None:
            # A second swap while one is in flight (two concurrent
            # rolling_swap calls) must not overwrite pending_swap: the
            # first swap_id would then never be answered and its
            # router-side waiter would burn the full timeout. Fail the
            # NEW one fast instead; the in-flight swap keeps its reply.
            self._post(
                ("swapped", self._index, message[1], False, old_version)
            )
        else:
            try:
                if swap_policy is None:
                    server.hot_swap(wait=False)
                else:
                    server.hot_swap(wait=False, policy_id=swap_policy)
                self._pending_swap = (
                    message[1], old_version, message[2], swap_policy
                )
            except Exception:
                _log.exception(
                    "replica %d: hot_swap failed", self._index
                )
                self._post(
                    ("swapped", self._index, message[1], False, old_version)
                )

    def handle(self, message: tuple) -> bool:
        """Dispatch one router message. Returns False on ("stop",) —
        the caller must then exit its loop and close()."""
        kind = message[0]
        if kind == "req":
            self._on_request(
                message[1], message[2], message[3], message[4],
                message[5] if len(message) > 5 else None,
            )
        elif kind == "health":
            chaos.maybe_fire("health")
            try:
                snap = self._server.snapshot()
            except Exception as err:  # a server that cannot even
                # snapshot is unhealthy; say so rather than vanish.
                snap = {"error": f"{type(err).__name__}: {err}"}
            if isinstance(snap, dict):
                snap.setdefault("host", self._host_identity())
            self._post(
                ("health", self._index, message[1], snap, time.time())
            )
        elif kind == "swap":
            self._on_swap(message)
            self.tick(time.time())
        elif kind == "hello":
            # Socket-fabric connect handshake: the router (or a fresh
            # router incarnation re-resolving us) asks who we are; the
            # local fabric never sends it, mp queues carry identity by
            # construction.
            self._post(self.started_message())
        elif kind == "stop":
            return False
        else:
            _log.warning(
                "replica %d: unknown message %r", self._index, kind
            )
        self.tick(time.time())
        return True

    def close(self) -> None:
        try:
            self._server.stop()
        except Exception:
            _log.exception("replica %d: server stop failed", self._index)
        self._cache.close()
        best_effort(self._post, ("stopped", self._index))


def build_server(index: int, spec: ReplicaSpec):
    """Apply the spec's env + chaos scope, then run its factory. Shared
    by both fabric entries so a socket replica boots exactly like a
    local one (same env routing, same scope defaulting, same typed
    factory-failure signal: the raised exception -> nonzero exit)."""
    _apply_env(spec.env)
    chaos.set_scope(spec.scope if spec.scope is not None else f"r{index}")
    try:
        return spec.factory(*spec.factory_args, **spec.factory_kwargs)
    except Exception:
        _log.exception("replica %d: server factory failed", index)
        # Exiting nonzero IS the failure signal; the router's monitor
        # handles a replica that dies before serving.
        raise


def replica_main(index: int, spec: ReplicaSpec, request_q, response_q,
                 free_q) -> None:
    """Process entry (local fabric). Never raises: a replica that cannot
    build its server exits nonzero — the router sees the exit and
    applies its death handling; a replica that cannot *reach* the
    router any more (queue torn down) just exits."""
    server = build_server(index, spec)
    core = ReplicaCore(index, server, response_q.put, free_q)
    chaos.maybe_fire("boot")
    response_q.put(core.started_message())
    try:
        while True:
            try:
                message = request_q.get(timeout=0.05)
            except queue.Empty:
                core.tick(time.time())
                continue
            except (OSError, ValueError):
                return  # request queue torn down: router is gone
            if not core.handle(message):
                return
    finally:
        core.close()


# -- backends ------------------------------------------------------------------


def policy_server_factory(
    export_root: str,
    batch_buckets=None,
    max_wait_ms: Optional[int] = None,
    predict_timeout_ms: Optional[int] = None,
    restore_timeout_s: int = 120,
):
    """The production backend: a PolicyServer over the newest export
    under `export_root`, predictor wrapped for chaos `predict`-site
    injection, every bucket prewarmed before the replica reports
    started. Heavy imports happen here, in the child, on purpose."""
    from tensor2robot_tpu.predictors.exported_savedmodel_predictor import (
        ExportedSavedModelPredictor,
    )
    from tensor2robot_tpu.serving.server import PolicyServer

    # Persistent compilation cache (T2R_COMPILE_CACHE_DIR): engaged by
    # the predictor's restore path per incoming version, BEFORE that
    # version's first compile (enable_compile_cache_for) — and skipped
    # there when the artifact's AOT executables cover every warmup
    # bucket, in which case this boot never compiles at all.
    chaos.maybe_fire("restore")
    predictor = ExportedSavedModelPredictor(
        export_dir=export_root, timeout=restore_timeout_s
    )
    if not predictor.restore():
        raise RuntimeError(
            f"replica predictor restore timed out under {export_root}"
        )
    server = PolicyServer(
        chaos.ChaosPredictor(predictor),
        batch_buckets=batch_buckets,
        max_wait_ms=max_wait_ms,
        predict_timeout_ms=predict_timeout_ms,
    )
    server.start(prewarm=True)
    return server


class _LocalFuture:
    """Minimal ServeFuture-alike for the mock backend (no jax import)."""

    def __init__(self):
        import threading
        from tensor2robot_tpu.testing import locksmith

        self._event = threading.Event()
        self._response = None
        self._error: Optional[BaseException] = None
        self._callbacks = []
        self._lock = locksmith.make_lock("_LocalFuture._lock")

    def done(self) -> bool:
        return self._event.is_set()

    def error(self) -> Optional[BaseException]:
        return self._error if self._event.is_set() else None

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("mock request still pending")
        if self._error is not None:
            raise self._error
        return self._response

    def add_done_callback(self, fn) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _complete(self, response, error) -> None:
        self._response, self._error = response, error
        with self._lock:
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)


class _MockResponse:
    __slots__ = ("outputs", "model_version", "spans")

    def __init__(self, outputs, model_version, spans):
        self.outputs = outputs
        self.model_version = model_version
        self.spans = spans


class _MockServer:
    """Deterministic server-surface stand-in: serial compute thread,
    fixed per-request service time, chaos `predict`/`restore` hooks.
    Outputs echo a checksum of the inputs so end-to-end tests can verify
    the reply really came from the submitted features."""

    def __init__(
        self,
        service_ms: float = 1.0,
        version: int = 1,
        scale: float = 1.0,
        bias: float = 0.0,
        mem_bytes: int = 0,
        fingerprint: Optional[str] = None,
    ):
        import threading
        from tensor2robot_tpu.testing import locksmith

        self._service_s = service_ms / 1e3
        self.model_version = version
        # Per-policy affine fingerprint: y = scale * sum(features) + bias
        # computed in float64 then cast once — bitwise-reproducible, so a
        # multi-policy fleet's responses can be audited against a
        # single-policy twin serving the same (scale, bias).
        self._scale = float(scale)
        self._bias = float(bias)
        self.mem_bytes = int(mem_bytes)
        # Optional artifact identity (PolicyServer snapshot parity):
        # pools of identical mocks can DECLARE interchangeability, so
        # gateway cross-pool failover has a fingerprint to match on.
        self._fingerprint = fingerprint
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False
        self._completed = 0
        self._lock = locksmith.make_lock("_MockServer._lock")
        self._worker = threading.Thread(
            target=self._compute_loop, name="t2r-mock-compute", daemon=True
        )
        self._worker.start()

    def _compute_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            future, features, deadline = item
            try:
                chaos.maybe_fire("predict")
                if self._service_s > 0:
                    time.sleep(self._service_s)
                if time.monotonic() > deadline:
                    raise TimeoutError("mock deadline passed in compute")
                total = 0.0
                for key in sorted(features):
                    total += float(np.sum(features[key].astype(np.float64)))
                outputs = {
                    "y": np.float32(total * self._scale + self._bias),
                    "nbytes": np.int64(
                        sum(v.nbytes for v in features.values())
                    ),
                }
                with self._lock:
                    self._completed += 1
                future._complete(
                    _MockResponse(
                        outputs, self.model_version, {"compute_ms": 0.0}
                    ),
                    None,
                )
            except BaseException as err:  # noqa: BLE001 — the future is the
                # error channel; the compute loop must survive any fault.
                future._complete(None, err)

    def submit(self, features, deadline_ms: float = 1000.0) -> _LocalFuture:
        if self._closed:
            raise RuntimeError("mock server is stopped")
        future = _LocalFuture()
        self._queue.put(
            (future, features, time.monotonic() + deadline_ms / 1e3)
        )
        return future

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            completed = self._completed
        snap = {
            "counters": {"completed": completed},
            "queue_depth": self._queue.qsize(),
            "model_version": self.model_version,
            # Health-snapshot parity with PolicyServer: the fleet's
            # boot-attribution surface (router/autoscaler snapshots)
            # reads prewarm_source off every backend; the mock has one
            # degenerate bucket and nothing to compile.
            "prewarm_source": {"1": "mock"},
        }
        if self._fingerprint is not None:
            snap["model_fingerprint"] = str(self._fingerprint)
        return snap

    def hot_swap(self, wait: bool = False) -> bool:
        """Version bump on a background thread after the chaos `restore`
        site — mirrors the async-restore shape so slow-restore plans
        exercise the router's swap timeout without stalling serving."""
        import threading

        def flip():
            chaos.maybe_fire("restore")
            self.model_version += 1

        if wait:
            flip()
            return True
        threading.Thread(target=flip, daemon=True).start()
        return True

    def stop(self) -> None:
        self._closed = True
        self._queue.put(None)
        self._worker.join(timeout=5)


def mock_server_factory(service_ms: float = 1.0, version: int = 1,
                        fingerprint: Optional[str] = None):
    """Jax-free replica backend for router tests and plumbing smokes.
    `fingerprint` optionally declares an artifact identity (surfaced as
    `model_fingerprint` in health snapshots), which is what gateway
    cross-pool failover matches on before moving a request."""
    return _MockServer(
        service_ms=service_ms, version=version, fingerprint=fingerprint
    )


def multi_policy_mock_factory(
    catalog: Mapping[str, Mapping[str, Any]],
    service_ms: float = 1.0,
    load_ms: float = 0.0,
    default_policy: Optional[str] = None,
    preload=(),
    mem_budget_mb: Optional[int] = None,
    max_resident: Optional[int] = None,
    cold_load: Optional[bool] = None,
):
    """Jax-free MULTI-policy backend: one `_MockServer` per resident
    policy, each with its own (scale, bias, version, mem_bytes) from the
    catalog — so every policy's replies are distinguishable and
    bitwise-auditable against a single-policy twin. `load_ms` models the
    cold-load (materialize + prewarm) cost."""
    from tensor2robot_tpu.serving.policies import MultiPolicyServer

    catalog = {str(k): dict(v) for k, v in catalog.items()}

    def loader(policy_id: str):
        chaos.maybe_fire("load")
        entry = catalog[policy_id]
        if load_ms > 0:
            time.sleep(load_ms / 1e3)
        return _MockServer(
            service_ms=service_ms,
            version=int(entry.get("version", 1)),
            scale=float(entry.get("scale", 1.0)),
            bias=float(entry.get("bias", 0.0)),
            mem_bytes=int(entry.get("mem_bytes", 0)),
        )

    return MultiPolicyServer(
        loader,
        list(catalog),
        default_policy=default_policy,
        mem_budget_mb=mem_budget_mb,
        max_resident=max_resident,
        cold_load=cold_load,
        preload=preload,
    )


def multi_policy_store_factory(
    store_root: str,
    policy_ids=None,
    work_dir: Optional[str] = None,
    batch_buckets=None,
    max_wait_ms: Optional[int] = None,
    predict_timeout_ms: Optional[int] = None,
    restore_timeout_s: int = 120,
    default_policy: Optional[str] = None,
    preload=(),
    mem_budget_mb: Optional[int] = None,
    max_resident: Optional[int] = None,
    cold_load: Optional[bool] = None,
):
    """The production multi-policy backend: every policy materializes
    from the content-addressed store (export/artifact_store.py — base
    payload shared, deltas decoded on load) into a PolicyServer
    prewarmed off the SHARED bucket ladder. Heavy imports happen here,
    in the child, on purpose."""
    from tensor2robot_tpu.serving.policies import MultiPolicyServer
    from tensor2robot_tpu.serving.server import exported_policy_loader

    loader, catalog = exported_policy_loader(
        store_root,
        policy_ids=policy_ids,
        work_dir=work_dir,
        batch_buckets=batch_buckets,
        max_wait_ms=max_wait_ms,
        predict_timeout_ms=predict_timeout_ms,
        restore_timeout_s=restore_timeout_s,
    )
    return MultiPolicyServer(
        loader,
        catalog,
        default_policy=default_policy,
        mem_budget_mb=mem_budget_mb,
        max_resident=max_resident,
        cold_load=cold_load,
        preload=preload,
    )
