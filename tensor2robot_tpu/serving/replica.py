"""Replica process entry: one policy-server worker in the router's pool.

`replica_main` is the spawn target. It stays deliberately light at
import time — the heavy stack (specs -> jax -> XLA) loads only inside
`policy_server_factory`, so a mock-backend replica (tests, bench
plumbing smoke) boots in fractions of a second while a real one pays
the jax import exactly once.

A replica owns: its request queue (router -> replica), the shared
response queue (replica -> router), and the shared free-slot queue of
the request shm ring (names go back as soon as a payload is copied
out). The protocol is at the bottom of this docstring; the router is
the only peer.

Chaos scope: each replica declares `r<index>` (testing/chaos.py), so a
plan can target one replica of a fleet ("r0/predict:3:kill") while its
siblings stay healthy — which is exactly the partial-failure regime the
router's retry/hedge/eviction logic exists for.

Wire protocol (all tuples, pickled by multiprocessing):

  router -> replica (request queue):
    ("req", req_id, attempt, deadline_wall_s, payload[, policy_id])
                                                         payload: transport.py
    ("health", probe_id)
    ("swap", swap_id, deadline_wall_s[, policy_id])
    ("stop",)

The optional trailing policy_id targets one policy of a MULTI-POLICY
backend (serving/policies.py, `multi_policy = True`); absent or None
means the backend's default. A single-policy backend receiving a
policy-addressed request replies with a typed PolicyUnknown error —
never silently serving the wrong weights.

  replica -> router (shared response queue):
    ("started", index, version, pid)
    ("rsp", index, req_id, attempt, crc, blob)     blob: ("ok", outputs,
                                                   version, spans) |
                                                   ("error", class, message)
    ("health", index, probe_id, snapshot, t_wall)
    ("swapped", index, swap_id, ok, version)
    ("stopped", index)
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import time
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import numpy as np

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.serving import transport
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "ReplicaSpec",
    "replica_main",
    "policy_server_factory",
    "multi_policy_store_factory",
    "mock_server_factory",
    "multi_policy_mock_factory",
]


@dataclasses.dataclass
class ReplicaSpec:
    """How a replica process builds its server.

    `factory` must be a module-level (picklable-by-name) callable
    returning a started server-like object: `submit(features,
    deadline_ms) -> future` (future: `add_done_callback`, `error()`,
    `result()`), `snapshot()`, `hot_swap(wait)`, `stop()`. `env` entries
    are applied in the child before the factory runs — `T2R_*` keys go
    through the flags registry (validated), everything else through the
    raw environment; this is the route chaos plans take into a replica.
    """

    factory: Callable
    factory_args: Tuple = ()
    factory_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    scope: Optional[str] = None  # chaos scope override (default r<index>)


def _apply_env(env: Mapping[str, str]) -> None:
    for key, value in env.items():
        if key.startswith("T2R_"):
            t2r_flags.write_env(key, value)
        else:
            os.environ[key] = value


def _server_version(server) -> int:
    version = getattr(server, "model_version", None)
    if version is not None:
        return int(version)
    try:
        return int(server.snapshot().get("model_version", -1))
    except Exception:
        return -1


def replica_main(index: int, spec: ReplicaSpec, request_q, response_q,
                 free_q) -> None:
    """Process entry. Never raises: a replica that cannot build its
    server posts ("started", index, -1, pid) with a follow-up error
    reply path dead, then exits — the router sees the exit and applies
    its death handling; a replica that cannot *reach* the router any
    more (queue torn down) just exits."""
    _apply_env(spec.env)
    chaos.set_scope(spec.scope if spec.scope is not None else f"r{index}")
    pid = os.getpid()
    try:
        server = spec.factory(*spec.factory_args, **spec.factory_kwargs)
    except Exception:
        _log.exception("replica %d: server factory failed", index)
        # Exiting nonzero IS the failure signal; the router's monitor
        # handles a replica that dies before serving.
        raise
    cache = transport.ReplicaSlotCache()
    chaos.maybe_fire("boot")
    response_q.put(("started", index, _server_version(server), pid))

    # id, old_version, deadline, policy_id (None = whole-backend swap)
    pending_swap: Optional[Tuple[int, int, float, Optional[str]]] = None

    def _version_of(policy_id: Optional[str]) -> int:
        if policy_id is not None and getattr(server, "multi_policy", False):
            try:
                return int(server.policy_version(policy_id))
            except Exception:
                return -1
        return _server_version(server)

    def post_reply(req_id: int, attempt: int, body) -> None:
        crc, blob = transport.pack(body)
        fault = chaos.maybe_fire("reply")
        if fault is not None and fault.action == "corrupt" and blob:
            # Flip one byte AFTER the checksum: the router must detect
            # the mismatch and treat this replica reply as a failure.
            blob = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        # Router gone -> best effort; our process is about to be reaped.
        best_effort(response_q.put, ("rsp", index, req_id, attempt, crc, blob))

    def on_request(req_id: int, attempt: int, deadline_wall: float, payload,
                   policy_id: Optional[str] = None):
        chaos.maybe_fire("recv")
        try:
            features = transport.decode_request(payload, free_q, cache)
        except transport.IntegrityError as err:
            post_reply(req_id, attempt, ("error", "RequestCorrupt", str(err)))
            return
        remaining_ms = (deadline_wall - time.time()) * 1e3
        if remaining_ms <= 0:
            post_reply(
                req_id, attempt,
                ("error", "DeadlineExceeded",
                 "deadline passed before the replica dequeued the request"),
            )
            return
        if policy_id is not None and not getattr(server, "multi_policy", False):
            post_reply(
                req_id, attempt,
                ("error", "PolicyUnknown",
                 f"request names policy {policy_id!r} but this replica "
                 "runs a single-policy backend"),
            )
            return
        try:
            if policy_id is None:
                future = server.submit(features, deadline_ms=remaining_ms)
            else:
                future = server.submit(
                    features, deadline_ms=remaining_ms, policy_id=policy_id
                )
        except Exception as err:  # typed submit failures (queue full,
            # closed, PolicyUnknown/PolicyEvicted residency refusals)
            post_reply(req_id, attempt, ("error", type(err).__name__, str(err)))
            return

        def on_done(f, req_id=req_id, attempt=attempt):
            err = f.error()
            if err is not None:
                post_reply(
                    req_id, attempt, ("error", type(err).__name__, str(err))
                )
                return
            response = f.result(0)
            outputs = {
                k: np.asarray(v) for k, v in response.outputs.items()
            }
            post_reply(
                req_id, attempt,
                ("ok", outputs, response.model_version,
                 dict(response.spans)),
            )

        future.add_done_callback(on_done)

    def check_pending_swap(now_wall: float) -> None:
        nonlocal pending_swap
        if pending_swap is None:
            return
        swap_id, old_version, deadline, swap_policy = pending_swap
        version = _version_of(swap_policy)
        if version != old_version:
            pending_swap = None
            response_q.put(("swapped", index, swap_id, True, version))
        elif now_wall > deadline:
            pending_swap = None
            response_q.put(("swapped", index, swap_id, False, version))

    try:
        while True:
            try:
                message = request_q.get(timeout=0.05)
            except queue.Empty:
                check_pending_swap(time.time())
                continue
            except (OSError, ValueError):
                return  # request queue torn down: router is gone
            kind = message[0]
            if kind == "req":
                on_request(
                    message[1], message[2], message[3], message[4],
                    message[5] if len(message) > 5 else None,
                )
            elif kind == "health":
                chaos.maybe_fire("health")
                try:
                    snap = server.snapshot()
                except Exception as err:  # a server that cannot even
                    # snapshot is unhealthy; say so rather than vanish.
                    snap = {"error": f"{type(err).__name__}: {err}"}
                response_q.put(("health", index, message[1], snap, time.time()))
            elif kind == "swap":
                chaos.maybe_fire("swap")
                swap_policy = message[3] if len(message) > 3 else None
                is_multi = getattr(server, "multi_policy", False)
                if swap_policy is not None and not is_multi:
                    response_q.put(
                        ("swapped", index, message[1], False,
                         _server_version(server))
                    )
                    check_pending_swap(time.time())
                    continue
                if (
                    swap_policy is not None
                    and is_multi
                    and not server.is_resident(swap_policy)
                ):
                    # Nothing resident to swap: trivially done — the
                    # next cold load materializes whatever the store
                    # now publishes for this policy.
                    response_q.put(
                        ("swapped", index, message[1], True,
                         _version_of(swap_policy))
                    )
                    check_pending_swap(time.time())
                    continue
                old_version = _version_of(swap_policy)
                if pending_swap is not None:
                    # A second swap while one is in flight (two concurrent
                    # rolling_swap calls) must not overwrite pending_swap:
                    # the first swap_id would then never be answered and
                    # its router-side waiter would burn the full timeout.
                    # Fail the NEW one fast instead; the in-flight swap
                    # keeps its reply.
                    response_q.put(
                        ("swapped", index, message[1], False, old_version)
                    )
                else:
                    try:
                        if swap_policy is None:
                            server.hot_swap(wait=False)
                        else:
                            server.hot_swap(
                                wait=False, policy_id=swap_policy
                            )
                        pending_swap = (
                            message[1], old_version, message[2], swap_policy
                        )
                    except Exception:
                        _log.exception("replica %d: hot_swap failed", index)
                        response_q.put(
                            ("swapped", index, message[1], False, old_version)
                        )
                check_pending_swap(time.time())
            elif kind == "stop":
                return
            else:
                _log.warning("replica %d: unknown message %r", index, kind)
            check_pending_swap(time.time())
    finally:
        try:
            server.stop()
        except Exception:
            _log.exception("replica %d: server stop failed", index)
        cache.close()
        best_effort(response_q.put, ("stopped", index))


# -- backends ------------------------------------------------------------------


def policy_server_factory(
    export_root: str,
    batch_buckets=None,
    max_wait_ms: Optional[int] = None,
    predict_timeout_ms: Optional[int] = None,
    restore_timeout_s: int = 120,
):
    """The production backend: a PolicyServer over the newest export
    under `export_root`, predictor wrapped for chaos `predict`-site
    injection, every bucket prewarmed before the replica reports
    started. Heavy imports happen here, in the child, on purpose."""
    from tensor2robot_tpu.predictors.exported_savedmodel_predictor import (
        ExportedSavedModelPredictor,
    )
    from tensor2robot_tpu.serving.server import PolicyServer

    # Persistent compilation cache (T2R_COMPILE_CACHE_DIR): engaged by
    # the predictor's restore path per incoming version, BEFORE that
    # version's first compile (enable_compile_cache_for) — and skipped
    # there when the artifact's AOT executables cover every warmup
    # bucket, in which case this boot never compiles at all.
    chaos.maybe_fire("restore")
    predictor = ExportedSavedModelPredictor(
        export_dir=export_root, timeout=restore_timeout_s
    )
    if not predictor.restore():
        raise RuntimeError(
            f"replica predictor restore timed out under {export_root}"
        )
    server = PolicyServer(
        chaos.ChaosPredictor(predictor),
        batch_buckets=batch_buckets,
        max_wait_ms=max_wait_ms,
        predict_timeout_ms=predict_timeout_ms,
    )
    server.start(prewarm=True)
    return server


class _LocalFuture:
    """Minimal ServeFuture-alike for the mock backend (no jax import)."""

    def __init__(self):
        import threading
        from tensor2robot_tpu.testing import locksmith

        self._event = threading.Event()
        self._response = None
        self._error: Optional[BaseException] = None
        self._callbacks = []
        self._lock = locksmith.make_lock("_LocalFuture._lock")

    def done(self) -> bool:
        return self._event.is_set()

    def error(self) -> Optional[BaseException]:
        return self._error if self._event.is_set() else None

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("mock request still pending")
        if self._error is not None:
            raise self._error
        return self._response

    def add_done_callback(self, fn) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _complete(self, response, error) -> None:
        self._response, self._error = response, error
        with self._lock:
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)


class _MockResponse:
    __slots__ = ("outputs", "model_version", "spans")

    def __init__(self, outputs, model_version, spans):
        self.outputs = outputs
        self.model_version = model_version
        self.spans = spans


class _MockServer:
    """Deterministic server-surface stand-in: serial compute thread,
    fixed per-request service time, chaos `predict`/`restore` hooks.
    Outputs echo a checksum of the inputs so end-to-end tests can verify
    the reply really came from the submitted features."""

    def __init__(
        self,
        service_ms: float = 1.0,
        version: int = 1,
        scale: float = 1.0,
        bias: float = 0.0,
        mem_bytes: int = 0,
    ):
        import threading
        from tensor2robot_tpu.testing import locksmith

        self._service_s = service_ms / 1e3
        self.model_version = version
        # Per-policy affine fingerprint: y = scale * sum(features) + bias
        # computed in float64 then cast once — bitwise-reproducible, so a
        # multi-policy fleet's responses can be audited against a
        # single-policy twin serving the same (scale, bias).
        self._scale = float(scale)
        self._bias = float(bias)
        self.mem_bytes = int(mem_bytes)
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False
        self._completed = 0
        self._lock = locksmith.make_lock("_MockServer._lock")
        self._worker = threading.Thread(
            target=self._compute_loop, name="t2r-mock-compute", daemon=True
        )
        self._worker.start()

    def _compute_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            future, features, deadline = item
            try:
                chaos.maybe_fire("predict")
                if self._service_s > 0:
                    time.sleep(self._service_s)
                if time.monotonic() > deadline:
                    raise TimeoutError("mock deadline passed in compute")
                total = 0.0
                for key in sorted(features):
                    total += float(np.sum(features[key].astype(np.float64)))
                outputs = {
                    "y": np.float32(total * self._scale + self._bias),
                    "nbytes": np.int64(
                        sum(v.nbytes for v in features.values())
                    ),
                }
                with self._lock:
                    self._completed += 1
                future._complete(
                    _MockResponse(
                        outputs, self.model_version, {"compute_ms": 0.0}
                    ),
                    None,
                )
            except BaseException as err:  # noqa: BLE001 — the future is the
                # error channel; the compute loop must survive any fault.
                future._complete(None, err)

    def submit(self, features, deadline_ms: float = 1000.0) -> _LocalFuture:
        if self._closed:
            raise RuntimeError("mock server is stopped")
        future = _LocalFuture()
        self._queue.put(
            (future, features, time.monotonic() + deadline_ms / 1e3)
        )
        return future

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            completed = self._completed
        return {
            "counters": {"completed": completed},
            "queue_depth": self._queue.qsize(),
            "model_version": self.model_version,
            # Health-snapshot parity with PolicyServer: the fleet's
            # boot-attribution surface (router/autoscaler snapshots)
            # reads prewarm_source off every backend; the mock has one
            # degenerate bucket and nothing to compile.
            "prewarm_source": {"1": "mock"},
        }

    def hot_swap(self, wait: bool = False) -> bool:
        """Version bump on a background thread after the chaos `restore`
        site — mirrors the async-restore shape so slow-restore plans
        exercise the router's swap timeout without stalling serving."""
        import threading

        def flip():
            chaos.maybe_fire("restore")
            self.model_version += 1

        if wait:
            flip()
            return True
        threading.Thread(target=flip, daemon=True).start()
        return True

    def stop(self) -> None:
        self._closed = True
        self._queue.put(None)
        self._worker.join(timeout=5)


def mock_server_factory(service_ms: float = 1.0, version: int = 1):
    """Jax-free replica backend for router tests and plumbing smokes."""
    return _MockServer(service_ms=service_ms, version=version)


def multi_policy_mock_factory(
    catalog: Mapping[str, Mapping[str, Any]],
    service_ms: float = 1.0,
    load_ms: float = 0.0,
    default_policy: Optional[str] = None,
    preload=(),
    mem_budget_mb: Optional[int] = None,
    max_resident: Optional[int] = None,
    cold_load: Optional[bool] = None,
):
    """Jax-free MULTI-policy backend: one `_MockServer` per resident
    policy, each with its own (scale, bias, version, mem_bytes) from the
    catalog — so every policy's replies are distinguishable and
    bitwise-auditable against a single-policy twin. `load_ms` models the
    cold-load (materialize + prewarm) cost."""
    from tensor2robot_tpu.serving.policies import MultiPolicyServer

    catalog = {str(k): dict(v) for k, v in catalog.items()}

    def loader(policy_id: str):
        chaos.maybe_fire("load")
        entry = catalog[policy_id]
        if load_ms > 0:
            time.sleep(load_ms / 1e3)
        return _MockServer(
            service_ms=service_ms,
            version=int(entry.get("version", 1)),
            scale=float(entry.get("scale", 1.0)),
            bias=float(entry.get("bias", 0.0)),
            mem_bytes=int(entry.get("mem_bytes", 0)),
        )

    return MultiPolicyServer(
        loader,
        list(catalog),
        default_policy=default_policy,
        mem_budget_mb=mem_budget_mb,
        max_resident=max_resident,
        cold_load=cold_load,
        preload=preload,
    )


def multi_policy_store_factory(
    store_root: str,
    policy_ids=None,
    work_dir: Optional[str] = None,
    batch_buckets=None,
    max_wait_ms: Optional[int] = None,
    predict_timeout_ms: Optional[int] = None,
    restore_timeout_s: int = 120,
    default_policy: Optional[str] = None,
    preload=(),
    mem_budget_mb: Optional[int] = None,
    max_resident: Optional[int] = None,
    cold_load: Optional[bool] = None,
):
    """The production multi-policy backend: every policy materializes
    from the content-addressed store (export/artifact_store.py — base
    payload shared, deltas decoded on load) into a PolicyServer
    prewarmed off the SHARED bucket ladder. Heavy imports happen here,
    in the child, on purpose."""
    from tensor2robot_tpu.serving.policies import MultiPolicyServer
    from tensor2robot_tpu.serving.server import exported_policy_loader

    loader, catalog = exported_policy_loader(
        store_root,
        policy_ids=policy_ids,
        work_dir=work_dir,
        batch_buckets=batch_buckets,
        max_wait_ms=max_wait_ms,
        predict_timeout_ms=predict_timeout_ms,
        restore_timeout_s=restore_timeout_s,
    )
    return MultiPolicyServer(
        loader,
        catalog,
        default_policy=default_policy,
        mem_budget_mb=mem_budget_mb,
        max_resident=max_resident,
        cold_load=cold_load,
        preload=preload,
    )
