"""FleetRouter: deadline-aware routing over a pool of replica processes.

PR 4's PolicyServer made one process serve many clients; this layer
makes many *processes* serve them — the horizontal step the "millions
of users" north star actually needs, built so that every failure mode a
fleet exhibits is a first-class, tested behavior rather than an outage:

  * **Least-loaded, deadline-aware dispatch.** Each request goes to the
    healthy replica with the fewest in-flight requests (ties broken
    round-robin from a seeded RNG); a request whose deadline has already
    passed is failed typed, never shipped. The wall-clock deadline rides
    to the replica, whose own PolicyServer enforces it pre-dispatch.
  * **Retry with jittered exponential backoff.** A replica failure
    (death, corrupt reply, typed serve error) re-dispatches the request
    to a different replica after `backoff * 2^attempt * (1 + U[0,1))`
    ms, up to `T2R_FLEET_RETRIES` extra attempts, always bounded by the
    request deadline.
  * **Hedging.** A request still pending `T2R_FLEET_HEDGE_MS` after
    dispatch is duplicated to a second replica; first reply wins, the
    loser is discarded on arrival. This is the classic tail-latency
    amputation for straggler replicas (stuck GC, throttled core).
  * **Health probing + eviction + circuit breaking + respawn.** The
    monitor polls each replica's `snapshot()`; a silent replica is
    SUSPECT (unrouted) and eventually hard-killed and respawned; a
    replica failing `circuit_threshold` consecutive requests is BROKEN
    (circuit open) for a cooloff, then readmitted on its next health
    reply. A dead process's in-flight requests fail over immediately.
  * **Graceful degradation — shed, never hang.** With every healthy
    replica at its in-flight cap the router fails new requests with
    `FleetSaturated` immediately; with no live replica,
    `ReplicaUnavailable`. Every submitted request also carries a
    router-side deadline timer, so even a wedged replica + a missed
    monitor tick cannot strand a future: *every* future resolves.
  * **Rolling deploys.** `rolling_swap()` hot-swaps one replica at a
    time (each keeps serving its old version until the new one is
    prewarmed — PR 4's per-replica zero-downtime swap), so a fleet-wide
    deploy never reduces capacity by more than the replica mid-swap.

Transport is `serving/transport.py`: checksummed inline pickles with a
shared-memory slab ring (the `data/dataset.py` ring discipline) for
large request payloads. See docs/RESILIENCE.md for the policy table and
the chaos plans that pin each behavior.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading

from tensor2robot_tpu.testing import locksmith
import time
from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.net import codec as wire_codec
from tensor2robot_tpu.serving import transport
from tensor2robot_tpu.serving.metrics import percentile
from tensor2robot_tpu.serving.replica import ReplicaSpec, replica_main
from tensor2robot_tpu.utils.backoff import Backoff, poll_loop
from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "FleetRouter",
    "FleetResponse",
    "RouterFuture",
    "FleetError",
    "FleetSaturated",
    "ReplicaUnavailable",
    "RequestAbandoned",
    "RouterClosed",
]


class FleetError(RuntimeError):
    """Base class for router-level request failures.

    Deliberately NOT a ServeError subclass: importing server.py would
    drag jax into mock-backend parents, and the two layers' errors never
    mix in one except clause (the router converts replica-side serve
    errors into its own types)."""


class FleetSaturated(FleetError):
    """Every healthy replica is at its in-flight cap; request shed."""


class ReplicaUnavailable(FleetError):
    """No live replica to dispatch to (pool down or still starting)."""


class RequestAbandoned(FleetError):
    """The request ran out of deadline or retry budget. `reason` is
    'deadline' or 'retries'; `detail` carries the last failure."""

    def __init__(self, message: str, reason: str, detail: str = ""):
        super().__init__(message)
        self.reason = reason
        self.detail = detail


class RouterClosed(FleetError):
    """The router stopped before the request completed."""


# Replica lifecycle states. `draining` is the scale-down limbo: unrouted
# (only `up` replicas take traffic) but alive until its in-flight
# requests finish — the state the autoscaler parks a replica in so
# retiring capacity never kills a request.
_STARTING, _UP, _SUSPECT, _BROKEN, _DEAD, _DRAINING = (
    "starting", "up", "suspect", "broken", "dead", "draining",
)


class FleetResponse:
    """One request's outputs plus fleet-level provenance."""

    __slots__ = (
        "outputs", "model_version", "spans", "replica", "attempts", "hedged",
    )

    def __init__(self, outputs, model_version, spans, replica, attempts,
                 hedged):
        self.outputs = outputs
        self.model_version = model_version
        self.spans = spans
        self.replica = replica
        self.attempts = attempts
        self.hedged = hedged


class RouterFuture:
    """Completion handle for one fleet request; resolves exactly once,
    always (success, typed failure, or RouterClosed at stop)."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._event = threading.Event()
        self._response: Optional[FleetResponse] = None
        self._error: Optional[BaseException] = None
        self._callbacks: List = []
        self._cb_lock = locksmith.make_lock("RouterFuture._cb_lock")

    def done(self) -> bool:
        return self._event.is_set()

    def error(self) -> Optional[BaseException]:
        return self._error if self._event.is_set() else None

    def result(self, timeout: Optional[float] = None) -> FleetResponse:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fleet request {self.request_id} still pending after "
                f"{timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._response

    def add_done_callback(self, fn) -> None:
        """Runs `fn(self)` when the future resolves — on the resolving
        thread for pending futures, immediately for completed ones.
        Fires exactly once per registration (open-loop load generators
        and relays hang off this instead of blocking in result())."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _set(self, response, error) -> None:
        self._response, self._error = response, error
        with self._cb_lock:
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)


class _FleetRequest:
    __slots__ = (
        "id", "features", "deadline", "future", "t_submit", "dispatches",
        "hedged", "hedge_attempts", "live", "last_failure", "policy_id",
    )

    def __init__(self, request_id, features, deadline, policy_id=None):
        self.id = request_id
        self.features = features
        self.deadline = deadline  # monotonic, router-local
        self.future = RouterFuture(request_id)
        self.t_submit = time.monotonic()
        self.dispatches = 0  # non-hedge dispatch count
        self.hedged = False
        self.hedge_attempts: Set[int] = set()  # attempt numbers placed as hedges
        self.live: Set[Tuple[int, int]] = set()  # (attempt, replica)
        self.last_failure = ""
        self.policy_id: Optional[str] = policy_id


class _Replica:
    __slots__ = (
        "index", "spec", "proc", "request_q", "state", "inflight",
        "consecutive_failures", "broken_until", "version", "last_health",
        "last_health_time", "respawns", "started_at", "retired", "boot_ms",
    )

    def __init__(self, index: int, spec: ReplicaSpec):
        self.index = index
        self.spec = spec
        self.proc = None
        self.request_q = None
        self.state = _STARTING
        self.inflight: Set[Tuple[int, int]] = set()  # (req_id, attempt)
        self.consecutive_failures = 0
        self.broken_until = 0.0
        self.version = -1
        self.last_health: Dict = {}
        self.last_health_time = 0.0
        self.respawns = 0
        self.started_at = 0.0
        self.retired = False  # scale-down: exits are expected, no respawn
        # spawn -> "started" wall time of the LAST boot (None before the
        # first): with prewarm_source this attributes a slow scale-up to
        # its restore tier (deserialize vs compile).
        self.boot_ms: Optional[float] = None


class _RouterMetrics:
    """Counters + bounded latency window; all O(1) mutators."""

    def __init__(self, span_window: int = 4096):
        self._lock = locksmith.make_lock("_RouterMetrics._lock")
        self._counters: Dict[str, int] = {}
        self._latencies: deque = deque(maxlen=span_window)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe_latency(self, ms: float) -> None:
        with self._lock:
            self._latencies.append(ms)

    def snapshot(self) -> Dict:
        with self._lock:
            counters = dict(self._counters)
            latencies = sorted(self._latencies)
        return {
            "counters": counters,
            "latency_ms": {
                "p50": round(percentile(latencies, 0.50), 3),
                "p99": round(percentile(latencies, 0.99), 3),
                "p999": round(percentile(latencies, 0.999), 3),
                "window": len(latencies),
            },
        }


class FleetRouter:
    """Routes requests over `num_replicas` spawned replica processes.

    Args mirror the `T2R_FLEET_*` flags (constructor overrides flag
    overrides default, the PolicyServer convention). `replica_spec` may
    be one ReplicaSpec (replicated) or a sequence of per-replica specs
    (how chaos plans target a single replica). `seed` drives backoff
    jitter and dispatch tie-breaks — router behavior under a fixed fault
    plan is reproducible.
    """

    def __init__(
        self,
        replica_spec,
        num_replicas: Optional[int] = None,
        *,
        max_inflight: Optional[int] = None,
        hedge_ms: Optional[int] = None,
        retries: Optional[int] = None,
        backoff_ms: float = 25.0,
        default_deadline_ms: Optional[int] = None,
        probe_interval_ms: float = 200.0,
        probe_miss_limit: int = 3,
        circuit_threshold: int = 3,
        circuit_cooloff_ms: float = 1000.0,
        respawn: bool = True,
        max_respawns: int = 3,
        boot_timeout_s: float = 120.0,
        inline_max_bytes: int = transport.DEFAULT_INLINE_MAX_BYTES,
        shm_slots: int = 8,
        seed: int = 0,
        transport_mode: Optional[str] = None,
        fabric_root: Optional[str] = None,
        zone: Optional[str] = None,
    ):
        if isinstance(replica_spec, ReplicaSpec):
            if num_replicas is None:
                raise ValueError(
                    "num_replicas is required with a single ReplicaSpec"
                )
            specs = [replica_spec] * num_replicas
        else:
            specs = list(replica_spec)
            if num_replicas is not None and num_replicas != len(specs):
                raise ValueError(
                    f"num_replicas={num_replicas} but {len(specs)} specs given"
                )
        if not specs:
            raise ValueError("a fleet needs at least one replica")
        self._specs = specs
        self._max_inflight = (
            max_inflight if max_inflight is not None
            else t2r_flags.get_int("T2R_FLEET_MAX_INFLIGHT")
        )
        self._hedge_s = (
            hedge_ms if hedge_ms is not None
            else t2r_flags.get_int("T2R_FLEET_HEDGE_MS")
        ) / 1e3
        self._retries = (
            retries if retries is not None
            else t2r_flags.get_int("T2R_FLEET_RETRIES")
        )
        self._backoff_s = backoff_ms / 1e3
        # Retry pacing through the shared schedule (utils/backoff.py):
        # uncapped per-delay (the request deadline is the real bound),
        # seeded so a fixed fault plan replays the same pacing.
        self._retry_backoff = Backoff(
            base_ms=backoff_ms, cap_ms=None, seed=seed
        )
        self._default_deadline_s = (
            default_deadline_ms if default_deadline_ms is not None
            else t2r_flags.get_int("T2R_SERVE_DEADLINE_MS")
        ) / 1e3
        self._probe_interval_s = probe_interval_ms / 1e3
        self._probe_miss_limit = probe_miss_limit
        self._circuit_threshold = circuit_threshold
        self._circuit_cooloff_s = circuit_cooloff_ms / 1e3
        self._respawn = respawn
        self._max_respawns = max_respawns
        self._boot_timeout_s = boot_timeout_s
        self._inline_max = inline_max_bytes
        self._shm_slots = shm_slots
        # Which fabric carries replica traffic: "local" (mp queues +
        # shared-memory slots, one process group — byte-compatible
        # tier-1 default) or "socket" (independent process groups on the
        # shared CRC-framed wire, published-address discovery — the
        # cross-host fabric). Everything above _spawn/start is
        # transport-blind: handles and links duck-type the mp surface.
        self._transport_mode = (
            transport_mode if transport_mode is not None
            else t2r_flags.get_enum("T2R_FLEET_TRANSPORT")
        )
        if self._transport_mode not in ("local", "socket"):
            raise ValueError(
                f"unknown transport_mode {self._transport_mode!r} "
                "(expected 'local' or 'socket')"
            )
        self._fabric_root = fabric_root
        self._zone = zone
        self._pool = None  # RemoteReplicaPool, socket mode only

        self._lock = locksmith.make_rlock("FleetRouter._lock")
        self._metrics = _RouterMetrics()
        self._replicas: List[_Replica] = [
            _Replica(i, spec) for i, spec in enumerate(specs)
        ]
        self._requests: Dict[int, _FleetRequest] = {}
        self._ids = itertools.count(1)
        self._probe_ids = itertools.count(1)
        self._swap_ids = itertools.count(1)
        self._swaps: Dict[int, List] = {}  # id -> [Event, ok, version]
        self._rr = 0  # dispatch tie-break cursor
        self._started = False
        self._closed = False

        # Timer wheel: (when, seq, fn) heap drained by one thread.
        self._timer_heap: List = []
        self._timer_seq = itertools.count()
        self._timer_cond = locksmith.make_condition("FleetRouter._timer_cond")

        self._ctx = None
        self._response_q = None
        self._free_q = None
        self._codec: Optional[transport.RequestCodec] = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle ------------------------------------------------------------

    def start(self, timeout_s: float = 120.0) -> "FleetRouter":
        """Spawns every replica and waits until at least one reports
        started (raises on a fully-failed bring-up). Late starters keep
        warming in the background and join the pool when ready."""
        if self._started:
            raise RuntimeError("FleetRouter.start() called twice")
        if self._transport_mode == "socket":
            # Cross-host fabric: replicas are independent process groups
            # on the CRC-framed wire. No mp context, no shared-memory
            # ring (the codec degrades to inline pickled arrays — the
            # only shape that crosses hosts); replies arrive through the
            # per-replica links into a plain thread queue.
            from tensor2robot_tpu.serving.pool import (
                RemoteReplicaPool, ResponseQueue,
            )

            if self._fabric_root is None:
                import tempfile

                self._fabric_root = tempfile.mkdtemp(prefix="t2r-fabric-")
            self._response_q = ResponseQueue()
            self._free_q = None
            self._codec = transport.RequestCodec(
                None, inline_max_bytes=self._inline_max
            )
            self._pool = RemoteReplicaPool(
                self._fabric_root,
                self._response_q.put,
                zone=self._zone,
                connect_timeout_s=t2r_flags.get_int(
                    "T2R_FABRIC_CONNECT_TIMEOUT_MS"
                ) / 1e3,
            )
        else:
            import multiprocessing

            self._ctx = multiprocessing.get_context("spawn")
            self._response_q = self._ctx.Queue()
            self._free_q = self._ctx.Queue()
            self._codec = transport.RequestCodec(
                self._free_q,
                inline_max_bytes=self._inline_max,
                num_slots=self._shm_slots,
            )
        # t2r: unguarded-ok(start() runs before any fleet thread exists)
        for replica in self._replicas:
            self._spawn(replica)
        self._started = True
        for name, target in (
            ("t2r-fleet-collect", self._collector_loop),
            ("t2r-fleet-timer", self._timer_loop),
            ("t2r-fleet-monitor", self._monitor_loop),
        ):
            thread = threading.Thread(target=target, name=name, daemon=True)
            thread.start()
            self._threads.append(thread)
        def bring_up_settled() -> bool:
            with self._lock:
                return any(r.state == _UP for r in self._replicas) or all(
                    r.state == _DEAD and r.respawns >= self._max_respawns
                    for r in self._replicas
                )

        Backoff(base_ms=20.0, cap_ms=60.0, factor=1.0, seed=0).poll(
            bring_up_settled, total_s=timeout_s
        )
        with self._lock:
            if any(r.state == _UP for r in self._replicas):
                return self
        self.stop()
        raise RuntimeError(
            f"no replica became healthy within {timeout_s}s"
        )

    def _spawn(self, replica: _Replica) -> None:
        replica.state = _STARTING
        replica.started_at = time.monotonic()
        replica.inflight = set()
        replica.consecutive_failures = 0
        if self._pool is not None:
            # Socket fabric: the pool bumps the incarnation, launches
            # the detached process, and hands back a (handle, link)
            # pair that duck-types (proc, request_q). The link refuses
            # the predecessor's stale published address; the monitor's
            # health-probe puts double as the re-resolution loop, and
            # the fresh connection's ("hello",) handshake elicits the
            # ("started", ...) that readmits the replica to routing.
            replica.proc, replica.request_q = self._pool.spawn(
                replica.index, replica.spec
            )
            return
        replica.request_q = self._ctx.Queue()
        replica.proc = self._ctx.Process(
            target=replica_main,
            args=(
                replica.index, replica.spec, replica.request_q,
                self._response_q, self._free_q,
            ),
            name=f"t2r-replica-{replica.index}",
            daemon=True,
        )
        replica.proc.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._requests.values())
            self._requests.clear()
        for request in pending:
            if not request.future.done():
                request.future._set(
                    None, RouterClosed("router stopped with request pending")
                )
        with self._timer_cond:
            self._timer_cond.notify_all()
        # t2r: unguarded-ok(stop() flipped _closed under the lock above; _replicas is append-only and fenced)
        for replica in self._replicas:
            if replica.request_q is not None:
                best_effort(replica.request_q.put, ("stop",))
        deadline = time.monotonic() + timeout_s
        # t2r: unguarded-ok(stop() flipped _closed under the lock above; _replicas is append-only and fenced)
        for replica in self._replicas:
            proc = replica.proc
            if proc is None:
                continue
            proc.join(timeout=max(0.1, deadline - time.monotonic()))
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=1.0)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=1.0)
        if self._codec is not None:
            self._codec.close()
        for q in [self._response_q, self._free_q] + [
            # t2r: unguarded-ok(stop() flipped _closed under the lock above; _replicas is append-only and fenced)
            r.request_q for r in self._replicas
        ]:
            if q is None:
                continue
            best_effort(q.cancel_join_thread)
            best_effort(q.close)
        if self._pool is not None:
            # Socket links already closed through the loop above (they
            # duck-type the queue teardown); this sweeps any link the
            # pool still tracks for a replica mid-respawn.
            best_effort(self._pool.close)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface -------------------------------------------------------

    def submit(
        self,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
        policy_id: Optional[str] = None,
    ) -> RouterFuture:
        """Routes one example; never blocks on replicas. Raises typed
        admission errors (FleetSaturated / ReplicaUnavailable /
        RouterClosed) synchronously; everything after admission resolves
        through the returned future. `policy_id` names the policy on a
        multi-policy fleet (placement-aware: replicas already holding it
        resident are preferred; a miss is a counted cold dispatch)."""
        # t2r: unguarded-ok(racy fast-fail only; admission re-checks _closed under the lock below)
        if not self._started or self._closed:
            raise RouterClosed("router is not running")
        now = time.monotonic()
        deadline = now + (
            deadline_ms / 1e3 if deadline_ms is not None
            else self._default_deadline_s
        )
        arrays = {k: np.asarray(v) for k, v in features.items()}
        request = _FleetRequest(next(self._ids), arrays, deadline, policy_id)
        with self._lock:
            # Re-check under the lock: stop() flips _closed and drains
            # _requests while holding it, so a request admitted past the
            # unlocked fast-path check but registered AFTER the drain
            # would never be failed by stop() — and the deadline backstop
            # timer has already exited — leaving its future unresolved
            # forever.
            if self._closed:
                raise RouterClosed("router is not running")
            replica = self._pick_replica(exclude=(), policy_id=policy_id)
            self._requests[request.id] = request
            self._metrics.count("submitted")
            try:
                self._dispatch(request, replica, hedge=False)
            except Exception:
                self._requests.pop(request.id, None)
                self._metrics.count("submitted", -1)
                raise
        # Router-side deadline backstop: EVERY future resolves, even if
        # the replica wedges and the monitor misses it.
        self._schedule(
            deadline - now + 0.005, lambda: self._on_deadline(request)
        )
        return request.future

    def call(
        self,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
        timeout: Optional[float] = None,
        policy_id: Optional[str] = None,
    ) -> FleetResponse:
        future = self.submit(
            features, deadline_ms=deadline_ms, policy_id=policy_id
        )
        if timeout is None:
            timeout = (
                deadline_ms / 1e3 if deadline_ms is not None
                else self._default_deadline_s
            ) + 30.0
        return future.result(timeout)

    # -- dispatch core (all called under self._lock) --------------------------

    def _pick_replica(
        self,
        exclude: Sequence[int],
        count: bool = True,
        policy_id: Optional[str] = None,
    ) -> _Replica:
        """Least-loaded healthy replica, deadline-aware admission.

        Raises FleetSaturated when healthy replicas exist but all are at
        the in-flight cap; ReplicaUnavailable when none are healthy.
        `count=False` suppresses the shed counters (hedge probes are
        best-effort and must not read as admission failures).

        With `policy_id` on a multi-policy fleet, replicas whose last
        health snapshot lists the policy RESIDENT are preferred among
        the admissible candidates — dispatching to one avoids a
        replica-side cold load. When the fleet reports residency but no
        admissible replica holds this policy, the dispatch is counted
        (`policy_cold_dispatches`) and falls back to least-loaded: a
        cold load there is still cheaper than shedding."""
        up = [r for r in self._replicas if r.state == _UP]
        if not up:
            if count:
                self._metrics.count("no_replica")
            raise ReplicaUnavailable(
                "no healthy replica (pool starting, broken, or dead)"
            )
        candidates = [
            r for r in up
            if r.index not in exclude and len(r.inflight) < self._max_inflight
        ]
        if not candidates:
            candidates = [
                r for r in up if len(r.inflight) < self._max_inflight
            ]
        if not candidates:
            if count:
                self._metrics.count("shed_saturated")
            raise FleetSaturated(
                f"all {len(up)} healthy replicas at the in-flight cap "
                f"({self._max_inflight}); request shed"
            )
        if policy_id is not None:
            aware = [
                r for r in candidates
                if r.last_health.get("resident_policies") is not None
            ]
            if aware:
                resident = [
                    r for r in aware
                    if policy_id in r.last_health["resident_policies"]
                ]
                if resident:
                    candidates = resident
                    if count:
                        self._metrics.count("policy_resident_dispatches")
                elif count:
                    # No admissible replica holds this policy resident:
                    # the dispatch will cold-load on arrival. Counted so
                    # placement regressions show up as a ratio, not as
                    # silent tail latency.
                    self._metrics.count("policy_cold_dispatches")
        load = min(len(r.inflight) for r in candidates)
        tied = [r for r in candidates if len(r.inflight) == load]
        self._rr += 1
        return tied[self._rr % len(tied)]

    def _dispatch(
        self, request: _FleetRequest, replica: _Replica, hedge: bool
    ) -> None:
        remaining = request.deadline - time.monotonic()
        if remaining <= 0:
            raise RequestAbandoned(
                f"request {request.id} deadline passed before dispatch",
                reason="deadline",
                detail=request.last_failure,
            )
        if not hedge:
            request.dispatches += 1
        attempt = request.dispatches + (1 if hedge or request.hedged else 0)
        if self._pool is not None and wire_codec.wire_mode() == "spec":
            # Socket fabric on the spec wire: ship the features dict
            # itself and let the frame codec segment the arrays —
            # pickling them into an inline blob here would re-bury the
            # payload the zero-copy wire exists to expose.
            payload = (
                "raw",
                {k: np.asarray(v) for k, v in request.features.items()},
            )
        else:
            payload = self._codec.encode(request.features)
        key = (request.id, attempt)
        replica.inflight.add(key)
        request.live.add((attempt, replica.index))
        message = ("req", request.id, attempt, time.time() + remaining, payload)
        if request.policy_id is not None:
            # Optional trailing element keeps the 5-tuple wire shape for
            # single-policy traffic byte-for-byte unchanged.
            message = message + (request.policy_id,)
        try:
            replica.request_q.put(message)
        except Exception as err:
            replica.inflight.discard(key)
            request.live.discard((attempt, replica.index))
            # The slot name never crossed the process boundary, so the
            # replica-side decode that normally releases it will never
            # run — reclaim it here or the ring shrinks by one slot per
            # failed dispatch.
            self._codec.release(payload)
            raise ReplicaUnavailable(
                f"replica {replica.index} transport failed: {err}"
            ) from err
        self._metrics.count("dispatched")
        if hedge:
            request.hedge_attempts.add(attempt)
            self._metrics.count("hedged")
        elif self._hedge_s > 0 and not request.hedged:
            self._schedule(
                self._hedge_s, lambda: self._maybe_hedge(request)
            )

    def _maybe_hedge(self, request: _FleetRequest) -> None:
        with self._lock:
            if (
                self._closed
                or request.future.done()
                or request.hedged
                or request.id not in self._requests
            ):
                return
            carrying = {replica for _, replica in request.live}
            try:
                replica = self._pick_replica(
                    exclude=tuple(carrying),
                    count=False,
                    policy_id=request.policy_id,
                )
            except FleetError:
                return  # no spare capacity: hedging is best-effort
            if replica.index in carrying:
                return  # only the original is free; a hedge there is noise
            request.hedged = True
            try:
                self._dispatch(request, replica, hedge=True)
            except FleetError:
                request.hedged = False  # failed to place; original stands

    def _retry(self, request: _FleetRequest, exclude: Tuple[int, ...]) -> None:
        with self._lock:
            if (
                self._closed
                or request.future.done()
                or request.id not in self._requests
            ):
                return
            self._metrics.count("retries")
            try:
                replica = self._pick_replica(
                    exclude=exclude, policy_id=request.policy_id
                )
                self._dispatch(request, replica, hedge=False)
                return
            except FleetError as err:
                failure = f"{type(err).__name__}: {err}"
        self._fail_request(
            request,
            RequestAbandoned(
                f"request {request.id} could not be re-dispatched: {failure}",
                reason="retries",
                detail=request.last_failure,
            ),
        )

    # -- completion paths -----------------------------------------------------

    def _finish(self, request: _FleetRequest, response, error) -> None:
        """Resolves a request exactly once and drops its bookkeeping.
        Caller must NOT hold the lock for the future._set (client
        callbacks run there)."""
        with self._lock:
            if self._requests.pop(request.id, None) is None:
                return  # already resolved
            for attempt, replica_index in request.live:
                self._replicas[replica_index].inflight.discard(
                    (request.id, attempt)
                )
            request.live.clear()
        if error is None:
            self._metrics.count("completed")
            self._metrics.observe_latency(
                (time.monotonic() - request.t_submit) * 1e3
            )
        else:
            self._metrics.count("failed")
        request.future._set(response, error)

    def _fail_request(self, request: _FleetRequest, error) -> None:
        self._finish(request, None, error)

    def _on_deadline(self, request: _FleetRequest) -> None:
        with self._lock:
            if request.future.done() or request.id not in self._requests:
                return
        self._metrics.count("abandoned_deadline")
        self._fail_request(
            request,
            RequestAbandoned(
                f"request {request.id} hit its deadline after "
                f"{request.dispatches} dispatch(es)"
                + (f"; last failure: {request.last_failure}"
                   if request.last_failure else ""),
                reason="deadline",
                detail=request.last_failure,
            ),
        )

    def _on_attempt_failure(
        self,
        request: _FleetRequest,
        replica_index: int,
        failure: str,
        fatal: bool = False,
    ) -> None:
        """One attempt failed: retry elsewhere with jittered backoff, or
        fail typed when budget/deadline is gone."""
        with self._lock:
            if request.future.done() or request.id not in self._requests:
                return
            request.last_failure = failure
            if fatal:
                fail_now: Optional[FleetError] = RequestAbandoned(
                    f"request {request.id} failed fatally on replica "
                    f"{replica_index}: {failure}",
                    reason="deadline" if "Deadline" in failure else "fatal",
                    detail=failure,
                )
            elif request.dispatches > self._retries:
                self._metrics.count("abandoned_retries")
                fail_now = RequestAbandoned(
                    f"request {request.id} exhausted its retry budget "
                    f"({self._retries} retries): {failure}",
                    reason="retries",
                    detail=failure,
                )
            else:
                fail_now = None
                backoff = self._retry_backoff.delay_s(
                    max(1, request.dispatches)
                )
                exclude = (replica_index,)
        if fail_now is not None:
            self._fail_request(request, fail_now)
            return
        self._schedule(backoff, lambda: self._retry(request, exclude))

    # -- replica state machine ------------------------------------------------

    def _note_replica_failure(self, replica: _Replica) -> None:
        replica.consecutive_failures += 1
        if (
            replica.consecutive_failures >= self._circuit_threshold
            and replica.state == _UP
        ):
            replica.state = _BROKEN
            replica.broken_until = time.monotonic() + self._circuit_cooloff_s
            self._metrics.count("circuit_breaks")
            _log.warning(
                "replica %d circuit-broken after %d consecutive failures",
                replica.index, replica.consecutive_failures,
            )

    def _on_replica_death(self, replica: _Replica) -> None:
        """Process gone: fail its in-flight attempts over to siblings,
        then respawn (bounded). A RETIRED replica's exit is the expected
        end of a drain — counted separately, never respawned."""
        with self._lock:
            if replica.state == _DEAD:
                return
            replica.state = _DEAD
            if replica.retired:
                self._metrics.count("retired_exits")
            else:
                self._metrics.count("replica_deaths")
            orphans = list(replica.inflight)
            replica.inflight = set()
            requests = []
            for req_id, attempt in orphans:
                request = self._requests.get(req_id)
                if request is None:
                    continue
                request.live.discard((attempt, replica.index))
                requests.append(request)
        if orphans or not replica.retired:
            _log.warning(
                "replica %d died with %d in-flight request(s); failing over",
                replica.index, len(orphans),
            )
        for request in requests:
            self._on_attempt_failure(
                request, replica.index, "replica process died"
            )
        with self._lock:
            can_respawn = (
                self._respawn
                and not self._closed
                and not replica.retired
                and replica.respawns < self._max_respawns
            )
            if can_respawn:
                replica.respawns += 1
                self._metrics.count("respawns")
                self._spawn(replica)

    # -- background threads ---------------------------------------------------

    def _collector_loop(self) -> None:
        import queue as queue_lib

        # t2r: unguarded-ok(loop-exit staleness is one 0.1s tick; stop() also closes the queue under us)
        while not self._closed:
            try:
                message = self._response_q.get(timeout=0.1)
            except queue_lib.Empty:
                continue
            except (OSError, ValueError):
                return  # queue closed under us during stop()
            try:
                self._handle_message(message)
            except Exception:
                _log.exception("collector: failed handling %r", message[:2])

    def _handle_message(self, message) -> None:
        kind = message[0]
        if kind == "rsp":
            self._on_reply(*message[1:])
        elif kind == "health":
            _, index, _probe_id, snap, _t = message
            with self._lock:
                replica = self._replicas[index]
                replica.last_health = snap
                replica.last_health_time = time.monotonic()
                replica.version = snap.get("model_version", replica.version)
                if replica.state == _SUSPECT:
                    replica.state = _UP
                    replica.consecutive_failures = 0
                elif (
                    replica.state == _BROKEN
                    and time.monotonic() >= replica.broken_until
                ):
                    replica.state = _UP
                    replica.consecutive_failures = 0
                    self._metrics.count("circuit_recoveries")
                elif replica.state == _STARTING and not replica.retired:
                    # Socket fabric: the ("hello",)->("started",...)
                    # handshake can be lost on the wire (drop/partition
                    # at net_send). The replica then answers probes
                    # while the router still holds it in `starting` —
                    # and every answer refreshes last_health_time, so
                    # the boot-timeout branch never fires either: the
                    # replica would be wedged out of routing forever.
                    # A health reply carries the same evidence
                    # "started" does (an address is only published
                    # after the factory succeeded), so it admits too.
                    replica.state = _UP
                    replica.consecutive_failures = 0
                    if replica.started_at:
                        replica.boot_ms = round(
                            (time.monotonic() - replica.started_at)
                            * 1e3,
                            3,
                        )
        elif kind == "started":
            _, index, version, _pid = message
            with self._lock:
                replica = self._replicas[index]
                if replica.retired or replica.state == _DRAINING:
                    # Socket fabric: a link reconnect re-elicits the
                    # ("hello",)->("started",...) handshake; a draining
                    # replica must not be readmitted to routing by it.
                    return
                replica.state = _UP
                replica.version = version
                replica.last_health_time = time.monotonic()
                replica.consecutive_failures = 0
                if replica.started_at:
                    replica.boot_ms = round(
                        (time.monotonic() - replica.started_at) * 1e3, 3
                    )
        elif kind == "swapped":
            _, index, swap_id, ok, version = message
            with self._lock:
                self._replicas[index].version = version
                entry = self._swaps.get(swap_id)
                if entry is not None:
                    entry[1], entry[2] = ok, version
                    entry[0].set()
        elif kind == "stopped":
            pass
        else:
            _log.warning("collector: unknown message kind %r", kind)

    def _on_reply(self, index, req_id, attempt, crc, blob) -> None:
        with self._lock:
            replica = self._replicas[index]
            replica.inflight.discard((req_id, attempt))
            request = self._requests.get(req_id)
            if request is not None:
                was_live = (attempt, index) in request.live
                request.live.discard((attempt, index))
            else:
                was_live = False
        try:
            body = transport.unpack(crc, blob)
        except transport.IntegrityError as err:
            self._metrics.count("corrupt_replies")
            with self._lock:
                self._note_replica_failure(replica)
            if request is not None and was_live:
                self._on_attempt_failure(
                    request, index, f"corrupt reply: {err}"
                )
            return
        if request is None or request.future.done():
            self._metrics.count("late_replies")
            return
        if body[0] == "ok":
            _, outputs, version, spans = body
            with self._lock:
                replica.consecutive_failures = 0
            spans = dict(spans)
            spans["total_ms"] = (
                time.monotonic() - request.t_submit
            ) * 1e3
            # Only an attempt actually PLACED as a hedge counts as a
            # hedge win — a retry winning on a hedged request must not
            # inflate the metric operators tune T2R_FLEET_HEDGE_MS by.
            if attempt in request.hedge_attempts:
                self._metrics.count("hedge_wins")
            self._finish(
                request,
                FleetResponse(
                    outputs, version, spans, index,
                    attempts=max(attempt, request.dispatches),
                    hedged=request.hedged,
                ),
                None,
            )
            return
        # Typed replica-side failure.
        _, failure_class, detail = body
        failure = f"{failure_class}: {detail}"
        self._metrics.count(f"replica_error_{failure_class}")
        with self._lock:
            # A deadline miss inside the replica is congestion, not a
            # replica fault; do not tip the circuit breaker for it.
            if failure_class != "DeadlineExceeded":
                self._note_replica_failure(replica)
        if not was_live:
            self._metrics.count("late_replies")
            return
        self._on_attempt_failure(
            request, index, failure,
            fatal=failure_class == "DeadlineExceeded",
        )

    def _timer_loop(self) -> None:
        # t2r: unguarded-ok(loop-exit staleness is one timer tick; stop() notifies the cond to wake us)
        while not self._closed:
            due: List = []
            with self._timer_cond:
                now = time.monotonic()
                while self._timer_heap and self._timer_heap[0][0] <= now:
                    due.append(heapq.heappop(self._timer_heap)[2])
                if not due:
                    wait = (
                        self._timer_heap[0][0] - now
                        if self._timer_heap else 0.05
                    )
                    self._timer_cond.wait(timeout=max(0.001, min(wait, 0.05)))
            # Actions run with NO lock held: they take self._lock
            # themselves, and holding the timer condition across them
            # would invert against _schedule() callers under self._lock.
            for fn in due:
                try:
                    fn()
                except Exception:
                    _log.exception("timer action failed")

    def _schedule(self, delay_s: float, fn) -> None:
        with self._timer_cond:
            heapq.heappush(
                self._timer_heap,
                (time.monotonic() + max(0.0, delay_s), next(self._timer_seq), fn),
            )
            self._timer_cond.notify()

    @poll_loop
    def _monitor_loop(self) -> None:
        # t2r: unguarded-ok(monitor cadence read; one stale probe tick is harmless)
        while not self._closed:
            time.sleep(self._probe_interval_s)
            # t2r: unguarded-ok(re-check after the sleep; worst case is one extra probe)
            if self._closed:
                return
            now = time.monotonic()
            # Copy: the autoscaler may append replicas mid-iteration.
            # t2r: unguarded-ok(snapshot copy; list append is atomic under the GIL and state is re-checked)
            for replica in list(self._replicas):
                proc = replica.proc
                if proc is not None and not proc.is_alive():
                    self._on_replica_death(replica)
                    continue
                if replica.state == _DEAD:
                    continue
                # Probe (replies flow back through the collector).
                try:
                    replica.request_q.put(("health", next(self._probe_ids)))
                except Exception:
                    continue
                silent_for = now - max(
                    replica.last_health_time, replica.started_at
                )
                if replica.state == _UP and silent_for > (
                    self._probe_miss_limit * self._probe_interval_s
                ):
                    with self._lock:
                        if replica.state == _UP:
                            replica.state = _SUSPECT
                            self._metrics.count("evictions")
                            _log.warning(
                                "replica %d silent for %.0fms; evicted from "
                                "routing", replica.index, silent_for * 1e3,
                            )
                elif replica.state in (_SUSPECT, _BROKEN) and silent_for > (
                    2 * self._probe_miss_limit * self._probe_interval_s
                ):
                    # Unresponsive past the hard limit: kill it and let
                    # the death path respawn a fresh one.
                    if self._respawn and proc is not None:
                        _log.warning(
                            "replica %d unresponsive %.0fms; hard-killing",
                            replica.index, silent_for * 1e3,
                        )
                        self._metrics.count("hard_kills")
                        proc.kill()
                elif (
                    replica.state == _STARTING
                    and silent_for > self._boot_timeout_s
                ):
                    # A boot can be slow (restore + bucket prewarm), but
                    # a process WEDGED in its factory would otherwise sit
                    # in `starting` forever — unrouted, unprobed by the
                    # eviction branches, permanently lost capacity. Kill
                    # it; the death path respawns it against the same
                    # max_respawns budget, so a boot-crash-loop still
                    # terminates in _DEAD rather than cycling forever.
                    if self._respawn and proc is not None:
                        _log.warning(
                            "replica %d stuck starting for %.0fs; "
                            "hard-killing", replica.index, silent_for,
                        )
                        self._metrics.count("hard_kills")
                        proc.kill()

    # -- fleet operations ------------------------------------------------------

    def add_replica(self, spec: Optional[ReplicaSpec] = None) -> int:
        """Grows the pool by one replica (the autoscaler's scale-up
        primitive): appends a fresh _Replica on the next index and
        spawns it — it joins routing when it reports started. `spec`
        defaults to the first construction spec (the homogeneous-pool
        case). Returns the new replica's index."""
        if not self._started:
            raise RuntimeError("add_replica() before start()")
        with self._lock:
            if self._closed:
                raise RouterClosed("router is not running")
            replica = _Replica(
                len(self._replicas), spec if spec is not None else self._specs[0]
            )
            self._replicas.append(replica)
            self._metrics.count("scale_ups")
            self._spawn(replica)
            return replica.index

    def retire_replica(
        self, index: int, drain_timeout_s: float = 30.0
    ) -> bool:
        """Shrinks the pool by draining replica `index` (the autoscaler's
        scale-down primitive): the replica leaves the routing set
        immediately (state `draining`), keeps serving its in-flight
        requests to completion, and only then is told to stop — the
        rolling-swap discipline applied to capacity, so retiring never
        kills a request. Returns False (and restores the replica to
        routing) if the drain does not empty within the timeout."""
        with self._lock:
            replica = self._replicas[index]
            if replica.state not in (_UP, _SUSPECT, _BROKEN):
                return False
            prior_state = replica.state
            replica.state = _DRAINING
            replica.retired = True
            self._metrics.count("retirements")

        def drained() -> bool:
            with self._lock:
                return not replica.inflight or self._closed

        Backoff(base_ms=10.0, cap_ms=50.0, factor=1.0, seed=index).poll(
            drained, total_s=drain_timeout_s
        )
        with self._lock:
            if replica.inflight and not self._closed:
                # Drain stalled: put the replica back rather than kill
                # its in-flight work. The caller may retry later.
                replica.state = prior_state
                replica.retired = False
                self._metrics.count("retirement_aborts")
                return False
        best_effort(replica.request_q.put, ("stop",))
        return True

    def load(self) -> Dict:
        """The autoscaler's signal: live capacity and how full it is.
        `utilization` is in-flight work over routable capacity
        (up-replicas x max_inflight); `shed_saturated` is cumulative —
        scalers diff it across ticks to see overload the in-flight
        gauge already shed."""
        with self._lock:
            up = [r for r in self._replicas if r.state == _UP]
            pending = [
                r for r in self._replicas
                if r.state in (_STARTING, _SUSPECT, _BROKEN)
                and not r.retired
            ]
            draining = [r for r in self._replicas if r.state == _DRAINING]
            inflight = sum(len(r.inflight) for r in up)
        counters = self._metrics.snapshot()["counters"]
        capacity = len(up) * self._max_inflight
        return {
            "replicas_up": len(up),
            "replicas_pending": len(pending),
            "replicas_draining": len(draining),
            "inflight": inflight,
            "capacity": capacity,
            "utilization": (inflight / capacity) if capacity else 1.0,
            "shed_saturated": counters.get("shed_saturated", 0),
        }

    def rolling_swap(
        self,
        swap_timeout_s: float = 60.0,
        policy_id: Optional[str] = None,
    ) -> Dict:
        """Hot-swaps every live replica to the newest export, one at a
        time. Each replica keeps serving its OLD version until the new
        one is prewarmed (PolicyServer's restore-prewarm hook), so fleet
        capacity never drops by more than zero servers and drops by one
        only if a swap fails outright. Returns per-replica results; a
        failed swap aborts the roll (the remaining replicas keep the old
        version — a bad artifact must not take the fleet down).

        `policy_id` scopes the roll to ONE policy on a multi-policy
        fleet: only that policy's server swaps per replica, so sibling
        policies keep serving their current versions without a blip."""
        results: Dict[str, Any] = {"swapped": [], "failed": None}
        self._metrics.count("rolling_swaps")
        # t2r: unguarded-ok(iterates a snapshot copy; per-replica work re-validates state under the lock)
        for replica in list(self._replicas):
            with self._lock:
                if replica.state not in (_UP, _SUSPECT, _BROKEN):
                    continue
                swap_id = next(self._swap_ids)
                entry = [threading.Event(), False, replica.version]
                self._swaps[swap_id] = entry
                message = ("swap", swap_id, time.time() + swap_timeout_s)
                if policy_id is not None:
                    message = message + (policy_id,)
                try:
                    # t2r: blocking-ok(unbounded mp.Queue put never blocks on capacity)
                    replica.request_q.put(message)
                except Exception:
                    results["failed"] = replica.index
                    self._swaps.pop(swap_id, None)
                    break
            if not entry[0].wait(swap_timeout_s + 5.0):
                results["failed"] = replica.index
                with self._lock:
                    self._swaps.pop(swap_id, None)
                break
            with self._lock:
                self._swaps.pop(swap_id, None)
            if not entry[1]:
                results["failed"] = replica.index
                break
            results["swapped"].append(
                {"replica": replica.index, "version": entry[2]}
            )
        return results

    # -- introspection --------------------------------------------------------

    @property
    def num_replicas(self) -> int:
        # t2r: unguarded-ok(len() of an append-only list is an atomic snapshot)
        return len(self._replicas)

    def replica_states(self) -> List[str]:
        with self._lock:
            return [r.state for r in self._replicas]

    def replica_pids(self) -> List[Optional[int]]:
        """Replica process pids by index (None before spawn). The ops
        surface for external fault injection — bench.py's chaos leg
        SIGKILLs a pid from here mid-sweep."""
        with self._lock:
            return [
                r.proc.pid if r.proc is not None else None
                for r in self._replicas
            ]

    def snapshot(self) -> Dict:
        snap = self._metrics.snapshot()
        with self._lock:
            snap["pending_requests"] = len(self._requests)
            snap["replicas"] = [
                {
                    "index": r.index,
                    "state": r.state,
                    "inflight": len(r.inflight),
                    "version": r.version,
                    "consecutive_failures": r.consecutive_failures,
                    "respawns": r.respawns,
                    # Per-replica low-precision regime off the last health
                    # snapshot: a mixed rollout (some replicas int8, some
                    # fp32) is verified HERE, version by version, instead
                    # of by observing precision drift in production.
                    "serve_quant": r.last_health.get("serve_quant"),
                    # ...and its calibration mode: a mixed static/dynamic
                    # rollout changes per-dispatch cost (quant reduces),
                    # so the fleet surface carries it next to the regime.
                    "serve_quant_calib": r.last_health.get(
                        "serve_quant_calib"
                    ),
                    # Boot attribution: how long the last spawn took to
                    # report started, and which restore tier each warmup
                    # bucket came from (off the health snapshot) — the
                    # pair that tells an operator whether a scale-up paid
                    # deserialize-time or compile-time.
                    "boot_ms": r.boot_ms,
                    "prewarm_source": r.last_health.get("prewarm_source"),
                    # Recorded AOT fingerprint of the loaded artifact
                    # (None on backends without one): the gateway folds
                    # this into its coalescing key so two pools serving
                    # different artifacts can never share a dispatch.
                    "model_fingerprint": r.last_health.get(
                        "model_fingerprint"
                    ),
                    # Multi-policy placement surface (None on
                    # single-policy backends): which policies this
                    # replica holds resident right now, and its
                    # replica-side eviction/cold-load counters — all off
                    # the health snapshot, backend-independent.
                    "resident_policies": r.last_health.get(
                        "resident_policies"
                    ),
                    "policy_evictions": r.last_health.get(
                        "policy_evictions"
                    ),
                    "policy_cold_loads": r.last_health.get(
                        "policy_cold_loads"
                    ),
                    # Host identity + per-host AOT key off the health
                    # snapshot (hostname/pid/topology): on the socket
                    # fabric this is the per-host table — which
                    # platform/topology each replica resolved the
                    # artifact's aot/ executables against.
                    "host": r.last_health.get("host"),
                }
                for r in self._replicas
            ]
        snap["transport"] = self._transport_mode
        snap["zone"] = self._zone
        # Router-process wire accounting (codec/stage timings, segment
        # byte classes, receive-pool audit). Meaningful on the socket
        # fabric; ~empty counters on the mp transport.
        snap["wire"] = wire_codec.wire_snapshot()
        snap["wire"]["codec"] = wire_codec.wire_mode()
        snap["wire"]["quant"] = wire_codec.quant_mode()
        snap["policy"] = {
            "max_inflight": self._max_inflight,
            "hedge_ms": self._hedge_s * 1e3,
            "retries": self._retries,
            "backoff_ms": self._backoff_s * 1e3,
            "probe_interval_ms": self._probe_interval_s * 1e3,
            "circuit_threshold": self._circuit_threshold,
            "circuit_cooloff_ms": self._circuit_cooloff_s * 1e3,
            "respawn": self._respawn,
        }
        return snap
