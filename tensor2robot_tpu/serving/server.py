"""PolicyServer: dynamic micro-batching over an AbstractPredictor.

The fleet-serving layer: many concurrent clients (robots, CEM planners,
web frontends) share one predictor whose exported StableHLO artifact is
batch-polymorphic but — like every XLA program — pays a full compile per
CONCRETE batch size. This server turns per-client batch-1 traffic into
bucket-sized batches the export already pre-warmed:

  * bounded request queue with per-request deadlines and admission
    control — when the queue is full the overload policy either sheds
    the OLDEST queued request (freshest-first service, the right default
    for control loops where a stale action is worthless) or rejects the
    incoming one (`T2R_SERVE_OVERLOAD`);
  * a dispatcher thread that coalesces queued requests up to a
    max-wait/max-batch window (`T2R_SERVE_MAX_WAIT_MS`), pads the batch
    to the smallest fitting bucket (serving/buckets.py; ladder =
    exporter's `warmup_batch_sizes`), and runs ONE predict per batch.
    Every served shape is a warmup bucket, so no request ever waits on a
    fresh XLA compile;
  * zero-downtime hot-swap: `hot_swap()` rides
    `ExportedSavedModelPredictor.restore(is_async=True)` — the in-flight
    batch drains on the old version (the predictor swaps its serving fn
    atomically under its own lock), subsequent batches land on the new
    one, and every response reports the model version that computed it;
  * per-request spans + counters (serving/metrics.py) exported as one
    structured `snapshot()`.

Discipline rule (enforced by the `serve-blocking-predict` lint,
analysis/lints.py): inside this package the predictor's blocking
`predict`/`traced_predict` surface is called ONLY from the dispatcher's
`_execute_batch` (and `_prewarm` at startup) — a predict call on the
submit path would serialize clients behind the model and defeat the
whole subsystem.
"""

from __future__ import annotations

import itertools
import logging
import threading

from tensor2robot_tpu.testing import locksmith
import time
from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.serving import buckets as buckets_lib
from tensor2robot_tpu.serving.metrics import RequestSpan, ServerMetrics
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    flatten_spec_structure,
    make_random_numpy,
)

__all__ = [
    "PolicyServer",
    "ServeFuture",
    "ServeResponse",
    "ServeError",
    "RequestRejected",
    "RequestShed",
    "DeadlineExceeded",
    "ServerClosed",
    "PredictFailed",
    "PredictTimeout",
]


class ServeError(RuntimeError):
    """Base class for request-level serving failures."""


class RequestRejected(ServeError):
    """Admission control refused the request (reject overload policy)."""


class RequestShed(ServeError):
    """The request was shed from a full queue (shed_oldest policy)."""


class DeadlineExceeded(ServeError):
    """The request's deadline passed before compute dispatched it."""


class ServerClosed(ServeError):
    """The server stopped before the request could be served."""


class PredictFailed(ServeError):
    """The predictor raised mid-batch; this batch failed, the loop lives.

    `failure_class` carries the original exception's type name (it is
    also the key in the metrics failed_by_class breakdown)."""

    def __init__(self, message: str, failure_class: str = "PredictFailed"):
        super().__init__(message)
        self.failure_class = failure_class


class PredictTimeout(ServeError):
    """The predictor exceeded the compute watchdog; the batch's futures
    failed typed and the dispatcher moved on (the stuck call is
    abandoned on a daemon thread — a hung accelerator call cannot be
    cancelled from the host, only routed around)."""


class ServeResponse:
    """One request's outputs + the model version that computed them."""

    __slots__ = ("outputs", "model_version", "spans")

    def __init__(self, outputs: Dict[str, np.ndarray], model_version: int,
                 spans: Dict[str, float]):
        self.outputs = outputs
        self.model_version = model_version
        self.spans = spans


class ServeFuture:
    """Completion handle returned by submit(); result() blocks."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._event = threading.Event()
        self._response: Optional[ServeResponse] = None
        self._error: Optional[BaseException] = None
        self._callbacks: List = []
        self._cb_lock = locksmith.make_lock("ServeFuture._cb_lock")

    def done(self) -> bool:
        return self._event.is_set()

    def error(self) -> Optional[BaseException]:
        """The failure, if the future completed with one (None while
        pending or on success) — lets completion callbacks branch
        without re-raising."""
        return self._error if self._event.is_set() else None

    def result(self, timeout: Optional[float] = None) -> ServeResponse:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} still pending after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._response

    def add_done_callback(self, fn) -> None:
        """Calls `fn(future)` when the future completes (immediately if it
        already has). Callbacks run on the completing thread (the
        dispatcher) and must be cheap and non-blocking — replica loops
        use this to post replies without a waiter thread per request."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _complete(self) -> None:
        with self._cb_lock:
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)

    def _set_response(self, response: ServeResponse) -> None:
        self._response = response
        self._complete()

    def _set_error(self, error: BaseException) -> None:
        self._error = error
        self._complete()


class _Request:
    __slots__ = ("id", "features", "deadline", "span", "future")

    def __init__(self, request_id: int, features: Dict[str, np.ndarray],
                 deadline: float, span: RequestSpan):
        self.id = request_id
        self.features = features
        self.deadline = deadline
        self.span = span
        self.future = ServeFuture(request_id)


class PolicyServer:
    """Micro-batching policy server over a restored AbstractPredictor.

    Constructor arguments override the `T2R_SERVE_*` flag defaults;
    `batch_buckets` overrides the exporter-published ladder entirely
    (tests, bring-up). The predictor must be restored (or restorable)
    before start().
    """

    def __init__(
        self,
        predictor,
        batch_buckets: Optional[Sequence[int]] = None,
        max_queue: Optional[int] = None,
        max_wait_ms: Optional[int] = None,
        overload: Optional[str] = None,
        default_deadline_ms: Optional[int] = None,
        predict_timeout_ms: Optional[int] = None,
    ):
        self._predictor = predictor
        self._explicit_buckets = batch_buckets
        self._max_queue = (
            max_queue if max_queue is not None
            else t2r_flags.get_int("T2R_SERVE_MAX_QUEUE")
        )
        self._max_wait_s = (
            max_wait_ms if max_wait_ms is not None
            else t2r_flags.get_int("T2R_SERVE_MAX_WAIT_MS")
        ) / 1e3
        self._overload = (
            overload if overload is not None
            else t2r_flags.get_enum("T2R_SERVE_OVERLOAD")
        )
        if self._overload not in ("shed_oldest", "reject"):
            raise ValueError(
                f"overload must be shed_oldest|reject, got {self._overload!r}"
            )
        self._default_deadline_s = (
            default_deadline_ms if default_deadline_ms is not None
            else t2r_flags.get_int("T2R_SERVE_DEADLINE_MS")
        ) / 1e3
        self._predict_timeout_s = (
            predict_timeout_ms if predict_timeout_ms is not None
            else t2r_flags.get_int("T2R_SERVE_PREDICT_TIMEOUT_MS")
        ) / 1e3  # 0 = watchdog off (predict on the dispatcher thread)
        self._buckets: Tuple[int, ...] = ()
        self._flat_spec: Dict[str, ExtendedTensorSpec] = {}
        # Per-bucket restore tier of the SERVING version ("aot" |
        # "cache" | "compile"; mock-ish predictors report "compile"):
        # updated at start() and on every swap prewarm, surfaced in
        # snapshot() so router health probes carry it fleet-wide.
        self._prewarm_source: Dict[int, str] = {}
        self._metrics = ServerMetrics()
        self._queue: deque = deque()
        self._cond = locksmith.make_condition("PolicyServer._cond")
        self._ids = itertools.count(1)
        self._dispatcher: Optional[threading.Thread] = None
        self._started = False
        self._closed = False

    # -- lifecycle ------------------------------------------------------------

    def start(self, prewarm: bool = True) -> "PolicyServer":
        """Resolves the bucket ladder from the loaded export, optionally
        pre-warms every bucket (compiles each served shape BEFORE traffic
        arrives), and starts the dispatcher."""
        if self._started:
            raise RuntimeError("PolicyServer.start() called twice")
        if self._predictor.model_version < 0:
            if not self._predictor.restore():
                raise RuntimeError(
                    "predictor restore failed; cannot start the server"
                )
        loaded = getattr(self._predictor, "loaded_model", None)
        metadata = getattr(loaded, "metadata", None) or {}
        self._buckets = buckets_lib.resolve_buckets(
            self._explicit_buckets, metadata
        )
        spec = self._predictor.get_feature_specification()
        self._flat_spec = {
            key: leaf
            for key, leaf in flatten_spec_structure(spec).items()
            if isinstance(leaf, ExtendedTensorSpec) and not leaf.is_optional
        }
        # Precompiled validation table: submit() runs per request on the
        # client thread, so the spec walk must not (fully-static shapes
        # compare as one tuple; dynamic dims fall back to a rank check;
        # dtypes are coerced to the spec's so one float64 request cannot
        # poison a coalesced batch with a novel-dtype recompile).
        self._spec_checks = []
        for key, leaf in self._flat_spec.items():
            dims = tuple(leaf.shape)
            static = tuple(int(d) for d in dims) if all(
                d is not None for d in dims
            ) else None
            try:
                want_dtype = np.dtype(leaf.dtype)
            except TypeError:
                want_dtype = None
            self._spec_checks.append(
                (key, dims, static, len(dims), want_dtype)
            )
        self._bucket_batches = self._build_bucket_batches(loaded, spec)
        self._ensure_compile_tier(loaded)
        self._record_prewarm_sources(loaded)
        if prewarm:
            self._prewarm()
        # Hot-swap continuity: compile every bucket on an INCOMING version
        # before the predictor flips to it (predictors without the hook
        # simply swap cold).
        installer = getattr(self._predictor, "set_restore_prewarm", None)
        if installer is not None:
            installer(self._prewarm_restored)
        self._started = True
        # t2r: unguarded-ok(start() runs before the dispatcher thread exists)
        self._closed = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="t2r-serve-dispatch", daemon=True
        )
        self._dispatcher.start()
        return self

    def _build_bucket_batches(self, loaded, spec):
        """One spec-conforming batch per bucket: the exporter's warmup
        payloads when the artifact carries them, synthesized random
        batches otherwise. Shared by start()-time prewarm and the
        restore-time prewarm of incoming versions (contents are
        irrelevant for compilation; shapes are the contract)."""
        warmed = {}
        export_dir = getattr(loaded, "export_dir", None)
        if export_dir:
            try:
                warmed = buckets_lib.load_warmup_batches(
                    export_dir, spec, getattr(loaded, "metadata", {})
                )
            except Exception as err:  # noqa: BLE001 — warmup payloads are an
                # optimization; synthesized batches warm the same shapes.
                logging.warning("warmup tfrecord unusable (%s); synthesizing", err)
        batches = {}
        for bucket in self._buckets:
            batch = warmed.get(bucket)
            if batch is None:
                batch = dict(
                    flatten_spec_structure(
                        make_random_numpy(spec, batch_size=bucket, seed=0)
                    ).items()
                )
            batches[bucket] = batch
        return batches

    def _prewarm(self) -> None:
        """One predict per bucket before traffic; after this, serving
        never compiles (on an AOT-hit version it never compiled at
        all — each predict deserialized its bucket's executable)."""
        for bucket in self._buckets:
            self._predictor.predict(self._bucket_batches[bucket])

    def _prewarm_restored(self, loaded, serve_fn) -> None:
        """Runs ON THE RESTORE THREAD before a new version swaps in:
        every bucket readies on the incoming serving fn while the old
        version keeps draining batches — the hot-swap blip stays queue
        drain, never an XLA compile. With AOT executables covering the
        ladder this loop is deserialize-time, not compile-time."""
        # Shapes are fixed by the start()-time ladder/spec; `loaded` is
        # the INCOMING version.
        self._ensure_compile_tier(loaded)
        for bucket in self._buckets:
            serve_fn(self._bucket_batches[bucket])
        # Record the incoming version's restore tiers only once every
        # bucket readied: a failed prewarm ABORTS the swap (the old
        # version keeps serving), and its record must not be
        # overwritten by a version that never served.
        self._record_prewarm_sources(loaded)

    def _ensure_compile_tier(self, loaded) -> None:
        """Engages the persistent compile cache whenever THIS server's
        resolved ladder has a bucket the loaded version cannot serve
        from an AOT executable. The restore-time engagement
        (enable_compile_cache_for) only sees the artifact's own ladder;
        an explicit `batch_buckets` constructor ladder can be wider, and
        its extra buckets must not compile uncached just because the
        warmup ladder happened to be AOT-covered. No-op when the cache
        flag is unset."""
        table = getattr(loaded, "aot_executables", None) or {}
        if any(bucket not in table for bucket in self._buckets):
            from tensor2robot_tpu.serving.compile_cache import (
                enable_compile_cache,
            )

            enable_compile_cache()

    def _record_prewarm_sources(self, loaded) -> None:
        """Per-bucket restore tier of `loaded` + the aot_hits/aot_misses
        counters. A miss is counted ONLY when AOT was requested (the
        loaded model resolved T2R_SERVE_AOT=1) and the bucket still fell
        back — the loud, counted fallback contract."""
        table = getattr(loaded, "aot_executables", None) or {}
        aot_requested = bool(getattr(loaded, "aot_enabled", False))
        cache_on = bool(t2r_flags.get_str("T2R_COMPILE_CACHE_DIR"))
        sources: Dict[int, str] = {}
        hits = misses = 0
        for bucket in self._buckets:
            if bucket in table:
                sources[bucket] = "aot"
                hits += 1
            else:
                sources[bucket] = "cache" if cache_on else "compile"
                if aot_requested:
                    misses += 1
        self._prewarm_source = sources
        if hits:
            self._metrics.count("aot_hits", hits)
        if misses:
            self._metrics.count("aot_misses", misses)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stops the dispatcher. drain=True serves everything already
        queued first; drain=False fails queued requests with
        ServerClosed."""
        with self._cond:
            if not self._started:
                return
            self._closed = True
            if not drain:
                while self._queue:
                    request = self._queue.popleft()
                    request.future._set_error(
                        ServerClosed(f"server stopped, request {request.id} dropped")
                    )
                    self._metrics.count_failure("ServerClosed")
            self._cond.notify_all()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=timeout)
        # The predictor may outlive this server; detach the prewarm hook.
        installer = getattr(self._predictor, "set_restore_prewarm", None)
        if installer is not None:
            installer(None)
        self._started = False

    def __enter__(self) -> "PolicyServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface -------------------------------------------------------

    def submit(
        self,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
    ) -> ServeFuture:
        """Enqueues ONE example (leaf shapes = the spec's, no batch dim);
        returns a future. Never blocks on the model."""
        if not self._started:
            raise RuntimeError("PolicyServer is not started")
        flat = self._validate(features)
        now = time.monotonic()
        deadline = now + (
            deadline_ms / 1e3 if deadline_ms is not None
            else self._default_deadline_s
        )
        request = _Request(next(self._ids), flat, deadline, RequestSpan(now))
        with self._cond:
            if self._closed:
                raise ServerClosed("server is stopping; request refused")
            if len(self._queue) >= self._max_queue:
                if self._overload == "reject":
                    self._metrics.count("rejected")
                    raise RequestRejected(
                        f"queue full ({self._max_queue}); request rejected"
                    )
                victim = self._queue.popleft()
                victim.future._set_error(
                    RequestShed(
                        f"request {victim.id} shed by newer arrival under load"
                    )
                )
                self._metrics.count("shed")
            self._queue.append(request)
            self._metrics.count("admitted")
            self._cond.notify()
        return request.future

    def call(
        self,
        features: Mapping[str, Any],
        deadline_ms: Optional[float] = None,
        timeout: Optional[float] = None,
    ) -> ServeResponse:
        """Blocking convenience: submit + wait (one client thread's view).
        The default wait outlives THIS request's deadline, not the server
        default — a long-deadline call must not time out while live."""
        future = self.submit(features, deadline_ms=deadline_ms)
        if timeout is None:
            timeout = (
                deadline_ms / 1e3 if deadline_ms is not None
                else self._default_deadline_s
            ) + 30.0
        return future.result(timeout)

    def _validate(self, features: Mapping[str, Any]) -> Dict[str, np.ndarray]:
        # Fast path: clients usually pass the flat dict already; fall back
        # to the full spec-structure flatten only for nested inputs.
        flat_in = features
        out: Dict[str, np.ndarray] = {}
        for key, dims, static, rank, want_dtype in self._spec_checks:
            value = flat_in.get(key)
            if value is None:
                if flat_in is features:
                    flat_in = dict(flatten_spec_structure(features).items())
                    value = flat_in.get(key)
                if value is None:
                    raise ValueError(
                        f"request is missing required feature {key!r}"
                    )
            if not isinstance(value, np.ndarray):
                value = np.asarray(value)
            shape = value.shape
            ok = shape == static if static is not None else (
                len(shape) == rank
                and all(d is None or d == g for d, g in zip(dims, shape))
            )
            if not ok:
                raise ValueError(
                    f"feature {key!r}: expected one example of shape "
                    f"{dims}, got {shape} (batching is the server's job — "
                    "submit single examples)"
                )
            if want_dtype is not None and value.dtype != want_dtype:
                value = value.astype(want_dtype)
            out[key] = value
        return out

    # -- introspection --------------------------------------------------------

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    def snapshot(self) -> Dict:
        with self._cond:
            depth = len(self._queue)
        snap = self._metrics.snapshot(queue_depth=depth)
        snap["buckets"] = list(self._buckets)
        snap["overload_policy"] = self._overload
        snap["max_queue"] = self._max_queue
        snap["max_wait_ms"] = self._max_wait_s * 1e3
        snap["model_version"] = self._predictor.model_version
        # Low-precision serving regime of the loaded artifact: router
        # health probes carry this snapshot, so a fleet can verify a
        # mixed rollout (some replicas int8, some fp32) version by
        # version instead of discovering a silent precision mismatch in
        # production Q-values.
        regime = getattr(self._predictor, "quant_regime", None)
        if regime is not None:
            snap["serve_quant"] = regime
            if regime != "none":
                # Which layers the loaded regime contracts NATIVELY in
                # its storage dtype (empty = pure dequant path, e.g.
                # fp16 or a parity-demoted map) — compute attribution
                # per replica, next to the regime it belongs to.
                snap["serve_quant_native_layers"] = list(
                    getattr(self._predictor, "native_dot_layers", ()) or ()
                )
                attention = getattr(
                    self._predictor, "native_attention", ()
                ) or ()
                if attention:
                    snap["serve_quant_native_attention"] = list(attention)
                # Activation-calibration mode + the export-recorded
                # reduce audit of the serving program: a fleet verifies
                # per replica that statically-calibrated versions really
                # dispatch zero activation-quant reduces
                # (activation_quant_reduces == 0), version by version.
                calib = getattr(self._predictor, "calib_mode", None)
                if calib is not None:
                    snap["serve_quant_calib"] = calib
                reduce_audit = getattr(
                    self._predictor, "quant_reduce_audit", None
                )
                if reduce_audit is not None:
                    snap["serve_quant_reduce_audit"] = dict(reduce_audit)
        # Per-bucket restore tier ("aot" = deserialized executable,
        # "cache"/"compile" = the fallback tiers): the boot-attribution
        # surface the router/autoscaler snapshots and the bench's
        # zero-fresh-compile audit read.
        snap["prewarm_source"] = {
            str(bucket): source
            for bucket, source in sorted(self._prewarm_source.items())
        }
        loaded = getattr(self._predictor, "loaded_model", None)
        fallbacks = getattr(loaded, "aot_fallbacks", None)
        if fallbacks:
            # WHY each declared bucket fell off the AOT tier (topology/
            # fingerprint mismatch, corrupt file, ...) — the loud half
            # of the loud-fallback contract, per bucket.
            snap["aot_fallbacks"] = {
                str(bucket): reason
                for bucket, reason in sorted(fallbacks.items())
            }
        # The artifact's recorded AOT fingerprint for the active regime
        # (the PR-11 sha256 over program + weight-payload bytes): the
        # gateway folds it into the coalescing key so requests against
        # different artifacts can never share a dispatch, and the
        # artifact store keys siblings on the same construction.
        meta = getattr(loaded, "metadata", None)
        if isinstance(meta, Mapping):
            fp_table = (meta.get("aot") or {}).get("fingerprint") or {}
            regime_key = getattr(loaded, "quant_regime", None) or "none"
            fingerprint = fp_table.get(regime_key)
            if fingerprint:
                snap["model_fingerprint"] = str(fingerprint)
        # Fleet-visible leak surface: a predictor whose close() abandoned
        # a restore thread reports it here, so router health probes (which
        # ride this snapshot) can see the wounded replica.
        leaked = getattr(self._predictor, "restore_thread_leaked", None)
        if leaked is not None:
            snap["restore_thread_leaked"] = bool(leaked)
        return snap

    # -- hot swap -------------------------------------------------------------

    def hot_swap(self, wait: bool = False) -> bool:
        """Begins serving the newest export version with zero downtime:
        the predictor reloads (async by default) while batches keep
        draining on the current version; the swap lands atomically
        between batches. Responses report model_version per batch."""
        self._metrics.count("hot_swaps")
        return self._predictor.restore(is_async=not wait)

    # -- dispatcher -----------------------------------------------------------

    def _dispatch_loop(self) -> None:
        max_bucket = self._buckets[-1]
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                # Coalesce: from the first request's enqueue, wait up to
                # max_wait for the batch to fill (skip the wait entirely
                # when it's already full or the server is draining).
                window_end = self._queue[0].span.t_enqueue + self._max_wait_s
                while (
                    len(self._queue) < max_bucket
                    and not self._closed
                ):
                    remaining = window_end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                    if not self._queue:
                        break  # everything shed while we slept
                # Micro-batch formation: a request whose deadline passed
                # while queued must not occupy a batch slot — it would
                # both burn compute (the router's backstop already
                # resolved its client future) and displace a LIVE
                # batchmate into the next dispatch cycle. Dropped typed
                # and counted (deadline_dropped) right here.
                batch: List[_Request] = []
                expired: List[_Request] = []
                now = time.monotonic()
                while self._queue and len(batch) < max_bucket:
                    request = self._queue.popleft()
                    if request.deadline < now:
                        expired.append(request)
                    else:
                        batch.append(request)
            for request in expired:
                # deadline_missed stays the aggregate expiry counter
                # (either enforcement point); deadline_dropped attributes
                # the formation-time drops specifically.
                self._metrics.count("deadline_missed")
                self._metrics.count("deadline_dropped")
                request.future._set_error(
                    DeadlineExceeded(
                        f"request {request.id} dropped at batch formation "
                        f"{(now - request.deadline) * 1e3:.1f}ms past its "
                        "deadline"
                    )
                )
            if not batch:
                continue
            try:
                self._execute_batch(batch)
            except Exception as err:  # noqa: BLE001 — a structural failure
                # (bad output shape, bucket assertion) must fail THIS
                # batch's futures, never kill the dispatcher: a dead
                # dispatcher with a live submit() surface is a silent
                # permanent outage.
                logging.exception(
                    "dispatcher: batch of %d failed structurally", len(batch)
                )
                pending = [r for r in batch if not r.future.done()]
                self._metrics.count_failure("DispatchError", len(pending))
                for request in pending:
                    request.future._set_error(
                        ServeError(
                            f"dispatch failed: {type(err).__name__}: {err}"
                        )
                    )

    def _execute_batch(self, batch: List[_Request]) -> None:
        now = time.monotonic()
        live: List[_Request] = []
        for request in batch:
            if request.deadline < now:
                self._metrics.count("deadline_missed")
                request.future._set_error(
                    DeadlineExceeded(
                        f"request {request.id} missed its deadline by "
                        f"{(now - request.deadline) * 1e3:.1f}ms before dispatch"
                    )
                )
            else:
                request.span.t_dispatch = now
                live.append(request)
        if not live:
            return
        bucket = buckets_lib.pick_bucket(self._buckets, len(live))
        features = buckets_lib.pad_feature_batch(
            [r.features for r in live], bucket
        )
        # Belt and braces for the no-novel-shapes guarantee: the batch
        # leading dim must be a warmup bucket.
        lead = {int(v.shape[0]) for v in features.values()}
        if lead != {bucket}:
            raise AssertionError(
                f"padded batch has leading dims {lead}, bucket {bucket}"
            )
        def run_predict():
            # predict_versioned reads (serving fn, version) as one atomic
            # pair so a hot-swap landing mid-call cannot mislabel the
            # responses; predictors without it fall back to the (benignly
            # racy) split read.
            predict_versioned = getattr(
                self._predictor, "predict_versioned", None
            )
            if predict_versioned is not None:
                return predict_versioned(features)
            version = self._predictor.model_version
            return self._predictor.predict(features), version

        def run_predict_watchdogged():
            # Compute watchdog: predict runs on a daemon thread and the
            # dispatcher waits at most the configured budget. A predictor
            # wedged inside an accelerator call cannot be interrupted
            # from here — the thread is abandoned (daemon) and THIS
            # batch fails typed, which is what lets a fleet router route
            # around a stuck replica instead of hanging its clients.
            box: Dict[str, Any] = {}
            done = threading.Event()

            def work():
                try:
                    box["value"] = run_predict()
                except BaseException as err:  # noqa: BLE001 — crosses threads
                    box["error"] = err
                finally:
                    done.set()

            worker = threading.Thread(
                target=work, name="t2r-serve-predict", daemon=True
            )
            worker.start()
            if not done.wait(self._predict_timeout_s):
                raise PredictTimeout(
                    f"predict exceeded the {self._predict_timeout_s * 1e3:.0f}"
                    "ms compute watchdog; batch failed, call abandoned"
                )
            if "error" in box:
                raise box["error"]
            return box["value"]

        try:
            if self._predict_timeout_s > 0:
                outputs, version = run_predict_watchdogged()
            else:
                outputs, version = run_predict()
        except Exception as err:  # noqa: BLE001 — one bad batch must not
            # kill the dispatcher; each request learns the real, TYPED
            # error and the metrics record which failure class it was.
            if isinstance(err, PredictTimeout):
                failure_class = "PredictTimeout"
                typed: ServeError = err
            else:
                failure_class = type(err).__name__
                typed = PredictFailed(
                    f"predict failed: {failure_class}: {err}",
                    failure_class=failure_class,
                )
            self._metrics.count_failure(failure_class, len(live))
            self._metrics.observe_batch(bucket, len(live))
            for request in live:
                request.future._set_error(typed)
            return
        done = time.monotonic()
        self._metrics.observe_batch(bucket, len(live))
        arrays = {k: np.asarray(v) for k, v in outputs.items()}
        spans = []
        for i, request in enumerate(live):
            request.span.t_compute_done = done
            request.span.t_reply = done
            row = {k: v[i] for k, v in arrays.items()}
            millis = request.span.as_millis()
            request.future._set_response(ServeResponse(row, version, millis))
            spans.append(millis)
        self._metrics.observe_replies(spans)


# -- multi-policy loader -------------------------------------------------------


def exported_policy_loader(
    store_root: str,
    policy_ids=None,
    work_dir: Optional[str] = None,
    batch_buckets=None,
    max_wait_ms: Optional[int] = None,
    predict_timeout_ms: Optional[int] = None,
    restore_timeout_s: int = 120,
):
    """(loader, catalog) for a MultiPolicyServer over the artifact store.

    Each load MATERIALIZES the policy's export dir from the
    content-addressed store (export/artifact_store.py — program/AOT
    blobs shared with its base, delta payload decoded and
    hash-verified), then boots a PolicyServer over it with the SHARED
    bucket ladder (`batch_buckets`, defaulting to each artifact's own
    warmup ladder — siblings share a program, hence a ladder) and
    prewarms every bucket before the policy serves. The started
    server's `mem_bytes` is the policy's dense weight footprint from
    the manifest, which is what the resident-set budget meters.
    """
    import tempfile

    from tensor2robot_tpu.export.artifact_store import ArtifactStore
    from tensor2robot_tpu.predictors.exported_savedmodel_predictor import (
        ExportedSavedModelPredictor,
    )

    store = ArtifactStore(store_root)
    catalog = list(policy_ids) if policy_ids is not None else store.policies()
    if not catalog:
        raise ValueError(f"artifact store {store_root} holds no policies")
    if work_dir is None:
        work_dir = tempfile.mkdtemp(prefix="t2r-policies-")

    def loader(policy_id: str):
        import os

        dest = os.path.join(work_dir, policy_id)
        if not os.path.exists(dest):
            store.materialize(policy_id, dest)
        predictor = ExportedSavedModelPredictor(
            export_dir=dest, timeout=restore_timeout_s
        )
        if not predictor.restore():
            raise RuntimeError(
                f"policy {policy_id!r} predictor restore timed out "
                f"under {dest}"
            )
        server = PolicyServer(
            predictor,
            batch_buckets=batch_buckets,
            max_wait_ms=max_wait_ms,
            predict_timeout_ms=predict_timeout_ms,
        )
        server.start(prewarm=True)
        server.mem_bytes = int(
            store.manifest(policy_id)["payload"].get("weights_nbytes", 0)
        )
        return server

    return loader, catalog
