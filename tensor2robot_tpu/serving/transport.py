"""Router <-> replica wire transport: checksummed messages + shm ring.

Two concerns live here, both deliberately boring:

1. **Integrity-checked inline payloads.** Every pickled blob that
   crosses a process boundary carries a CRC32; `unpack` raises
   `IntegrityError` on mismatch instead of handing the router a
   silently-wrong reply. A corrupt reply is a *replica failure* the
   router retries elsewhere — the chaos harness's `corrupt` action
   exists precisely to prove that path.

2. **Shared-memory slab ring for large request payloads.** Image-bearing
   observations (a 472x472x3 uint8 frame is ~670 KB) would otherwise pay
   pickle + pipe + unpickle per hop. The ring reuses the
   `data/dataset.py` slot discipline exactly (and is checked by the same
   `shm-*` lints): slots are created and unlinked ONLY by the ring owner
   (the router); acquisition is `get_nowait` with an inline-pickle
   fallback — a transport under pressure degrades to slower, never to
   stuck; release paths use `put_nowait`. Roles are inverted from the
   dataset (here the *owner* writes and the *worker* releases after
   copying out), but the liveness argument is identical.

   A replica SIGKILLed while holding a slot never returns its name; the
   slot leaks until `close()`. That is bounded (num_slots) and benign —
   an exhausted ring just means every payload rides the inline path —
   whereas trying to reclaim a maybe-still-mapped slot risks two writers
   on one buffer, which is corruption. Crash-safety beats throughput.
"""

from __future__ import annotations

import logging
import pickle
import queue
import zlib
from typing import Any, Dict, List, Mapping, Optional, Tuple

import numpy as np

from tensor2robot_tpu.utils.errors import best_effort

_log = logging.getLogger(__name__)

__all__ = [
    "IntegrityError",
    "pack",
    "unpack",
    "ShmSlabRing",
    "RequestCodec",
    "decode_request",
    "ReplicaSlotCache",
]

_SHM_ALIGN = 64
# Payloads below this ride the pickle pipe; above it they try for a slot.
DEFAULT_INLINE_MAX_BYTES = 64 << 10


class IntegrityError(RuntimeError):
    """A blob failed its CRC (or structural) check at the receiver."""


def pack(obj: Any) -> Tuple[int, bytes]:
    """(crc32, pickle) for one message body."""
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return zlib.crc32(blob), blob


def unpack(crc: int, blob: bytes) -> Any:
    if zlib.crc32(blob) != crc:
        raise IntegrityError(
            f"blob of {len(blob)} bytes failed its CRC32 check"
        )
    try:
        return pickle.loads(blob)
    except Exception as err:
        # A blob that checksums but does not unpickle is the same wire
        # failure from the caller's perspective.
        raise IntegrityError(f"blob failed to decode: {err}") from err


def _align(nbytes: int) -> int:
    return (nbytes + _SHM_ALIGN - 1) // _SHM_ALIGN * _SHM_ALIGN


class ShmSlabRing:
    """Fixed set of shared-memory slots cycling owner -> worker -> owner.

    The owner creates every slot up front and seeds the shared free-name
    queue; `acquire_nowait` takes a name without blocking (None when the
    ring is drained); the worker that consumed a payload returns the
    name via the same queue. `close()` unlinks everything — slots still
    mapped by a live consumer are kept as zombies until their views die
    (same BufferError handling as the dataset ring).
    """

    def __init__(self, free_queue, slot_bytes: int, num_slots: int):
        from multiprocessing import shared_memory

        self.slot_bytes = slot_bytes
        self.slots: Dict[str, Any] = {}
        self.free_queue = free_queue
        created: List[Any] = []
        try:
            for _ in range(num_slots):
                created.append(
                    shared_memory.SharedMemory(create=True, size=slot_bytes)
                )
        except Exception:
            # A mid-loop failure (small /dev/shm) must publish nothing:
            # the caller falls back to inline returns with no slot leaked.
            for shm in created:
                best_effort(shm.close)
                best_effort(shm.unlink)
            raise
        for shm in created:
            self.slots[shm.name] = shm
            self.free_queue.put_nowait(shm.name)
        self._closed = False
        self._zombies: List[Any] = []

    def acquire_nowait(self) -> Optional[str]:
        """A free slot name, or None — the caller then goes inline."""
        if self._closed:
            return None
        try:
            return self.free_queue.get_nowait()
        except queue.Empty:
            return None
        except (OSError, ValueError):
            return None  # queue torn down under us (router stopping)

    def release(self, name: str) -> None:
        if not self._closed:
            best_effort(self.free_queue.put_nowait, name)

    def close(self) -> None:
        self._closed = True
        for shm in self.slots.values():
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
            try:
                shm.close()
            except BufferError:
                self._zombies.append(shm)
        self.slots = {}


class RequestCodec:
    """Owner-side payload encoder with lazy ring creation.

    The first payload large enough to want a slot sizes the ring (plus
    50% headroom, mirroring the dataset's `_maybe_seed_ring`); until
    then — and whenever no slot is free — payloads go inline. Encoded
    forms:

      ("inline", crc, blob)                       blob = pickle(features)
      ("shm", slot, entries, crc, blob)           entries =
            [(key, dtype_str, shape, offset)]; blob = pickle(small items)
      ("raw", features)                           spec-wire socket path:
            the dict rides the frame codec's own segments, no inner
            pickle/CRC (net/codec.py checksums the whole frame)
    """

    def __init__(
        self,
        free_queue,
        inline_max_bytes: int = DEFAULT_INLINE_MAX_BYTES,
        num_slots: int = 8,
    ):
        self._free_queue = free_queue
        self._inline_max = inline_max_bytes
        self._num_slots = num_slots
        self._ring: Optional[ShmSlabRing] = None
        self._ring_failed = False

    @property
    def ring(self) -> Optional[ShmSlabRing]:
        return self._ring

    def _inline(self, features: Mapping[str, np.ndarray]):
        crc, blob = pack(dict(features))
        return ("inline", crc, blob)

    def release(self, payload) -> None:
        """Returns an encoded-but-never-sent shm payload's slot to the
        ring — for dispatch failures after encode but before the slot
        name crossed the process boundary (nothing will ever read it, so
        reuse is safe; NOT releasing it would shrink the ring by one
        slot per failed dispatch). Inline payloads and torn-down rings
        no-op. Callers own single-release discipline: a payload whose
        name DID reach a replica is released by the replica's decode."""
        if payload and payload[0] == "shm" and self._ring is not None:
            self._ring.release(payload[1])

    def encode(self, features: Mapping[str, np.ndarray]):
        arrays = {k: np.asarray(v) for k, v in features.items()}
        large = {k: v for k, v in arrays.items() if v.nbytes >= self._inline_max}
        if not large or self._free_queue is None:
            return self._inline(arrays)
        need = sum(_align(v.nbytes) for v in large.values())
        if self._ring is None and not self._ring_failed:
            try:
                self._ring = ShmSlabRing(
                    self._free_queue,
                    slot_bytes=need + need // 2 + (1 << 16),
                    num_slots=self._num_slots,
                )
            except OSError as err:
                _log.warning(
                    "request shm ring unavailable (%s); inline transport", err
                )
                self._ring_failed = True
        ring = self._ring
        if ring is None or need > ring.slot_bytes:
            return self._inline(arrays)
        name = ring.acquire_nowait()
        if name is None:
            return self._inline(arrays)
        shm = ring.slots.get(name)
        if shm is None:  # foreign name (should not happen); drop it
            return self._inline(arrays)
        entries = []
        offset = 0
        small = {}
        for key, value in arrays.items():
            if value.nbytes < self._inline_max:
                small[key] = value
                continue
            view = np.frombuffer(
                shm.buf, dtype=value.dtype, count=value.size, offset=offset
            ).reshape(value.shape)
            np.copyto(view, value)
            del view
            entries.append((key, str(value.dtype), value.shape, offset))
            offset += _align(value.nbytes)
        crc, blob = pack(small)
        return ("shm", name, entries, crc, blob)

    def close(self) -> None:
        if self._ring is not None:
            self._ring.close()
            self._ring = None


class ReplicaSlotCache:
    """Worker-side attach cache: one SharedMemory mapping per slot name
    for the replica's lifetime (attaching is a syscall; slots cycle)."""

    def __init__(self):
        self._cache: Dict[str, Any] = {}

    def attach(self, name: str):
        shm = self._cache.get(name)
        if shm is None:
            from multiprocessing import shared_memory

            shm = shared_memory.SharedMemory(name=name)
            self._cache[name] = shm
        return shm

    def close(self) -> None:
        for shm in self._cache.values():
            best_effort(shm.close)
        self._cache = {}


def decode_request(
    payload, free_queue, cache: ReplicaSlotCache
) -> Dict[str, np.ndarray]:
    """Worker-side decode. Shm entries are COPIED out and the slot name
    returned to the owner's free queue immediately — the replica holds
    no view into shared state while it computes, so a replica crash
    after this point cannot strand a slot."""
    kind = payload[0]
    if kind == "inline":
        _, crc, blob = payload
        features = unpack(crc, blob)
        if not isinstance(features, dict):
            raise IntegrityError("inline request decoded to a non-dict")
        return features
    if kind == "raw":
        # Spec-wire socket path: the arrays were already validated and
        # materialized by the frame codec (adler32 body + crc32
        # structural region + per-segment spec checks); a second
        # pickle/CRC here is exactly the double pass the spec codec
        # removes. Structural validation still applies.
        _, features = payload
        if not isinstance(features, dict):
            raise IntegrityError("raw request decoded to a non-dict")
        return features
    if kind != "shm":
        raise IntegrityError(f"unknown request payload kind {payload[0]!r}")
    _, name, entries, crc, blob = payload
    try:
        # Everything that can raise sits INSIDE the release scope: a
        # corrupt small-items blob (unpack's CRC) or a failed attach
        # must still return the slot, or each such request permanently
        # shrinks the ring.
        features = unpack(crc, blob)
        shm = cache.attach(name)
        for key, dtype, shape, offset in entries:
            count = 1
            for dim in shape:
                count *= int(dim)
            view = np.frombuffer(
                shm.buf, dtype=np.dtype(dtype), count=count, offset=offset
            ).reshape(shape)
            features[key] = np.array(view)  # copy OUT of the slot
            del view
    finally:
        if free_queue is not None:
            best_effort(free_queue.put_nowait, name)
    return features
