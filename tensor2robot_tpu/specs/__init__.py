"""Spec system: typed tensor contracts and the flat/hierarchical container."""

from tensor2robot_tpu.specs.spec import (
    ExtendedTensorSpec,
    TensorSpec,
    canonical_dtype,
    is_leaf,
)
from tensor2robot_tpu.specs.struct import TensorSpecStruct
from tensor2robot_tpu.specs.proto_io import (
    read_t2r_assets,
    spec_from_proto,
    spec_to_proto,
    struct_from_proto,
    struct_to_proto,
    write_t2r_assets,
)
from tensor2robot_tpu.specs.utils import (
    add_sequence_length_specs,
    assert_equal,
    assert_equal_spec_or_tensor,
    assert_required,
    cast_bfloat16_to_float32,
    cast_float32_to_bfloat16,
    cast_tensors,
    copy_tensorspec,
    dataset_keys,
    filter_required_flat_tensor_spec,
    filter_spec_structure_by_dataset,
    flatten_spec_structure,
    make_constant_numpy,
    make_example_args,
    make_placeholders,
    make_random_numpy,
    map_feed_dict,
    pad_or_clip_tensor_to_spec_shape,
    replace_dtype,
    validate_and_flatten,
    validate_and_pack,
)
