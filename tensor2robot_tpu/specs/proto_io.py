"""Spec <-> proto (de)serialization and the T2RAssets sidecar.

Every exported model ships `assets.extra/t2r_assets.pbtxt` holding its
feature/label specs + global step, so predictors reconstruct the input
contract without model code (reference utils/tensorspec_utils.py:178-216,
411-436, 1685-1733 and proto/t2r.proto).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np
from google.protobuf import text_format

from tensor2robot_tpu.proto import t2r_pb2
from tensor2robot_tpu.specs.spec import ExtendedTensorSpec, canonical_dtype
from tensor2robot_tpu.specs.struct import TensorSpecStruct
from tensor2robot_tpu.specs.utils import flatten_spec_structure

T2R_ASSETS_FILENAME = "t2r_assets.pbtxt"
ASSETS_EXTRA_DIR = "assets.extra"


def spec_to_proto(spec: ExtendedTensorSpec) -> t2r_pb2.ExtendedTensorSpecProto:
    proto = t2r_pb2.ExtendedTensorSpecProto()
    proto.shape.extend(-1 if d is None else int(d) for d in spec.shape)
    proto.dtype = np.dtype(spec.dtype).name
    if spec.name:
        proto.name = spec.name
    proto.is_optional = spec.is_optional
    proto.is_extracted = spec.is_extracted
    proto.is_sequence = spec.is_sequence
    if spec.data_format:
        proto.data_format = spec.data_format
    if spec.dataset_key:
        proto.dataset_key = spec.dataset_key
    if spec.varlen_default_value is not None:
        proto.has_varlen_default_value = True
        proto.varlen_default_value = float(spec.varlen_default_value)
    return proto


def spec_from_proto(proto: t2r_pb2.ExtendedTensorSpecProto) -> ExtendedTensorSpec:
    return ExtendedTensorSpec(
        shape=tuple(None if d == -1 else int(d) for d in proto.shape),
        dtype=canonical_dtype(proto.dtype),
        name=proto.name or None,
        is_optional=proto.is_optional,
        is_extracted=proto.is_extracted,
        is_sequence=proto.is_sequence,
        data_format=proto.data_format or None,
        dataset_key=proto.dataset_key,
        varlen_default_value=(
            proto.varlen_default_value if proto.has_varlen_default_value else None
        ),
    )


def struct_to_proto(structure) -> t2r_pb2.TensorSpecStructProto:
    flat = flatten_spec_structure(structure)
    proto = t2r_pb2.TensorSpecStructProto()
    for key, spec in flat.items():
        if not isinstance(spec, ExtendedTensorSpec):
            raise ValueError(f"Only spec structures serialize; {key!r} is not a spec.")
        proto.keys.append(key)
        proto.key_value[key].CopyFrom(spec_to_proto(spec))
    return proto


def struct_from_proto(proto: t2r_pb2.TensorSpecStructProto) -> TensorSpecStruct:
    out = TensorSpecStruct()
    keys = list(proto.keys) or sorted(proto.key_value.keys())
    for key in keys:
        out[key] = spec_from_proto(proto.key_value[key])
    return out


def write_t2r_assets(
    export_dir: str,
    feature_spec,
    label_spec=None,
    global_step: int = 0,
) -> str:
    """Writes assets.extra/t2r_assets.pbtxt under `export_dir`; returns path."""
    assets = t2r_pb2.T2RAssets()
    assets.feature_spec.CopyFrom(struct_to_proto(feature_spec))
    if label_spec is not None:
        assets.label_spec.CopyFrom(struct_to_proto(label_spec))
    assets.global_step = int(global_step)
    assets_dir = os.path.join(export_dir, ASSETS_EXTRA_DIR)
    os.makedirs(assets_dir, exist_ok=True)
    path = os.path.join(assets_dir, T2R_ASSETS_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text_format.MessageToString(assets))
    os.replace(tmp, path)
    return path


def read_t2r_assets(
    export_dir: str,
) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct], int]:
    """Reads the sidecar; returns (feature_spec, label_spec, global_step)."""
    path = os.path.join(export_dir, ASSETS_EXTRA_DIR, T2R_ASSETS_FILENAME)
    with open(path) as f:
        assets = text_format.Parse(f.read(), t2r_pb2.T2RAssets())
    feature_spec = struct_from_proto(assets.feature_spec)
    label_spec = (
        struct_from_proto(assets.label_spec)
        if assets.HasField("label_spec") and len(assets.label_spec.key_value)
        else None
    )
    return feature_spec, label_spec, int(assets.global_step)
