"""Typed tensor specifications — the contract core of the framework.

`ExtendedTensorSpec` declares the shape/dtype/name of a tensor a model
consumes or produces, plus data-sourcing metadata (optionality, sequence-ness,
on-disk image encoding, multi-dataset routing, varlen padding).  Every other
layer — parsing, preprocessing, serving signatures, placeholder/fixture
generation — is derived from structures of these specs.

Behavioral reference: tensor2robot/utils/tensorspec_utils.py:41-279
(ExtendedTensorSpec).  This implementation is JAX-native: dtypes are numpy
dtypes (including ml_dtypes.bfloat16), and a spec lowers directly to a
`jax.ShapeDtypeStruct` for tracing/export.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

# Image encodings we can decode from serialized byte features.
_VALID_DATA_FORMATS = frozenset(["jpeg", "png", "JPEG", "PNG"])


def canonical_dtype(dtype: Any) -> np.dtype:
    """Normalizes any dtype-like (str, np.dtype, jnp dtype) to np.dtype.

    bfloat16 is represented via ml_dtypes (what `jnp.bfloat16` aliases), so
    `canonical_dtype('bfloat16') == jnp.bfloat16` holds.
    """
    if isinstance(dtype, str) and dtype == "bfloat16":
        return np.dtype(jnp.bfloat16)
    return np.dtype(dtype)


def is_floating(dtype: Any) -> bool:
    return jnp.issubdtype(canonical_dtype(dtype), np.floating)


@dataclasses.dataclass(frozen=True)
class ExtendedTensorSpec:
    """A tensor contract: shape (without batch dim), dtype, and metadata.

    Attributes:
      shape: Tensor shape *excluding* the batch dimension. Entries may be
        ``None`` for dimensions only known at runtime (e.g. sequence length).
      dtype: Element dtype (numpy dtype; bfloat16 supported).
      name: The feature key used to look the tensor up in serialized examples
        and feed dicts. Distinct from the *path* a spec occupies inside a
        TensorSpecStruct (see README "name vs path" duality).
      is_optional: Optional tensors may be absent from inputs; validation
        drops them rather than failing, and the TPU dtype-policy wrapper
        strips them from infeed.
      is_sequence: If True the feature is parsed from the feature_lists of a
        SequenceExample (variable leading time dimension).
      is_extracted: Marks a spec as already extracted from raw data (internal
        bookkeeping used by preprocessors operating on parsed tensors).
      data_format: 'jpeg'/'png' if the on-disk representation is an encoded
        image string that must be decoded to this spec's shape.
      dataset_key: Routes the feature to a named dataset when reading from
        multiple zipped datasets at once ('' = the default dataset).
      varlen_default_value: If set, the feature is parsed as a variable-length
        list and padded (with this value) or clipped to the spec shape.
    """

    shape: Tuple[Optional[int], ...]
    dtype: np.dtype
    name: Optional[str] = None
    is_optional: bool = False
    is_sequence: bool = False
    is_extracted: bool = False
    data_format: Optional[str] = None
    dataset_key: str = ""
    varlen_default_value: Optional[float] = None

    def __post_init__(self):
        # Normalize shape: allow ints, np ints, None; scalars via () or int.
        raw = self.shape
        if raw is None:
            raw = ()
        if isinstance(raw, (int, np.integer)):
            raw = (int(raw),)
        shape = tuple(None if d is None else int(d) for d in raw)
        object.__setattr__(self, "shape", shape)
        object.__setattr__(self, "dtype", canonical_dtype(self.dtype))
        if self.data_format is not None and self.data_format not in _VALID_DATA_FORMATS:
            raise ValueError(
                f"data_format must be one of {sorted(_VALID_DATA_FORMATS)}, "
                f"got {self.data_format!r}"
            )
        if self.varlen_default_value is not None:
            # Varlen features are flat lists on disk; we require rank-1 spec
            # shapes with a concrete length so pad-or-clip semantics are
            # unambiguous (the reference additionally allowed images; images
            # are routed via data_format).
            if self.data_format is None and (
                len(shape) != 1 or shape[0] is None
            ):
                raise ValueError(
                    "varlen_default_value requires a rank-1 shape with a "
                    f"concrete length (or an image data_format); got {shape}"
                )

    # -- constructors ---------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: "ExtendedTensorSpec", **overrides) -> "ExtendedTensorSpec":
        """Copy `spec`, overriding any subset of fields.

        Accepts plain specs from other systems as long as they expose
        shape/dtype (duck-typed), mirroring tensorspec_utils.from_spec.
        """
        base = dict(
            shape=tuple(spec.shape) if spec.shape is not None else (),
            dtype=spec.dtype,
            name=getattr(spec, "name", None),
            is_optional=getattr(spec, "is_optional", False),
            is_sequence=getattr(spec, "is_sequence", False),
            is_extracted=getattr(spec, "is_extracted", False),
            data_format=getattr(spec, "data_format", None),
            dataset_key=getattr(spec, "dataset_key", ""),
            varlen_default_value=getattr(spec, "varlen_default_value", None),
        )
        base.update(overrides)
        return cls(**base)

    @classmethod
    def from_tensor(cls, tensor: Any, name: Optional[str] = None) -> "ExtendedTensorSpec":
        """Builds a spec describing an ndarray/jax.Array (batch dim excluded).

        The first dimension of `tensor` is treated as the batch dimension and
        dropped, matching how specs are declared batch-free everywhere else.
        """
        arr = np.asarray(tensor) if not isinstance(tensor, jax.Array) else tensor
        if arr.ndim == 0:
            raise ValueError("Cannot infer a batched spec from a scalar tensor.")
        return cls(shape=tuple(arr.shape[1:]), dtype=arr.dtype, name=name)

    # -- conversions ----------------------------------------------------------

    def to_shape_dtype_struct(
        self, batch_size: Optional[int] = None
    ) -> jax.ShapeDtypeStruct:
        """Lowers to jax.ShapeDtypeStruct, optionally prepending a batch dim.

        Unknown (None) dims are not representable in XLA static shapes; they
        must be resolved (via batch_size or spec rewriting) before tracing.
        """
        shape = self.shape
        if any(d is None for d in shape):
            raise ValueError(
                f"Spec {self.name!r} has unknown dims {shape}; resolve them "
                "before lowering to a static ShapeDtypeStruct."
            )
        if batch_size is not None:
            shape = (batch_size,) + shape
        return jax.ShapeDtypeStruct(shape, self.dtype)

    # -- equality: shape + dtype only (reference tensorspec_utils.py:262-264) --

    def __eq__(self, other: Any) -> bool:
        if not hasattr(other, "shape") or not hasattr(other, "dtype"):
            return NotImplemented
        return tuple(self.shape) == tuple(other.shape) and canonical_dtype(
            self.dtype
        ) == canonical_dtype(other.dtype)

    def __hash__(self) -> int:
        return hash((tuple(self.shape), str(self.dtype)))

    def __repr__(self) -> str:  # compact, test-friendly
        fields = [f"shape={self.shape}", f"dtype={np.dtype(self.dtype).name}"]
        if self.name is not None:
            fields.append(f"name={self.name!r}")
        for flag in ("is_optional", "is_sequence", "is_extracted"):
            if getattr(self, flag):
                fields.append(f"{flag}=True")
        if self.data_format:
            fields.append(f"data_format={self.data_format!r}")
        if self.dataset_key:
            fields.append(f"dataset_key={self.dataset_key!r}")
        if self.varlen_default_value is not None:
            fields.append(f"varlen_default_value={self.varlen_default_value}")
        return f"ExtendedTensorSpec({', '.join(fields)})"


TensorSpec = ExtendedTensorSpec  # Convenience alias.

SpecOrTensor = Union[ExtendedTensorSpec, np.ndarray, jax.Array]


def is_leaf(value: Any) -> bool:
    """True for values that terminate a spec/tensor structure."""
    return isinstance(
        value, (ExtendedTensorSpec, np.ndarray, jax.Array, np.number, bytes, str)
    ) or np.isscalar(value)
