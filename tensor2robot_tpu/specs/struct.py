"""TensorSpecStruct: an ordered mapping that is simultaneously flat and
hierarchical.

The flat view is a dict with '/'-separated path keys ('train/state'); the
hierarchical view is attribute access (`struct.train.state`) returning *live*
sub-views backed by the same storage — mutation through a view writes through
to the root.  It is the universal container for both specs and tensors
throughout the framework.

Behavioral reference: tensor2robot/utils/tensorspec_utils.py:303-683 and the
observable contract documented in the reference README ("Working with Tensor
Specifications").  Registered as a JAX pytree so batches packed into a struct
flow directly through jit/pjit/vmap.
"""

from __future__ import annotations

import collections
from collections import abc as cabc
from typing import Any, Iterator, Optional, Tuple

import jax


class TensorSpecStruct(cabc.MutableMapping):
    """Ordered flat mapping with live hierarchical attribute views.

    Invariants:
      * Keys are non-empty '/'-separated paths; a path is either a leaf or a
        prefix of deeper leaves, never both (collision-checked on insert).
      * A view created by attribute access shares storage with its root;
        `keys()`/`items()` on the view are relative to the view's prefix.
      * Assigning a mapping to an attribute copies its items under the
        attribute's prefix; assigning an *empty* mapping is forbidden.
    """

    __slots__ = ("_storage", "_prefix")

    def __init__(self, *args, **kwargs):
        object.__setattr__(self, "_storage", collections.OrderedDict())
        object.__setattr__(self, "_prefix", "")
        init = collections.OrderedDict(*args, **kwargs)
        for key, value in init.items():
            self[key] = value

    # -- view construction ----------------------------------------------------

    @classmethod
    def _view(cls, storage, prefix: str) -> "TensorSpecStruct":
        view = cls.__new__(cls)
        object.__setattr__(view, "_storage", storage)
        object.__setattr__(view, "_prefix", prefix)
        return view

    def _abs(self, key: str) -> str:
        if not isinstance(key, str):
            raise KeyError(f"Keys must be non-empty strings, got {key!r}")
        key = key.strip("/")
        if not key:
            raise KeyError("Keys must be non-empty strings")
        return f"{self._prefix}{key}" if not self._prefix else f"{self._prefix}/{key}"

    # -- MutableMapping interface (flat, prefix-relative) ---------------------

    def __getitem__(self, key: str) -> Any:
        abs_key = self._abs(key)
        if abs_key in self._storage:
            return self._storage[abs_key]
        # A path prefix resolves to a sub-view (so struct['train'] works
        # symmetrically with struct.train).
        sub_prefix = abs_key + "/"
        if any(k.startswith(sub_prefix) for k in self._storage):
            return TensorSpecStruct._view(self._storage, abs_key)
        raise KeyError(key)

    def __setitem__(self, key: str, value: Any) -> None:
        abs_key = self._abs(key)
        if isinstance(value, (TensorSpecStruct, cabc.Mapping)):
            items = list(value.items())
            if not items:
                raise ValueError(
                    f"Cannot assign an empty mapping to {key!r}; build the "
                    "sub-struct first, then assign it (see README pattern)."
                )
            for sub_key, sub_value in items:
                self[f"{key}/{sub_key}"] = sub_value
            return
        self._check_collision(abs_key)
        self._storage[abs_key] = value

    def __delitem__(self, key: str) -> None:
        abs_key = self._abs(key)
        if abs_key in self._storage:
            del self._storage[abs_key]
            return
        sub_prefix = abs_key + "/"
        sub_keys = [k for k in self._storage if k.startswith(sub_prefix)]
        if not sub_keys:
            raise KeyError(key)
        for k in sub_keys:
            del self._storage[k]

    def __iter__(self) -> Iterator[str]:
        if not self._prefix:
            yield from list(self._storage)
            return
        prefix = self._prefix + "/"
        for k in list(self._storage):
            if k.startswith(prefix):
                yield k[len(prefix):]

    def __len__(self) -> int:
        return sum(1 for _ in self)

    def __contains__(self, key: object) -> bool:
        try:
            abs_key = self._abs(key)  # type: ignore[arg-type]
        except KeyError:
            return False
        if abs_key in self._storage:
            return True
        sub_prefix = abs_key + "/"
        return any(k.startswith(sub_prefix) for k in self._storage)

    # -- hierarchical (attribute) interface -----------------------------------

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self[name]
        except KeyError:
            raise AttributeError(
                f"TensorSpecStruct has no key or sub-structure {name!r}; "
                f"available: {list(self)}"
            ) from None

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        self[name] = value

    def __delattr__(self, name: str) -> None:
        if name.startswith("_"):
            object.__delattr__(self, name)
            return
        try:
            del self[name]
        except KeyError:
            raise AttributeError(name) from None

    # -- helpers --------------------------------------------------------------

    def _check_collision(self, abs_key: str) -> None:
        """A path may not be both a leaf and a prefix of deeper leaves."""
        sub_prefix = abs_key + "/"
        if any(k.startswith(sub_prefix) for k in self._storage):
            raise ValueError(
                f"Key {abs_key!r} already exists as a sub-structure; cannot "
                "overwrite it with a leaf."
            )
        parts = abs_key.split("/")
        for i in range(1, len(parts)):
            ancestor = "/".join(parts[:i])
            if ancestor in self._storage:
                raise ValueError(
                    f"Key {abs_key!r} collides with existing leaf {ancestor!r}."
                )

    def to_dict(self) -> "collections.OrderedDict[str, Any]":
        """Flat OrderedDict copy (prefix-relative keys)."""
        return collections.OrderedDict(self.items())

    def to_hierarchical_dict(self) -> dict:
        """Nested plain-dict copy."""
        out: dict = {}
        for key, value in self.items():
            parts = key.split("/")
            node = out
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = value
        return out

    @classmethod
    def from_serialized_dict(cls, flat: cabc.Mapping) -> "TensorSpecStruct":
        return cls(flat)

    def copy(self) -> "TensorSpecStruct":
        """Shallow copy materializing this view into a fresh root struct."""
        fresh = TensorSpecStruct()
        for key, value in self.items():
            fresh[key] = value
        return fresh

    def __repr__(self) -> str:
        inner = ", ".join(f"{k!r}: {v!r}" for k, v in self.items())
        prefix = f", prefix={self._prefix!r}" if self._prefix else ""
        return f"TensorSpecStruct({{{inner}}}{prefix})"

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, cabc.Mapping):
            if list(self.keys()) != list(other.keys()):
                return False
            for k in self:
                if not _leaves_equal(self[k], other[k]):
                    return False
            return True
        return NotImplemented

    def __ne__(self, other: Any) -> bool:
        result = self.__eq__(other)
        return result if result is NotImplemented else not result

    __hash__ = None  # type: ignore[assignment]


def _leaves_equal(a: Any, b: Any) -> bool:
    try:
        import numpy as np

        if hasattr(a, "shape") and hasattr(a, "dtype") and not hasattr(a, "is_optional"):
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        return bool(a == b)
    except Exception:
        return False


# -- JAX pytree registration --------------------------------------------------
# Leaves in key order; keys as aux data. Views flatten to their subtree only
# and unflatten to a fresh root (views are an access pattern, not identity).


def _tss_flatten(struct: TensorSpecStruct):
    keys = tuple(struct.keys())
    children = tuple(struct[k] for k in keys)
    return children, keys


def _tss_unflatten(keys, children) -> TensorSpecStruct:
    out = TensorSpecStruct()
    for key, child in zip(keys, children):
        out[key] = child
    return out


jax.tree_util.register_pytree_node(TensorSpecStruct, _tss_flatten, _tss_unflatten)
