"""Spec algebra: flatten / validate / pack / cast / fixture generation.

These are the operations every layer of the framework composes:
  * `flatten_spec_structure` normalizes any hierarchical structure (dicts,
    (named)tuples, lists, TensorSpecStruct) into a flat TensorSpecStruct.
  * `validate_and_flatten` / `validate_and_pack` check that a structure of
    tensors conforms to a structure of specs and return the flat / packed
    form — the gate at every model and preprocessor boundary.
  * dtype-policy casts (float32 <-> bfloat16) implement the TPU infeed policy.
  * random/constant numpy makers generate spec-conforming fixtures, the basis
    of serving example-args and all unit tests.

Behavioral reference: tensor2robot/utils/tensorspec_utils.py:685-1682.
"""

from __future__ import annotations

import collections
from collections import abc as cabc
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.specs.spec import ExtendedTensorSpec, canonical_dtype, is_leaf
from tensor2robot_tpu.specs.struct import TensorSpecStruct

SpecStructure = Union[TensorSpecStruct, cabc.Mapping, tuple, list]


# -- flattening ---------------------------------------------------------------


def _is_namedtuple(value: Any) -> bool:
    return isinstance(value, tuple) and hasattr(value, "_fields")


def flatten_spec_structure(structure: Any) -> TensorSpecStruct:
    """Flattens any hierarchical spec/tensor structure to path-keyed form.

    Supports dict, OrderedDict, TensorSpecStruct, namedtuple, tuple and list
    containers (tuples/lists use their index as the path component).  Leaf
    name collisions — two leaves whose specs share a `name` but disagree on
    shape/dtype — are rejected (reference :1463-1529).
    """
    flat = TensorSpecStruct()
    _flatten_into(flat, "", structure)
    _check_name_collisions(flat)
    return flat


def _flatten_into(flat: TensorSpecStruct, prefix: str, value: Any) -> None:
    if value is None:
        return
    if is_leaf(value):
        if not prefix:
            raise ValueError("Cannot flatten a bare leaf; wrap it in a container.")
        flat[prefix] = value
        return
    if _is_namedtuple(value):
        items = [(f, getattr(value, f)) for f in value._fields]
    elif isinstance(value, cabc.Mapping):
        items = list(value.items())
    elif isinstance(value, (tuple, list)):
        items = [(str(i), v) for i, v in enumerate(value)]
    else:
        raise ValueError(
            f"Unsupported structure element of type {type(value)!r} at "
            f"{prefix or '<root>'!r}"
        )
    for key, sub_value in items:
        if sub_value is None:
            continue
        sub_prefix = f"{prefix}/{key}" if prefix else str(key)
        _flatten_into(flat, sub_prefix, sub_value)


def _check_name_collisions(flat: TensorSpecStruct) -> None:
    by_name: Dict[str, ExtendedTensorSpec] = {}
    for _, spec in flat.items():
        if not isinstance(spec, ExtendedTensorSpec) or spec.name is None:
            continue
        ref = by_name.get(spec.name)
        if ref is None:
            by_name[spec.name] = spec
        elif ref != spec:  # spec equality = shape + dtype
            raise ValueError(
                f"Name collision: two specs named {spec.name!r} disagree on "
                f"shape/dtype ({ref} vs {spec})."
            )


# -- validation ---------------------------------------------------------------


def _static_dim(d):
    """Concrete dims -> int; None and symbolic dims (jax.export shape
    polymorphism) -> None wildcard, so batch-polymorphic tracing validates."""
    if d is None or isinstance(d, int):
        return d
    try:
        return int(d)
    except Exception:  # noqa: BLE001 — symbolic dims raise jax-internal types
        return None


def _shapes_compatible(
    spec_shape: Tuple[Optional[int], ...],
    tensor_shape: Tuple[Optional[int], ...],
    ignore_batch: bool,
) -> bool:
    if ignore_batch:
        # The tensor carries a leading batch dim absent from the spec.
        if len(tensor_shape) != len(spec_shape) + 1:
            return False
        tensor_shape = tensor_shape[1:]
    elif len(tensor_shape) != len(spec_shape):
        return False
    # None on either side is a wildcard (unknown dim).
    return all(
        s is None or t is None or s == t for s, t in zip(spec_shape, tensor_shape)
    )


def assert_equal_spec_or_tensor(spec: ExtendedTensorSpec, tensor: Any, ignore_batch: bool = False) -> None:
    """Raises ValueError unless `tensor` (or a second spec) conforms to `spec`.

    When comparing spec-to-spec, neither side carries a batch dim, so exact
    (wildcard-aware) shape match is required regardless of ignore_batch.
    """
    if not isinstance(tensor, ExtendedTensorSpec) and not hasattr(tensor, "shape"):
        # Python scalars / bytes / str are admissible structure leaves; view
        # them through numpy so conformance is reported as a ValueError, not
        # an AttributeError.
        tensor = np.asarray(tensor)
    tensor_shape = tuple(_static_dim(d) for d in tuple(tensor.shape))
    spec_shape = tuple(spec.shape)
    if isinstance(tensor, ExtendedTensorSpec):
        ok = _shapes_compatible(spec_shape, tensor_shape, ignore_batch=False)
    else:
        if spec.is_sequence:
            # Parsed sequence tensors carry a leading time dim in addition to
            # the (optional) batch dim: (b, T, *spec.shape).
            spec_shape = (None,) + spec_shape
        ok = _shapes_compatible(spec_shape, tensor_shape, ignore_batch)
    if not ok:
        raise ValueError(
            f"Shape mismatch for {spec.name!r}: spec {spec_shape} vs tensor "
            f"{tensor_shape} (ignore_batch={ignore_batch})."
        )
    if canonical_dtype(tensor.dtype) != canonical_dtype(spec.dtype):
        raise ValueError(
            f"Dtype mismatch for {spec.name!r}: spec {np.dtype(spec.dtype)} "
            f"vs tensor {np.dtype(tensor.dtype)}."
        )


def assert_equal(
    expected: SpecStructure, actual: SpecStructure, ignore_batch: bool = False
) -> None:
    """Structural + per-leaf equality of two spec/tensor structures."""
    flat_expected = flatten_spec_structure(expected)
    flat_actual = flatten_spec_structure(actual)
    if set(flat_expected.keys()) != set(flat_actual.keys()):
        raise ValueError(
            "Structures differ: expected keys "
            f"{sorted(flat_expected.keys())} vs {sorted(flat_actual.keys())}"
        )
    for key, spec in flat_expected.items():
        if isinstance(spec, ExtendedTensorSpec):
            assert_equal_spec_or_tensor(spec, flat_actual[key], ignore_batch)


def assert_required(
    expected_specs: SpecStructure,
    actual: SpecStructure,
    ignore_batch: bool = False,
) -> None:
    """Like assert_equal but tolerates absence of optional specs
    (reference :1169)."""
    flat_specs = flatten_spec_structure(expected_specs)
    flat_actual = flatten_spec_structure(actual)
    for key, spec in flat_specs.items():
        if key not in flat_actual:
            if isinstance(spec, ExtendedTensorSpec) and spec.is_optional:
                continue
            raise ValueError(f"Required tensor {key!r} missing from structure.")
        assert_equal_spec_or_tensor(spec, flat_actual[key], ignore_batch)
    # Tensors beyond the declared specs are tolerated (and dropped by the
    # pack/flatten callers), matching the reference's assert_required
    # semantics: pipelines may carry auxiliary tensors past a narrower spec.


def validate_and_flatten(
    expected_spec: SpecStructure,
    actual_tensors_or_spec: SpecStructure,
    ignore_batch: bool = False,
) -> TensorSpecStruct:
    """Validates then returns the flat view of `actual_tensors_or_spec`,
    restricted to the keys the spec declares (extras are dropped)."""
    flat_spec = flatten_spec_structure(expected_spec)
    flat_actual = flatten_spec_structure(actual_tensors_or_spec)
    assert_required(flat_spec, flat_actual, ignore_batch)
    out = TensorSpecStruct()
    for key in flat_spec.keys():
        if key in flat_actual:
            out[key] = flat_actual[key]
    return out


def validate_and_pack(
    expected_spec: SpecStructure,
    actual_tensors_or_spec: SpecStructure,
    ignore_batch: bool = False,
) -> TensorSpecStruct:
    """Validates `actual` against the spec and packs it into the spec's
    hierarchy (a TensorSpecStruct mirroring the expected paths)."""
    flat_spec = flatten_spec_structure(expected_spec)
    flat_actual = flatten_spec_structure(actual_tensors_or_spec)
    assert_required(flat_spec, flat_actual, ignore_batch)
    packed = TensorSpecStruct()
    for key in flat_spec.keys():
        if key in flat_actual:
            packed[key] = flat_actual[key]
    return packed


# -- copying / filtering / rewriting -----------------------------------------


def copy_tensorspec(
    structure: SpecStructure,
    batch_size: Optional[int] = None,
    prefix: str = "",
) -> TensorSpecStruct:
    """Deep-copies a spec structure, optionally prefixing every spec *name*.

    Note the name-vs-path duality: `prefix` lands on the feature `name`
    (used for serialized-data lookup), while the returned struct keeps the
    original relative paths; callers attach it at whatever path they choose.
    batch_size, if given, is prepended to every spec's shape (used when
    episode/task structure makes the per-element batch explicit).
    """
    flat = flatten_spec_structure(structure)
    out = TensorSpecStruct()
    for key, spec in flat.items():
        if not isinstance(spec, ExtendedTensorSpec):
            out[key] = spec
            continue
        name = spec.name if spec.name is not None else key
        if prefix:
            name = f"{prefix}/{name}"
        shape = spec.shape
        if batch_size is not None:
            # batch_size=-1 prepends a wildcard dim (the reference's
            # make_placeholders(batch_size=-1) "unknown batch" semantics).
            leading = None if batch_size == -1 else batch_size
            shape = (leading,) + tuple(shape)
        out[key] = ExtendedTensorSpec.from_spec(spec, name=name, shape=shape)
    return out


def filter_required_flat_tensor_spec(structure: SpecStructure) -> TensorSpecStruct:
    """Drops optional specs (reference :1532)."""
    flat = flatten_spec_structure(structure)
    out = TensorSpecStruct()
    for key, spec in flat.items():
        if isinstance(spec, ExtendedTensorSpec) and spec.is_optional:
            continue
        out[key] = spec
    return out


def filter_spec_structure_by_dataset(
    structure: SpecStructure, dataset_key: str
) -> TensorSpecStruct:
    """Keeps only specs routed to `dataset_key` (reference :1291)."""
    flat = flatten_spec_structure(structure)
    out = TensorSpecStruct()
    for key, spec in flat.items():
        if isinstance(spec, ExtendedTensorSpec) and spec.dataset_key == dataset_key:
            out[key] = spec
    return out


def dataset_keys(structure: SpecStructure) -> Tuple[str, ...]:
    """All distinct dataset keys present, in first-appearance order."""
    seen = collections.OrderedDict()
    for _, spec in flatten_spec_structure(structure).items():
        if isinstance(spec, ExtendedTensorSpec):
            seen.setdefault(spec.dataset_key, None)
    return tuple(seen.keys())


def add_sequence_length_specs(structure: SpecStructure) -> TensorSpecStruct:
    """For every sequence spec 'x', appends an int64 scalar spec 'x_length'
    carrying the true (pre-padding) sequence length (reference :1280)."""
    flat = flatten_spec_structure(structure).copy()
    for key, spec in list(flat.items()):
        if isinstance(spec, ExtendedTensorSpec) and spec.is_sequence:
            length_key = f"{key}_length"
            name = (spec.name or key) + "_length"
            flat[length_key] = ExtendedTensorSpec(
                shape=(), dtype=np.int64, name=name, dataset_key=spec.dataset_key
            )
    return flat


def replace_dtype(
    structure: SpecStructure,
    from_dtype: Any,
    to_dtype: Any,
) -> TensorSpecStruct:
    """Returns a copy with every spec of `from_dtype` re-declared as
    `to_dtype` — the basis of the bfloat16 infeed policy."""
    src, dst = canonical_dtype(from_dtype), canonical_dtype(to_dtype)
    flat = flatten_spec_structure(structure)
    out = TensorSpecStruct()
    for key, spec in flat.items():
        if isinstance(spec, ExtendedTensorSpec) and canonical_dtype(spec.dtype) == src:
            out[key] = ExtendedTensorSpec.from_spec(spec, dtype=dst)
        else:
            out[key] = spec
    return out


def cast_float32_to_bfloat16(structure: SpecStructure) -> TensorSpecStruct:
    return replace_dtype(structure, np.float32, jnp.bfloat16)


def cast_bfloat16_to_float32(structure: SpecStructure) -> TensorSpecStruct:
    return replace_dtype(structure, jnp.bfloat16, np.float32)


def cast_tensors(tensors: SpecStructure, from_dtype: Any, to_dtype: Any) -> TensorSpecStruct:
    """Casts every tensor leaf of `from_dtype` to `to_dtype`."""
    src = canonical_dtype(from_dtype)
    dst = canonical_dtype(to_dtype)
    flat = flatten_spec_structure(tensors)
    out = TensorSpecStruct()
    for key, value in flat.items():
        if hasattr(value, "dtype") and canonical_dtype(value.dtype) == src:
            if isinstance(value, np.ndarray):
                out[key] = value.astype(dst)
            else:
                out[key] = jnp.asarray(value, dtype=dst)
        else:
            out[key] = value
    return out


# -- pad/clip -----------------------------------------------------------------


def pad_or_clip_tensor_to_spec_shape(tensor: np.ndarray, spec: ExtendedTensorSpec) -> np.ndarray:
    """Pads (with varlen_default_value) or clips a parsed varlen tensor to the
    spec's static shape along the first axis (reference :1631-1682)."""
    target = int(spec.shape[0])
    value = spec.varlen_default_value
    if value is None:
        value = 0
    tensor = np.asarray(tensor)
    n = tensor.shape[0]
    if n > target:
        return tensor[:target]
    if n < target:
        pad = np.full((target - n,) + tensor.shape[1:], value, dtype=tensor.dtype)
        return np.concatenate([tensor, pad], axis=0)
    return tensor


# -- fixture / example-args generation ---------------------------------------


def _resolve_shape(
    spec: ExtendedTensorSpec, batch_size: Optional[int], sequence_length: int
) -> Tuple[int, ...]:
    shape = tuple(sequence_length if d is None else d for d in spec.shape)
    if spec.is_sequence:
        shape = (sequence_length,) + shape
    if batch_size is not None:
        shape = (batch_size,) + shape
    return shape


def make_random_numpy(
    structure: SpecStructure,
    batch_size: Optional[int] = 2,
    sequence_length: int = 3,
    seed: int = 0,
) -> TensorSpecStruct:
    """Spec-conforming random numpy tensors (reference :847-920).

    Images get uint8-ish ranges; floats U[0,1); ints U[0,10).
    """
    rng = np.random.RandomState(seed)
    flat = flatten_spec_structure(structure)
    out = TensorSpecStruct()
    for key, spec in flat.items():
        if not isinstance(spec, ExtendedTensorSpec):
            continue
        shape = _resolve_shape(spec, batch_size, sequence_length)
        dtype = canonical_dtype(spec.dtype)
        if jnp.issubdtype(dtype, np.floating):
            value = rng.rand(*shape).astype(dtype)
        elif dtype == np.dtype(np.uint8):
            value = rng.randint(0, 256, size=shape, dtype=np.uint8)
        elif jnp.issubdtype(dtype, np.integer):
            value = rng.randint(0, 10, size=shape).astype(dtype)
        elif dtype == np.dtype(bool):
            value = rng.rand(*shape) > 0.5
        else:
            raise ValueError(f"Unsupported random dtype {dtype} for {key!r}")
        out[key] = value
    return out


def make_constant_numpy(
    structure: SpecStructure,
    constant_value: float = 0.0,
    batch_size: Optional[int] = 2,
    sequence_length: int = 3,
) -> TensorSpecStruct:
    """Spec-conforming constant numpy tensors (reference :847-886)."""
    flat = flatten_spec_structure(structure)
    out = TensorSpecStruct()
    for key, spec in flat.items():
        if not isinstance(spec, ExtendedTensorSpec):
            continue
        shape = _resolve_shape(spec, batch_size, sequence_length)
        out[key] = np.full(shape, constant_value, dtype=canonical_dtype(spec.dtype))
    return out


def make_example_args(
    structure: SpecStructure,
    batch_size: Optional[int] = 1,
    sequence_length: int = 3,
) -> TensorSpecStruct:
    """jax.ShapeDtypeStruct leaves for tracing/export — the JAX-native
    equivalent of the reference's `make_placeholders` (:783-814)."""
    flat = flatten_spec_structure(structure)
    out = TensorSpecStruct()
    for key, spec in flat.items():
        if not isinstance(spec, ExtendedTensorSpec):
            continue
        shape = _resolve_shape(spec, batch_size, sequence_length)
        out[key] = jax.ShapeDtypeStruct(shape, canonical_dtype(spec.dtype))
    return out


make_placeholders = make_example_args  # API-parity alias.


# -- feed mapping -------------------------------------------------------------


def map_feed_dict(
    spec_structure: SpecStructure,
    numpy_inputs: cabc.Mapping,
    ignore_batch: bool = True,
) -> Dict[str, np.ndarray]:
    """Validated spec-name -> numpy mapping for feeding serving functions.

    Looks each required spec's *name* up in `numpy_inputs` (falling back to
    the path key), validates shape/dtype, and returns {name: array}
    (reference map_feed_dict :923-1010).
    """
    flat = flatten_spec_structure(spec_structure)
    feed: Dict[str, np.ndarray] = {}
    for key, spec in flat.items():
        if not isinstance(spec, ExtendedTensorSpec):
            continue
        name = spec.name or key
        if name in numpy_inputs:
            value = numpy_inputs[name]
        elif key in numpy_inputs:
            value = numpy_inputs[key]
        elif spec.is_optional:
            continue
        else:
            raise ValueError(
                f"Missing input for required spec {name!r} (path {key!r}); "
                f"got keys {sorted(numpy_inputs.keys())}"
            )
        value = np.asarray(value)
        target = canonical_dtype(spec.dtype)
        if value.dtype != target:
            # Feeds are host-side: permit only value-preserving casts (safe
            # per numpy) plus float64->float32 narrowing, the common case for
            # Python-float feeds. Anything lossy (float->int, int64->uint8)
            # must fail validation rather than silently truncate.
            if np.can_cast(value.dtype, target, casting="safe") or (
                value.dtype == np.float64 and jnp.issubdtype(target, np.floating)
            ):
                value = value.astype(target)
        assert_equal_spec_or_tensor(spec, value, ignore_batch=ignore_batch)
        feed[name] = value
    return feed
