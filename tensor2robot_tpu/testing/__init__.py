"""Deterministic test instrumentation (fault injection lives here).

Nothing in this package may be imported by production modules except
through the narrow `chaos.maybe_fire(site)` hooks, which are inert (a
counter bump and a None return) unless a `T2R_CHAOS` plan is active.
"""
