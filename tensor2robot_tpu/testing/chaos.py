"""Deterministic fault injection: seeded, flag-gated chaos plans.

The fleet layer's failure handling (serving/router.py retry/hedge/
circuit-break, train/ crash-consistent restore) is only trustworthy if
the failures it survives are *reproducible*. Wall-clock fault injection
("kill a replica after 3 seconds") makes every red run a debugging
seance; this module injects faults at **named sites by occurrence
count**, so a failing test replays bit-for-bit.

A plan is a semicolon-separated list of clauses:

    [scope/]site:occurrence:action[:arg]

  * `site`    — the name a production hook passes to `maybe_fire()`
                (e.g. `predict`, `reply`, `save`, `restore`).
  * `occurrence` — 1-based count of `maybe_fire(site)` calls in this
                process (within the matching scope) at which the fault
                fires. Each clause fires at most once.
  * `action`  — what happens (see table).
  * `scope`   — optional; when set, the clause is inert unless the
                process declared the same scope via `set_scope()`
                (replica processes declare `r<index>`, replay shards
                `s<k>`) OR the production hook passed the same
                call-site scope via `maybe_fire(site, scope=...)` —
                the multi-tenant gateway passes tenant scopes `t<i>`,
                so one clause can target ONE tenant's admissions in a
                process shared by every tenant. Call-scoped clauses
                count occurrences PER SCOPE: `t1/admit:3:raise` fires
                at tenant t1's third admission, not the process's
                third.

Actions:

  * `kill` / `sigkill` — SIGKILL this process, right here. No cleanup
    handlers run: this is the real crash the recovery path must survive.
  * `delay:<ms>` / `hang:<ms>` — sleep for `ms` milliseconds at the
    site (straggler/stall injection; bounded by the plan, so tests stay
    deterministic and inside the tier-1 time budget).
  * `corrupt` — returns the fault to the caller, which applies the
    corruption it is testing (e.g. the replica loop flips a byte in an
    already-checksummed reply).
  * `raise` — raises `ChaosFault` at the site (exception-path testing).
  * `flake:<N>` — raises `ChaosFault` at occurrences `occurrence`
    through `occurrence + N - 1` of the site, then never again: the
    site fails its first N visits (from the clause's start point) and
    succeeds afterwards. This is the *recovery* fixture — retry/backoff
    paths (router re-dispatch, actor reconnects, replay re-appends) are
    only proven by a fault that eventually clears, not by one that
    fails forever. Unlike every other action, a flake clause fires up
    to N times.

Network fault actions (the replay fabric's transport sites pass a
link-endpoint name as `maybe_fire(site, peer=...)` — the SENDER passes
the remote shard's scope at `net_send`, the RECEIVER passes its own
scope at `net_recv`, since it cannot know who is calling; so
`partition:s1` cuts frames *to* s1 when installed sender-side and
frames s1 *hears* when installed in s1's own process):

  * `drop` — returns the fault to the caller, which discards the frame
    (the sender skips the write; the receiver ignores the request). The
    peer perceives a timeout — the lost-datagram fault.
  * `slow:<ms>` — sleeps at the site: link latency injection. Identical
    machinery to `delay`, named separately so network plans read as
    network plans.
  * `partition:<peers>` — a PERSISTENT link cut: from occurrence N
    onward, every visit of the site whose `peer` is in the
    `+`-separated peer list (e.g. `partition:s1` or `partition:s1+s2`,
    matching the shard scopes `s<k>`) fires as a drop. Unlike every
    single-shot action (and like `flake`), a partition clause keeps
    firing — a partition heals when the plan is replaced
    (`configure(...)`/`reset()`), not by itself.

The plan comes from the `T2R_CHAOS` env flag (declared in flags.py; the
env route is what reaches spawned replica/trainer processes), or
in-process via `configure()` for unit tests. Counters are per-process
and monotonic; `reset()` re-arms everything (tests only).

Example — kill replica 0 on its 3rd predict and SIGKILL a trainer in
its 2nd checkpoint-save window:

    T2R_CHAOS="r0/predict:3:kill;save:2:sigkill"
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

from tensor2robot_tpu import flags as t2r_flags

__all__ = [
    "ChaosFault",
    "ChaosPredictor",
    "Clause",
    "parse_plan",
    "configure",
    "set_scope",
    "get_scope",
    "active",
    "maybe_fire",
    "fired",
    "counters",
    "reset",
]

_KNOWN_ACTIONS = (
    "kill", "sigkill", "delay", "hang", "corrupt", "raise", "flake",
    "drop", "slow", "partition",
)
# Injected stalls are test instrumentation: cap them so a typo'd plan
# cannot park the tier-1 suite (the fault model is a *straggler*, and
# 5 s is already far beyond every router timeout under test).
_MAX_DELAY_MS = 5000.0


class ChaosFault(RuntimeError):
    """Raised at a site by a `raise` clause (and the base for plan errors)."""


@dataclasses.dataclass(frozen=True)
class Clause:
    """One parsed fault: fire `action` at the Nth visit of `site`
    (for `flake`, at visits N .. N + flake_n - 1; for `partition`, at
    every visit from N on whose peer is in `peers`)."""

    site: str
    occurrence: int
    action: str
    arg_ms: Optional[float] = None
    scope: Optional[str] = None
    flake_n: Optional[int] = None
    peers: Optional[Tuple[str, ...]] = None

    def describe(self) -> str:
        prefix = f"{self.scope}/" if self.scope else ""
        if self.arg_ms is not None:
            suffix = f":{self.arg_ms:g}"
        elif self.flake_n is not None:
            suffix = f":{self.flake_n}"
        elif self.peers is not None:
            suffix = f":{'+'.join(self.peers)}"
        else:
            suffix = ""
        return f"{prefix}{self.site}:{self.occurrence}:{self.action}{suffix}"

    def matches(self, count: int) -> bool:
        if self.action == "flake":
            return (
                self.occurrence <= count < self.occurrence + (self.flake_n or 0)
            )
        if self.action == "partition":
            return count >= self.occurrence
        return self.occurrence == count


def parse_plan(spec: Optional[str]) -> Tuple[Clause, ...]:
    """Parses a plan string; raises ValueError with the offending clause
    on any malformation — a chaos typo must fail the test run loudly,
    not silently inject nothing."""
    if spec is None or not spec.strip():
        return ()
    clauses: List[Clause] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        scope = None
        body = raw
        if "/" in body:
            scope, body = body.split("/", 1)
            scope = scope.strip()
            if not scope:
                raise ValueError(f"chaos clause {raw!r}: empty scope")
        parts = [p.strip() for p in body.split(":")]
        if len(parts) not in (3, 4):
            raise ValueError(
                f"chaos clause {raw!r}: expected "
                "[scope/]site:occurrence:action[:arg]"
            )
        site, occurrence_s, action = parts[0], parts[1], parts[2]
        if not site:
            raise ValueError(f"chaos clause {raw!r}: empty site")
        try:
            occurrence = int(occurrence_s)
        except ValueError as err:
            raise ValueError(
                f"chaos clause {raw!r}: occurrence must be an int"
            ) from err
        if occurrence < 1:
            raise ValueError(
                f"chaos clause {raw!r}: occurrence is 1-based (got "
                f"{occurrence})"
            )
        if action not in _KNOWN_ACTIONS:
            raise ValueError(
                f"chaos clause {raw!r}: unknown action {action!r} "
                f"(known: {', '.join(_KNOWN_ACTIONS)})"
            )
        arg_ms = None
        flake_n = None
        peers = None
        if action in ("delay", "hang", "slow"):
            if len(parts) != 4:
                raise ValueError(
                    f"chaos clause {raw!r}: {action} needs a millisecond "
                    "argument"
                )
            try:
                arg_ms = float(parts[3])
            except ValueError as err:
                raise ValueError(
                    f"chaos clause {raw!r}: bad delay {parts[3]!r}"
                ) from err
            if not 0 <= arg_ms <= _MAX_DELAY_MS:
                raise ValueError(
                    f"chaos clause {raw!r}: delay must be in "
                    f"[0, {_MAX_DELAY_MS:g}] ms"
                )
        elif action == "flake":
            if len(parts) != 4:
                raise ValueError(
                    f"chaos clause {raw!r}: flake needs a failure count "
                    "(flake:<N> fails the first N visits, then succeeds)"
                )
            try:
                flake_n = int(parts[3])
            except ValueError as err:
                raise ValueError(
                    f"chaos clause {raw!r}: bad flake count {parts[3]!r}"
                ) from err
            if flake_n < 1:
                raise ValueError(
                    f"chaos clause {raw!r}: flake count must be >= 1 "
                    f"(got {flake_n})"
                )
        elif action == "partition":
            if len(parts) != 4 or not parts[3]:
                raise ValueError(
                    f"chaos clause {raw!r}: partition needs a '+'-separated "
                    "peer list (partition:<peer>[+<peer>...], e.g. "
                    "partition:s1+s2)"
                )
            peers = tuple(p.strip() for p in parts[3].split("+"))
            if any(not p for p in peers):
                raise ValueError(
                    f"chaos clause {raw!r}: empty peer in partition list"
                )
        elif len(parts) == 4:
            raise ValueError(
                f"chaos clause {raw!r}: {action} takes no argument"
            )
        clauses.append(
            Clause(site, occurrence, action, arg_ms, scope, flake_n, peers)
        )
    return tuple(clauses)


# -- per-process state ---------------------------------------------------------

_lock = threading.Lock()
_plan: Optional[Tuple[Clause, ...]] = None  # None = not yet loaded from env
_scope: Optional[str] = None
_counters: Dict[str, int] = {}
_fired: List[str] = []


def _load_plan() -> Tuple[Clause, ...]:
    global _plan
    if _plan is None:
        _plan = parse_plan(t2r_flags.get_str("T2R_CHAOS"))
    return _plan


def configure(spec: Optional[str]) -> None:
    """Installs a plan in-process (unit tests). Resets counters. To reach
    a *spawned* process instead, write the T2R_CHAOS env flag (via
    flags.write_env or a replica spec's env overrides)."""
    global _plan
    with _lock:
        _plan = parse_plan(spec)
        _counters.clear()
        _fired.clear()


def set_scope(scope: Optional[str]) -> None:
    """Declares this process's clause scope (replica main sets `r<i>`)."""
    global _scope
    with _lock:
        _scope = scope


def get_scope() -> Optional[str]:
    return _scope


def active() -> bool:
    """True when a non-empty plan is installed (cheap enough to gate log
    lines; maybe_fire() is self-gating either way)."""
    with _lock:
        return bool(_load_plan())


def reset() -> None:
    """Clears plan/scope/counters and re-arms env loading (tests only)."""
    global _plan, _scope
    with _lock:
        _plan = None
        _scope = None
        _counters.clear()
        _fired.clear()


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def fired() -> List[str]:
    """Descriptions of clauses that have fired in this process, in order."""
    with _lock:
        return list(_fired)


def maybe_fire(
    site: str,
    peer: Optional[str] = None,
    scope: Optional[str] = None,
) -> Optional[Clause]:
    """Production hook: bumps the site counter and fires any matching
    clause. Returns the fired Clause for caller-applied actions
    (`corrupt`, `drop`, `partition`), after sleeping for
    `delay`/`hang`/`slow`, never for `kill` (the process is gone), or
    None when nothing matched.

    `peer` names the remote end of a link site (transport hooks pass
    the shard scope they are talking to): `partition` clauses only
    match when the peer is in their list; every other action ignores
    it.

    `scope` names a CALL-SITE scope for sites shared by many logical
    actors in one process — the gateway passes the tenant scope
    (`t<i>`) at its `admit`/`coalesce` sites. A clause whose scope
    equals the call scope matches against a per-(site, scope)
    occurrence counter, so `t1/admit:3:raise` means tenant t1's third
    admission; unscoped clauses and clauses matching the PROCESS scope
    keep counting process-wide visits exactly as before.

    Sleeps and kills happen OUTSIDE the module lock: a hung site must
    not serialize other threads' (non-firing) hooks behind it.
    """
    with _lock:
        plan = _load_plan()
        if not plan:
            return None
        count = _counters.get(site, 0) + 1
        _counters[site] = count
        scoped_count: Optional[int] = None
        if scope is not None:
            scoped_key = f"{site}@{scope}"
            scoped_count = _counters.get(scoped_key, 0) + 1
            _counters[scoped_key] = scoped_count
        hit: Optional[Clause] = None
        for clause in plan:
            if clause.site != site:
                continue
            if clause.scope is not None and clause.scope == scope:
                # Call-scoped clause: occurrences count per scope.
                effective = scoped_count if scoped_count is not None else count
            elif clause.scope is None or clause.scope == _scope:
                effective = count
            else:
                continue
            if not clause.matches(effective):
                continue
            if clause.action == "partition" and (
                peer is None or peer not in (clause.peers or ())
            ):
                continue
            hit = clause
            hit_visit = effective
            description = clause.describe()
            # A partition fires on every matching visit; record it once
            # so the fired log stays bounded and readable.
            if clause.action != "partition" or description not in _fired:
                _fired.append(description)
            break
    if hit is None:
        return None
    if hit.action in ("kill", "sigkill"):
        os.kill(os.getpid(), signal.SIGKILL)
        # Unreachable on POSIX; keep a hard stop in case the signal is
        # briefly pending on an alternate thread.
        time.sleep(60)
        raise ChaosFault(f"chaos kill at {hit.describe()} did not land")
    if hit.action in ("delay", "hang", "slow"):
        time.sleep((hit.arg_ms or 0.0) / 1e3)
        return hit
    if hit.action == "raise":
        raise ChaosFault(f"injected fault at {hit.describe()}")
    if hit.action == "flake":
        raise ChaosFault(
            f"injected flake at {hit.describe()} (visit {hit_visit} of "
            f"{site}; succeeds from visit "
            f"{hit.occurrence + (hit.flake_n or 0)})"
        )
    return hit  # corrupt/drop/partition: caller applies it


class ChaosPredictor:
    """Delegating predictor wrapper that fires the `predict` site before
    every compute call — the hook point for replica-side straggler
    (`delay`), crash (`kill`), and exception (`raise`) injection. Inert
    (one dict lookup) without an active plan; replica factories install
    it unconditionally so a chaos plan needs no code changes to reach a
    live replica's compute path."""

    def __init__(self, inner):
        self._inner = inner

    def predict(self, features):
        maybe_fire("predict")
        return self._inner.predict(features)

    def predict_versioned(self, features):
        maybe_fire("predict")
        return self._inner.predict_versioned(features)

    def __getattr__(self, name):
        return getattr(self._inner, name)

