"""Runtime lock sanitizer: the dynamic half of the lock-discipline pass.

`analysis/concurrency.py` proves what it can from the AST; this module
watches what actually happens. Behind `T2R_LOCK_SANITIZER`, the
threaded modules create their locks through the factory seam below
(`make_lock` / `make_rlock` / `make_condition`) instead of calling
`threading.*` directly. With the flag OFF (the default) the factories
return the plain `threading` primitives — bitwise identical behavior,
zero overhead. With it ON they return instrumented wrappers that:

* record per-thread acquisition stacks and maintain a global
  acquisition-order graph keyed by the same `(Class, attr)` lock
  identity the static pass uses — an edge A->B means "B was acquired
  while A was held", anywhere, by any thread;

* detect lock-order cycles the moment the closing edge is observed
  (lockdep's trick: a cycle in the ORDER graph is a deadlock that some
  interleaving can hit, so it fires deterministically even when this
  run's timing never actually deadlocks), reporting both acquisition
  stacks;

* enforce a per-lock hold-time budget (`T2R_LOCK_HOLD_BUDGET_MS`): a
  critical section held past the budget records a typed violation —
  a report, never a kill. Locks that legitimately bracket long work
  (single-flight model loads, the XLA dispatch-order lock) opt out
  with `budget_ms=0` at the creation site, which keeps the exemption
  grep-able like the lint allow-decorators;

* detect blocking-call-under-lock dynamically: a patched `time.sleep`
  hook (installed only while the sanitizer is on) and untimed
  `Condition.wait` while OTHER sanitized locks are held both record
  typed violations — this is how a chaos `delay` clause landing inside
  a critical section becomes a visible finding instead of silent tail
  latency.

The chaos suites run with the sanitizer enabled, so every tier-1 chaos
run doubles as a deadlock hunt; `dump_report()` writes a deterministic
acquisition-order artifact (sorted edges, repo-relative `path:line`
frames, no wall-clock fields in the graph) so a cycle reproduces like
a corpus crash.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time as _time
import traceback
from typing import Dict, List, Optional, Tuple

from tensor2robot_tpu import flags as t2r_flags

__all__ = [
    "make_lock",
    "make_rlock",
    "make_condition",
    "enabled",
    "report",
    "violations",
    "dump_report",
    "load_report",
    "reset",
]

_OWN_FILE = os.path.abspath(__file__)
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(_OWN_FILE)))

# Violation kinds (the typed report vocabulary).
ORDER_CYCLE = "order-cycle"
HOLD_BUDGET = "hold-budget"
BLOCKING_UNDER_LOCK = "blocking-under-lock"

# -- global sanitizer state ----------------------------------------------------

_state_lock = threading.Lock()
# (held_name, acquired_name) -> first-observed {"stack": [...], "thread": str}
_edges: Dict[Tuple[str, str], Dict] = {}
_violations: List[Dict] = []
_tls = threading.local()

_real_sleep = _time.sleep
_hook_installed = False


def enabled() -> bool:
    return t2r_flags.get_bool("T2R_LOCK_SANITIZER")


def _stack(skip: int = 2, limit: int = 12) -> List[str]:
    """Repo-relative `path:line:func` frames, innermost last. The
    sanitizer's own frames are dropped — a report points at the
    acquisition SITE, not the instrumentation; frames outside the repo
    are kept by basename so artifacts stay stable across checkouts."""
    del skip  # superseded by the own-file filter below
    frames = traceback.extract_stack()[:-1]
    out = []
    for f in frames:
        path = f.filename
        if os.path.abspath(path) == _OWN_FILE:
            continue
        out.append(f"{_rel(path)}:{f.lineno}:{f.name}")
    return out[-limit:]


_rel_cache: Dict[str, str] = {}


def _rel(path: str) -> str:
    rel = _rel_cache.get(path)
    if rel is None:
        rel = os.path.relpath(path, _REPO_ROOT)
        if rel.startswith(".."):
            rel = os.path.basename(path)
        _rel_cache[path] = rel
    return rel


def _site() -> List[str]:
    """The single nearest non-locksmith frame, as a one-element stack.

    Full `_stack()` extraction is too slow for every acquisition (it
    would perturb the timing-sensitive suites the sanitizer rides
    along with); the steady state pays one frame walk, and the rare
    events — a first-seen edge, a violation — pay for a full stack."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return []
    return [f"{_rel(f.f_code.co_filename)}:{f.f_lineno}:{f.f_code.co_name}"]


def _held_stack() -> List:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


class _Held:
    __slots__ = ("name", "t0", "frames", "count", "budget_ms")

    def __init__(self, name: str, frames: List[str], budget_ms: Optional[int]):
        self.name = name
        self.t0 = _time.monotonic()
        self.frames = frames
        self.count = 1
        self.budget_ms = budget_ms


def _path_exists(src: str, dst: str) -> Optional[List[Tuple[str, str]]]:
    """DFS in the order graph; returns the edge path src->...->dst or
    None. Called under _state_lock."""
    stack: List[Tuple[str, List[Tuple[str, str]]]] = [(src, [])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for (a, b) in _edges:
            if a != node or b in seen and b != dst:
                continue
            step = path + [(a, b)]
            if b == dst:
                return step
            seen.add(b)
            stack.append((b, step))
    return None


def _record_violation(kind: str, detail: Dict) -> None:
    with _state_lock:
        _violations.append({"kind": kind, **detail})


def _note_acquired(name: str, budget_ms: Optional[int]) -> None:
    held = _held_stack()
    frames: Optional[List[str]] = None
    for h in held:
        if h.name == name:
            continue
        edge = (h.name, name)
        # Unlocked membership probe: dict reads are GIL-atomic and a
        # stale miss just falls through to the locked re-check. The
        # steady state (edge already known) records nothing and
        # captures no stack.
        if edge in _edges:
            continue
        if frames is None:
            frames = _stack(skip=3)
        with _state_lock:
            if edge not in _edges:
                # Closing edge check BEFORE inserting: does a path
                # name -> ... -> h.name already exist? Then this
                # acquisition completes a cycle.
                back = _path_exists(name, h.name)
                if back is not None:
                    _violations.append(
                        {
                            "kind": ORDER_CYCLE,
                            "locks": sorted(
                                {name, h.name}
                                | {x for e in back for x in e}
                            ),
                            "edge": list(edge),
                            "stack": frames,
                            "held_stack": list(h.frames),
                            "reverse_path": [list(e) for e in back],
                            "reverse_stacks": {
                                "->".join(e): _edges[e]["stack"]
                                for e in back
                                if e in _edges
                            },
                            "thread": threading.current_thread().name,
                        }
                    )
                _edges[edge] = {
                    "stack": frames,
                    "thread": threading.current_thread().name,
                }
    held.append(_Held(name, frames if frames is not None else _site(), budget_ms))


def _note_released(name: str) -> None:
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i].name == name:
            entry = held.pop(i)
            hold_ms = (_time.monotonic() - entry.t0) * 1e3
            budget = (
                entry.budget_ms
                if entry.budget_ms is not None
                else t2r_flags.get_int("T2R_LOCK_HOLD_BUDGET_MS")
            )
            if budget and hold_ms > budget:
                _record_violation(
                    HOLD_BUDGET,
                    {
                        "lock": name,
                        "hold_ms": round(hold_ms, 3),
                        "budget_ms": budget,
                        "stack": entry.frames,
                        "thread": threading.current_thread().name,
                    },
                )
            return


def _note_blocking(what: str, skip: int = 3) -> None:
    held = _held_stack()
    if not held:
        return
    _record_violation(
        BLOCKING_UNDER_LOCK,
        {
            "call": what,
            "locks": [h.name for h in held],
            "stack": _stack(skip=skip),
            "thread": threading.current_thread().name,
        },
    )


def _hooked_sleep(seconds):
    # Only a finding when a sanitized lock is held by THIS thread.
    if getattr(_tls, "held", None):
        _note_blocking(f"time.sleep({seconds!r})")
    return _real_sleep(seconds)


def _ensure_hook() -> None:
    global _hook_installed
    if not _hook_installed:
        _time.sleep = _hooked_sleep
        _hook_installed = True


def _uninstall_hook() -> None:
    global _hook_installed
    if _hook_installed:
        _time.sleep = _real_sleep
        _hook_installed = False


# -- instrumented primitives ---------------------------------------------------


class _SanLock:
    """Drop-in threading.Lock with acquisition accounting."""

    _reentrant = False

    def __init__(self, name: str, budget_ms: Optional[int]):
        self._name = name
        self._budget_ms = budget_ms
        self._inner = self._make_inner()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._reentrant:
                for h in _held_stack():
                    if h.name is self._name and h.count:
                        h.count += 1
                        return got
            _note_acquired(self._name, self._budget_ms)
        return got

    def release(self) -> None:
        if self._reentrant:
            for h in _held_stack():
                if h.name is self._name and h.count > 1:
                    h.count -= 1
                    self._inner.release()
                    return
        self._inner.release()
        _note_released(self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self._name} {self._inner!r}>"


class _SanRLock(_SanLock):
    """Drop-in threading.RLock; recursion tracked so order/hold
    accounting sees one logical hold. Implements the private Condition
    protocol (`_is_owned`/`_acquire_restore`/`_release_save`) so a
    Condition built over it can fully release around wait()."""

    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        for i, h in enumerate(_held_stack()):
            if h.name is self._name:
                _held_stack().pop(i)
                break
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        _note_acquired(self._name, self._budget_ms)


class _SanCondition:
    """Drop-in threading.Condition over a sanitized RLock. wait()
    releases the underlying lock (so hold-time accounting pauses, as
    it should) and an UNTIMED wait while other sanitized locks are
    held records a blocking-under-lock violation."""

    def __init__(self, name: str, budget_ms: Optional[int]):
        self._name = name
        self._lock = _SanRLock(name, budget_ms)
        self._cond = threading.Condition(self._lock)

    def acquire(self, *args, **kwargs):
        return self._lock.acquire(*args, **kwargs)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self._cond.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._cond.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            others = [
                h.name for h in _held_stack() if h.name is not self._name
            ]
            if others:
                _note_blocking(f"{self._name}.wait() untimed")
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"<_SanCondition {self._name}>"


# -- the factory seam ----------------------------------------------------------


def make_lock(name: str, budget_ms: Optional[int] = None):
    """A Lock named by its static identity (`Class._attr`). Off-path:
    a plain threading.Lock. `budget_ms` overrides the flag budget for
    this lock; 0 = exempt (a designed-long-hold critical section)."""
    if not enabled():
        return threading.Lock()
    _ensure_hook()
    return _SanLock(name, budget_ms)


def make_rlock(name: str, budget_ms: Optional[int] = None):
    if not enabled():
        return threading.RLock()
    _ensure_hook()
    return _SanRLock(name, budget_ms)


def make_condition(name: str, budget_ms: Optional[int] = None):
    if not enabled():
        return threading.Condition()
    _ensure_hook()
    return _SanCondition(name, budget_ms)


# -- report surface ------------------------------------------------------------


def violations(kind: Optional[str] = None) -> List[Dict]:
    with _state_lock:
        out = [dict(v) for v in _violations]
    if kind is not None:
        out = [v for v in out if v["kind"] == kind]
    return out


def report() -> Dict:
    """The full typed report: the acquisition-order graph plus every
    violation, deterministically ordered."""
    with _state_lock:
        edges = [
            {"held": a, "acquired": b, **info}
            for (a, b), info in _edges.items()
        ]
        viols = [dict(v) for v in _violations]
    edges.sort(key=lambda e: (e["held"], e["acquired"]))
    viols.sort(
        key=lambda v: (
            v["kind"],
            json.dumps(
                {k: v[k] for k in v if k not in ("hold_ms", "thread")},
                sort_keys=True,
                default=str,
            ),
        )
    )
    return {
        "schema": "t2r-locksmith-v1",
        "enabled": enabled(),
        "edges": edges,
        "violations": viols,
    }


def dump_report(path: str) -> str:
    """Writes the report artifact (atomic rename, sorted keys) and
    returns `path` — a cycle reproduces like a corpus crash: the
    artifact names both acquisition paths by `path:line:func`."""
    payload = json.dumps(report(), indent=2, sort_keys=True, default=str)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(payload)
    os.replace(tmp, path)
    return path


def load_report(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as fh:
        loaded = json.load(fh)
    if loaded.get("schema") != "t2r-locksmith-v1":
        raise ValueError(
            f"{path}: not a locksmith report (schema "
            f"{loaded.get('schema')!r})"
        )
    return loaded


def reset() -> None:
    """Clears the graph and violations (per-test isolation). The sleep
    hook stays installed while the sanitizer is on; it uninstalls when
    the flag is off."""
    global _edges, _violations
    with _state_lock:
        _edges = {}
        _violations = []
    if not enabled():
        _uninstall_hook()
