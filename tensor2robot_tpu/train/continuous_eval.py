"""Continuous evaluation: a standalone process tailing a trainer's output.

The reference ran eval as its own job ("continuous_eval" mode): loop over
checkpoints_iterator(model_dir), back each checkpoint up against the
trainer's GC, evaluate every named eval dataset, and drive exporters
manually (utils/train_eval.py:584-610; backup machinery :615-683). This is
the learner/eval process topology from the reference README:44-51 — the two
jobs communicate only through the model_dir filesystem.

JAX rebuild: orbax checkpoints are the bus. `wait_for_new_checkpoint` polls
the trainer's checkpoint root; each new step is copied into
`current_eval_checkpoint/` (with retries — the trainer's max_to_keep GC can
delete a version mid-copy), restored onto this process's mesh, evaluated on
every named dataset (per-name metric streams under eval_<name>/), and handed
to the exporters.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import orbax.checkpoint as ocp

from tensor2robot_tpu.models.abstract_model import MODE_EVAL, AbstractT2RModel
from tensor2robot_tpu.train import durability
from tensor2robot_tpu.train.metrics import MetricsWriter
from tensor2robot_tpu.train.train_eval import (
    CompiledModel,
    eval_dir_name,
    maybe_wrap_for_tpu,
    normalize_eval_generators,
    provide_input_generator_with_model_information,
    run_named_evals,
)


def _checkpoint_root(model_dir: str) -> str:
    return os.path.abspath(os.path.join(model_dir, "checkpoints"))


# Steps that already validated durable, per checkpoint root. A durable
# verdict is immutable (the manifest blesses a finalized checkpoint), so
# the poll loop only pays full manifest validation — a json parse plus a
# stat per checkpoint file — once per NEW step instead of for every step
# on every tick; on a network filesystem the difference is a sustained
# metadata storm. Bounded by keep_checkpoint_max per live root.
_DURABLE_SEEN: set = set()


def _committed_steps(checkpoint_root: str) -> List[int]:
    """DURABLE step dirs on disk, newest last. Orbax tmp dirs
    (uncommitted writes) and torn final-named dirs are excluded — this
    is the read-only side of the durability contract (train/durability):
    an eval tail must never copy or restore a torn checkpoint, but it
    also must not quarantine anything, because the trainer writing this
    dir is alive."""
    if not os.path.isdir(checkpoint_root):
        return []
    steps = []
    for entry in os.listdir(checkpoint_root):
        path = os.path.join(checkpoint_root, entry)
        if not (entry.isdigit() and os.path.isdir(path)):
            continue
        key = (checkpoint_root, int(entry))
        if key not in _DURABLE_SEEN:
            if durability.validate_step_dir(path) is not None:
                continue
            _DURABLE_SEEN.add(key)
        steps.append(int(entry))
    return sorted(steps)


def wait_for_new_checkpoint(
    model_dir: str,
    last_step: Optional[int] = None,
    timeout: float = 600.0,
    poll_interval: float = 2.0,
) -> Optional[int]:
    """Blocks until a checkpoint newer than last_step exists; returns its
    step, or None on timeout (reference checkpoints_iterator semantics)."""
    root = _checkpoint_root(model_dir)
    deadline = time.time() + timeout
    while True:
        steps = _committed_steps(root)
        fresh = [s for s in steps if last_step is None or s > last_step]
        if fresh:
            return fresh[-1]
        if time.time() >= deadline:
            return None
        time.sleep(poll_interval)


def backup_checkpoint_for_eval(
    model_dir: str,
    step: int,
    backup_name: str = "current_eval_checkpoint",
    retries: int = 3,
) -> Optional[str]:
    """Copies checkpoint `step` into model_dir/<backup_name>/<step>.

    Returns the backup ROOT (a valid orbax root holding exactly this step),
    or None if the checkpoint vanished (GC won the race) — callers then move
    on to a newer step. Reference create_backup_checkpoint_for_eval
    (utils/train_eval.py:615-683) with its retry/tmp-file behavior.
    """
    source = os.path.join(_checkpoint_root(model_dir), str(step))
    backup_root = os.path.join(os.path.abspath(model_dir), backup_name)
    dest = os.path.join(backup_root, str(step))
    for attempt in range(retries):
        if not os.path.isdir(source):
            return None
        # One backup at a time: drop older backups first (the eval job is
        # the only consumer).
        if os.path.isdir(backup_root):
            for entry in os.listdir(backup_root):
                if entry != str(step):
                    shutil.rmtree(
                        os.path.join(backup_root, entry), ignore_errors=True
                    )
        tmp = dest + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        try:
            shutil.copytree(source, tmp)
            # The copy only counts if the source survived it (otherwise some
            # files may be partial deletions).
            if not os.path.isdir(source):
                shutil.rmtree(tmp, ignore_errors=True)
                return None
            if os.path.isdir(dest):
                shutil.rmtree(dest, ignore_errors=True)
            os.replace(tmp, dest)
            return backup_root
        except (OSError, shutil.Error):
            shutil.rmtree(tmp, ignore_errors=True)
            time.sleep(0.5 * (attempt + 1))
    return None


def abstract_state_template(compiled: CompiledModel, example_batch):
    """ShapeDtypeStruct template of the TrainState (with shardings) — built
    once; checkpoint restores reuse it across the tail loop."""
    state = compiled.init_state(jax.random.PRNGKey(0), example_batch)
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state,
    )


def restore_state_from_backup(
    backup_root: str, step: int, compiled: CompiledModel, example_batch=None,
    abstract=None,
):
    """Restores a TrainState from a backed-up checkpoint root."""
    if abstract is None:
        abstract = abstract_state_template(compiled, example_batch)
    manager = ocp.CheckpointManager(backup_root)
    try:
        return manager.restore(step, args=ocp.args.StandardRestore(abstract))
    finally:
        manager.close()


def continuous_eval(
    t2r_model: AbstractT2RModel,
    model_dir: str,
    input_generator_eval: Union[Any, Dict[str, Any], None] = None,
    eval_steps: Optional[int] = 100,
    max_train_steps: Optional[int] = None,
    create_exporters_fn: Optional[Callable] = None,
    timeout: float = 600.0,
    poll_interval: float = 2.0,
    mesh=None,
    use_ema_for_eval: Optional[bool] = None,
    use_backup: bool = True,
) -> Dict[str, float]:
    """Tails model_dir checkpoints, evaluating (and exporting) each one.

    Runs until the evaluated step reaches max_train_steps or no new
    checkpoint appears within `timeout`. Returns the last eval metrics.
    `input_generator_eval` may be a {name: generator} map — each name gets
    its own metric stream under model_dir/eval_<name>/ (multi-eval parity).
    """
    model = maybe_wrap_for_tpu(t2r_model)
    compiled = CompiledModel(model, mesh=mesh, donate_state=False)
    if use_ema_for_eval is None:
        use_ema_for_eval = getattr(model, "use_avg_model_params", False)

    eval_generators = normalize_eval_generators(input_generator_eval)
    if not eval_generators:
        raise ValueError("continuous_eval requires at least one eval generator.")
    for generator in eval_generators.values():
        provide_input_generator_with_model_information(
            generator, model, MODE_EVAL
        )
    first_name = next(iter(eval_generators))
    example_batch = next(
        iter(eval_generators[first_name].create_dataset(MODE_EVAL))
    )

    writers = {
        name: MetricsWriter(
            os.path.join(model_dir, eval_dir_name(name)), use_tensorboard=False
        )
        for name in eval_generators
    }
    exporters = (
        create_exporters_fn(model) if create_exporters_fn is not None else []
    )

    abstract = abstract_state_template(compiled, example_batch)
    last_step: Optional[int] = None
    last_metrics: Dict[str, float] = {}
    try:
        while True:
            step = wait_for_new_checkpoint(
                model_dir, last_step, timeout=timeout, poll_interval=poll_interval
            )
            if step is None:
                break  # trainer stopped producing checkpoints
            if use_backup:
                restore_root = backup_checkpoint_for_eval(model_dir, step)
                if restore_root is None:
                    last_step = step  # GC raced us; wait for a newer one
                    continue
            else:
                restore_root = _checkpoint_root(model_dir)
            state = restore_state_from_backup(
                restore_root, step, compiled, abstract=abstract
            )
            metrics = run_named_evals(
                compiled,
                state,
                eval_generators,
                eval_steps=eval_steps,
                use_ema=use_ema_for_eval,
                step=step,
                writers=writers,
            )
            for exporter in exporters:
                exporter.maybe_export(
                    step=step,
                    state=state,
                    eval_metrics=metrics,
                    compiled=compiled,
                    model_dir=model_dir,
                )
            last_metrics = metrics
            last_step = step
            if max_train_steps is not None and step >= max_train_steps:
                break
    finally:
        for writer in writers.values():
            writer.close()
    return last_metrics
