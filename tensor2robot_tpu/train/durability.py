"""Crash-consistent checkpoint durability: detect, skip, and quarantine
torn checkpoints; publish a verifiable durability manifest per step.

Why orbax's atomic rename is not enough
---------------------------------------
Orbax writes each step into `<step>.orbax-checkpoint-tmp-<ts>/` and
commits it with one atomic rename to `<step>/`, so a SIGKILL mid-save
normally leaves only a tmp dir that `CheckpointManager` excludes from
its step listing. But the *final-named* form carries no integrity
evidence: a partially copied backup, a crashed filesystem without
fsync, or a half-deleted GC victim all present as `<step>/` with files
missing — and `CheckpointManager.latest_step()` happily returns such a
directory (verified against orbax 0.7.0: an empty `4/` wins
`latest_step` and the restore dies with an unrelated error). A trainer
that trusts `latest_step()` therefore cannot promise "resume from the
last durable checkpoint".

The barrier
-----------
After orbax *finalizes* step S (rename done — saves are serialized, so
issuing save S+1 or calling `wait_until_finished()` is the barrier),
the trainer writes `<step>/t2r_durable.json`: a manifest of every file
in the checkpoint with its size, written tmp-then-`os.replace` so the
manifest itself is atomic. Validation is then:

  * name carries the orbax tmp suffix        -> torn (uncommitted)
  * manifest present, inventory verifies     -> durable
  * manifest present, any file missing/short -> torn
  * no manifest: structural fallback — the orbax step metadata and the
    item's `_METADATA`/`manifest.ocdbt` must exist (covers the window
    between orbax's rename and our manifest write, and checkpoints
    written before this module existed)

Writers (the trainer owns `checkpoints/`) additionally *quarantine*
torn directories into `<model_dir>/checkpoints.quarantine/` at startup:
leaving a torn `<step>/` in place would collide with the re-save of
that step after the replayed window. Readers (continuous_eval, serving)
only ever *skip* — a tmp dir they see may be a live write.

Chaos hooks: `train_eval.checkpoint_and_eval` fires the `save` site
right after the async save is issued (a `kill` clause there is the
SIGKILL-mid-orbax-save fault) and `restore_or_init_state` fires
`restore` before reading (slow-restore / exception injection).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import List, Optional, Tuple

MANIFEST_NAME = "t2r_durable.json"
QUARANTINE_DIRNAME = "checkpoints.quarantine"
# Mirrors orbax.checkpoint.utils.TMP_DIR_SUFFIX (0.7.0); inlined so
# validation stays importable without pulling in orbax (readers such as
# fleet health probes run in slim processes).
_ORBAX_TMP_MARKER = ".orbax-checkpoint-tmp-"
_STEP_METADATA = "_CHECKPOINT_METADATA"


def checkpoint_root(model_dir: str) -> str:
    return os.path.abspath(os.path.join(model_dir, "checkpoints"))


def quarantine_root(model_dir: str) -> str:
    return os.path.abspath(os.path.join(model_dir, QUARANTINE_DIRNAME))


def _inventory(step_dir: str) -> List[Tuple[str, int]]:
    """(relpath, size) for every regular file under step_dir, sorted,
    excluding the manifest itself."""
    entries: List[Tuple[str, int]] = []
    for dirpath, _, filenames in os.walk(step_dir):
        for name in filenames:
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, step_dir)
            if rel == MANIFEST_NAME:
                continue
            entries.append((rel, os.path.getsize(full)))
    entries.sort()
    return entries


def write_manifest(step_dir: str) -> None:
    """Publishes the durability manifest for a FINALIZED step dir.

    Must only be called after the orbax commit barrier for this step
    (save of the next step issued, or wait_until_finished returned);
    writing earlier would bless a checkpoint that is still streaming.
    """
    files = _inventory(step_dir)
    payload = {
        "version": 1,
        "files": [{"path": p, "size": s} for p, s in files],
    }
    tmp = os.path.join(step_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(step_dir, MANIFEST_NAME))


def validate_step_dir(step_dir: str) -> Optional[str]:
    """Returns None when the directory is a durable checkpoint, else a
    human-readable torn-reason. Read-only (safe on live trees)."""
    name = os.path.basename(step_dir.rstrip(os.sep))
    if _ORBAX_TMP_MARKER in name:
        return "orbax tmp dir (uncommitted write)"
    if not os.path.isdir(step_dir):
        return "not a directory"
    manifest_path = os.path.join(step_dir, MANIFEST_NAME)
    if os.path.exists(manifest_path):
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
            declared = manifest["files"]
        except (OSError, ValueError, KeyError) as err:
            return f"unreadable durability manifest: {err}"
        for entry in declared:
            path = os.path.join(step_dir, entry["path"])
            if not os.path.isfile(path):
                return f"manifest file missing: {entry['path']}"
            actual = os.path.getsize(path)
            if actual != entry["size"]:
                return (
                    f"manifest size mismatch: {entry['path']} is {actual} "
                    f"bytes, manifest says {entry['size']}"
                )
        return None
    # No manifest (pre-manifest checkpoint, or crash landed between
    # orbax's rename and the manifest write): structural fallback.
    if not os.path.isfile(os.path.join(step_dir, _STEP_METADATA)):
        return f"no {_STEP_METADATA} (incomplete step directory)"
    items = [
        entry
        for entry in os.listdir(step_dir)
        if os.path.isdir(os.path.join(step_dir, entry))
    ]
    if not items:
        return "no checkpoint items in step directory"
    for item in items:
        item_dir = os.path.join(step_dir, item)
        if not os.path.isfile(os.path.join(item_dir, "_METADATA")):
            return f"item {item!r} missing _METADATA"
    return None


def _step_entries(root: str) -> List[Tuple[int, str]]:
    """(step, dirname) for every final-named step dir under root."""
    if not os.path.isdir(root):
        return []
    out = []
    for entry in os.listdir(root):
        if entry.isdigit() and os.path.isdir(os.path.join(root, entry)):
            out.append((int(entry), entry))
    out.sort()
    return out


def durable_steps(model_dir: str) -> List[int]:
    """Steps under model_dir/checkpoints that validate as durable,
    ascending. Read-only — safe for concurrent readers of a live dir."""
    root = checkpoint_root(model_dir)
    return [
        step
        for step, name in _step_entries(root)
        if validate_step_dir(os.path.join(root, name)) is None
    ]


def latest_durable_step(model_dir: str) -> Optional[int]:
    steps = durable_steps(model_dir)
    return steps[-1] if steps else None


def latest_durable_step_in(manager) -> Optional[int]:
    """Newest step in an orbax CheckpointManager's root that validates
    as DURABLE.

    `manager.latest_step()` trusts directory names: a torn final-named
    dir (partial copy, fsync-less crash) wins it and the restore dies —
    or loads garbage. Walk newest-first, skip anything torn (read-only:
    never quarantines, so concurrent readers are safe on a live dir).

    The manager is duck-typed (`all_steps()` + `directory`) so this
    module stays importable without orbax — serving-side readers
    (checkpoint_predictor) call it from slim processes.
    """
    root = str(manager.directory)
    for step in sorted(manager.all_steps(), reverse=True):
        reason = validate_step_dir(os.path.join(root, str(step)))
        if reason is None:
            return int(step)
        logging.warning(
            "Skipping torn checkpoint %s/%s: %s", root, step, reason
        )
    return None


def sweep_torn_checkpoints(model_dir: str) -> List[Tuple[str, str]]:
    """WRITER-ONLY startup sweep: moves torn step dirs (and stale orbax
    tmp dirs) into model_dir/checkpoints.quarantine/, so a resumed run
    can re-save the replayed steps without colliding with the wreckage.
    Never deletes — the quarantined tree is the crash forensics.

    Returns [(dirname, reason)] for everything quarantined. Must only be
    called by the process that OWNS the checkpoint dir (the trainer,
    before it opens its CheckpointManager): a reader sweeping a live dir
    would quarantine the write in progress.
    """
    root = checkpoint_root(model_dir)
    if not os.path.isdir(root):
        return []
    report: List[Tuple[str, str]] = []
    for entry in sorted(os.listdir(root)):
        path = os.path.join(root, entry)
        if not os.path.isdir(path):
            continue
        if entry.isdigit():
            reason = validate_step_dir(path)
        elif _ORBAX_TMP_MARKER in entry:
            reason = "orbax tmp dir (uncommitted write)"
        else:
            continue  # not checkpoint-shaped; leave it alone
        if reason is None:
            continue
        quarantine = quarantine_root(model_dir)
        os.makedirs(quarantine, exist_ok=True)
        # Monotonic-ish unique destination; collisions only matter for
        # repeated crashes at the same step, where the suffix saves us.
        dest = os.path.join(quarantine, f"{entry}.{int(time.time() * 1e3)}")
        while os.path.exists(dest):
            dest += "x"
        shutil.move(path, dest)
        logging.warning(
            "Quarantined torn checkpoint %s -> %s (%s)", path, dest, reason
        )
        report.append((entry, reason))
    return report


def publish_durable(model_dir: str, step: int) -> bool:
    """Writes the manifest for `step` if its dir exists, validates
    structurally, and does not already carry one. Returns True when a
    manifest is present after the call. Call only past the orbax commit
    barrier for this step."""
    step_dir = os.path.join(checkpoint_root(model_dir), str(step))
    if not os.path.isdir(step_dir):
        return False
    if os.path.exists(os.path.join(step_dir, MANIFEST_NAME)):
        return True
    if validate_step_dir(step_dir) is not None:
        # Structurally torn even though finalized-named: refuse to bless.
        return False
    write_manifest(step_dir)
    return True
