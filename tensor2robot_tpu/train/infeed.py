"""Device infeed: double-buffered transfers + multi-step batch stacking.

The reference hid host->device transfer behind TPUEstimator's infeed queue
(per-host infeed, utils/tfdata.py:38-61) and amortized host round-trips with
TPUConfig.iterations_per_loop (models/abstract_model.py:76-77). The JAX
equivalents here:

  * `device_prefetch` keeps `depth` batches resident on the mesh ahead of
    the consumer. jax.device_put is asynchronous, so enqueueing batch N+1's
    transfer before step N is dispatched overlaps PCIe/ICI transfer with
    compute — the double-buffering the round-1 trainer lacked.
  * `stack_batches` concatenates K host batches along a new leading axis for
    the lax.scan multi-step train loop (iterations_per_loop equivalent):
    one host dispatch drives K device steps.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator, Optional, Sequence

import jax
import numpy as np

from tensor2robot_tpu import flags
from tensor2robot_tpu.parallel import mesh as mesh_lib


def resolve_depth(depth: Optional[int] = None) -> int:
    """Prefetch depth: an explicit argument wins; None reads the central
    T2R_INFEED_DEPTH gate (default 2 = classic double buffering)."""
    if depth is not None:
        return depth
    return flags.get_int("T2R_INFEED_DEPTH")


def device_prefetch(
    batches: Iterator,
    shard_fn: Callable,
    depth: int = 2,
) -> Iterator:
    """Yields device-resident batches, keeping `depth` transfers in flight.

    `shard_fn` is typically CompiledModel.shard_batch. With depth=2 the
    transfer of batch N+1 is enqueued before the consumer dispatches step N;
    because device_put is async the copy runs while the device computes.
    """
    buf: collections.deque = collections.deque()
    it = iter(batches)
    try:
        while len(buf) < depth:
            buf.append(shard_fn(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(shard_fn(next(it)))
        except StopIteration:
            pass
        yield out


def stack_batches(batches: Sequence) -> object:
    """Stacks K host batches leaf-wise along a new leading axis [K, B, ...].

    Each leaf writes straight into its slot of ONE preallocated output
    array — the earlier np.asarray-then-np.stack form materialized every
    leaf twice (a full extra copy of the whole chunk per dispatch, paid
    on the host hot path between device steps).
    """

    def stack(*leaves):
        # np.asarray is a no-copy view for ndarray leaves; the copy this
        # saves is np.stack's gather into a second buffer. Shape/dtype
        # strictness matches np.stack: mismatched shapes raise (instead
        # of broadcasting a short tail batch across the slot) and dtypes
        # promote to the common type (instead of pinning the first
        # leaf's and silently wrapping).
        arrays = [np.asarray(leaf) for leaf in leaves]
        first = arrays[0]
        for arr in arrays[1:]:
            if arr.shape != first.shape:
                raise ValueError(
                    "all input batches must have the same leaf shapes; "
                    f"got {arr.shape} vs {first.shape}"
                )
        out = np.empty(
            (len(arrays),) + first.shape, np.result_type(*arrays)
        )
        for i, arr in enumerate(arrays):
            out[i] = arr
        return out

    return jax.tree_util.tree_map(stack, *batches)


def shard_stacked_batch(stacked, mesh):
    """Places a [K, B, ...] stacked batch: scan axis replicated, batch axis
    (dim 1) split over data×fsdp; non-divisible leaves replicated."""
    sharding = mesh_lib.stacked_batch_sharding(mesh)
    replicated = mesh_lib.replicated(mesh)
    divisor = mesh.shape[mesh_lib.DATA_AXIS] * mesh.shape[mesh_lib.FSDP_AXIS]

    def put(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 2 and shape[1] % divisor == 0:
            return jax.device_put(leaf, sharding)
        return jax.device_put(leaf, replicated)

    return jax.tree_util.tree_map(put, stacked)


