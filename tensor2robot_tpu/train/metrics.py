"""Metrics/event writing: JSONL always, TensorBoard events optionally.

The observability channel replacing TF summaries (reference gated summaries
off on TPU, models/abstract_model.py:873-893; here metrics are scalars
returned from the jitted step — no host transfer happens except on log
steps, so they are TPU-safe by construction).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional


class MetricsWriter:
    """Writes {step, wall_time, metrics...} JSONL; optional TB events."""

    def __init__(self, log_dir: str, filename: str = "metrics.jsonl",
                 use_tensorboard: bool = False):
        os.makedirs(log_dir, exist_ok=True)
        self._path = os.path.join(log_dir, filename)
        self._file = open(self._path, "a")
        self._tb = None
        if use_tensorboard:
            try:
                from flax.metrics import tensorboard  # requires tf

                self._tb = tensorboard.SummaryWriter(log_dir)
            except Exception:
                self._tb = None

    def write(self, step: int, metrics: Dict[str, float]) -> None:
        record = {"step": int(step), "wall_time": time.time()}
        for key, value in metrics.items():
            record[key] = float(value)
        self._file.write(json.dumps(record) + "\n")
        self._file.flush()
        if self._tb is not None:
            for key, value in metrics.items():
                self._tb.scalar(key, float(value), step)
            self._tb.flush()

    def close(self) -> None:
        self._file.close()
        if self._tb is not None:
            self._tb.close()


def read_metrics(log_dir: str, filename: str = "metrics.jsonl"):
    path = os.path.join(log_dir, filename)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
