"""Metrics/event writing: JSONL always, TensorBoard events optionally.

The observability channel replacing TF summaries (reference gated summaries
off on TPU, models/abstract_model.py:873-893; here metrics are scalars
returned from the jitted step — no host transfer happens except on log
steps, so they are TPU-safe by construction).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional


class MetricsWriter:
    """Writes {step, wall_time, metrics...} JSONL; optional TB events."""

    def __init__(self, log_dir: str, filename: str = "metrics.jsonl",
                 use_tensorboard: bool = False):
        os.makedirs(log_dir, exist_ok=True)
        self._path = os.path.join(log_dir, filename)
        self._file = open(self._path, "a")
        self._tb = None
        if use_tensorboard:
            try:
                from flax.metrics import tensorboard  # requires tf

                self._tb = tensorboard.SummaryWriter(log_dir)
            except Exception:
                self._tb = None

    def write(self, step: int, metrics: Dict[str, float]) -> None:
        record = {"step": int(step), "wall_time": time.time()}
        for key, value in metrics.items():
            record[key] = float(value)
        self._file.write(json.dumps(record) + "\n")
        self._file.flush()
        if self._tb is not None:
            for key, value in metrics.items():
                self._tb.scalar(key, float(value), step)
            self._tb.flush()

    def close(self) -> None:
        self._file.close()
        if self._tb is not None:
            self._tb.close()


class DeferredFetch:
    """One-window-deferred device readback.

    The eval loop needs a periodic host sync purely to bound the device
    dispatch queue — but fetching the value it just enqueued serializes
    dispatch behind the newest computation. Pushing the handle here and
    draining the PREVIOUS window's handle instead keeps the queue bounded
    (at most two windows in flight) while the fetched array has had a full
    window to finish: the readback returns immediately instead of
    blocking the host at the dispatch frontier.
    """

    def __init__(self):
        self._pending = None

    def push(self, device_value):
        """Enqueues a device value; returns the PREVIOUSLY pushed value
        fetched to host (None on the first push)."""
        previous, self._pending = self._pending, device_value
        if previous is None:
            return None
        import jax

        return jax.device_get(previous)

    def drain(self):
        """Fetches and clears the pending value (end-of-loop cleanup)."""
        pending, self._pending = self._pending, None
        if pending is None:
            return None
        import jax

        return jax.device_get(pending)


def collective_record(
    bytes_pre: float,
    bytes_post: float,
    wall_ms: Optional[float] = None,
) -> Dict[str, float]:
    """Canonical metric keys for the gradient-collective channel: pre/post
    compression bytes per device-step and (when measured) the collective
    wall-time. Merged into every train log record by train_eval and into
    bench payloads by `bench.py comms`, under the same names."""
    record = {
        "collective/bytes_pre": float(bytes_pre),
        "collective/bytes_post": float(bytes_post),
        "collective/compression": float(bytes_pre) / float(bytes_post),
    }
    if wall_ms is not None:
        record["collective/wall_ms"] = float(wall_ms)
    return record


def read_metrics(log_dir: str, filename: str = "metrics.jsonl"):
    path = os.path.join(log_dir, filename)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
