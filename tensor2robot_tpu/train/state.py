"""TrainState: the complete training snapshot as one pytree.

Holds model variables (params + mutable collections), optimizer state, step,
and — when the model requests moving-average params — an EMA copy. The EMA
replaces the reference's MovingAverageOptimizer + swapping-saver machinery
(models/optimizers.py:133-159): checkpoints persist both raw and averaged
params; export selects the EMA (see export/).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    variables: Dict[str, Any]  # {'params': ..., 'batch_stats': ...}
    opt_state: Any
    ema_params: Optional[Any] = None

    @property
    def params(self):
        return self.variables["params"]

    def export_variables(self, use_ema: bool = False) -> Dict[str, Any]:
        """Variables to serve/export: EMA params when present and requested."""
        if use_ema and self.ema_params is not None:
            out = dict(self.variables)
            out["params"] = self.ema_params
            return out
        return dict(self.variables)


def create_train_state(
    model,
    rng: jax.Array,
    example_features,
    optimizer: optax.GradientTransformation,
) -> TrainState:
    """Initializes variables (with warm-start hook) + optimizer state."""
    variables = model.init_variables(rng, example_features)
    variables = model.maybe_init_from_checkpoint(variables)
    opt_state = optimizer.init(variables["params"])
    ema = (
        jax.tree_util.tree_map(jnp.copy, variables["params"])
        if getattr(model, "use_avg_model_params", False)
        else None
    )
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        variables=variables,
        opt_state=opt_state,
        ema_params=ema,
    )


def update_ema(ema_params, new_params, decay: float):
    return jax.tree_util.tree_map(
        lambda e, p: e * decay + p.astype(e.dtype) * (1.0 - decay),
        ema_params,
        new_params,
    )


def checkpoint_metadata_template(root, step):
    """Abstract restore template read from a checkpoint's OWN metadata,
    with every leaf placed on the local host.

    Restoring with this template makes the read independent of (a) the
    topology the trainer ran on — leaving shardings unset replays the
    checkpoint's sharding file, which cannot be reconstructed on a host
    with a different device count — and (b) the consumer's own guess at
    the saved structure (e.g. which optimizer layout the trainer used).
    Returns a pytree of jax.ShapeDtypeStruct mirroring the on-disk tree.
    """
    import orbax.checkpoint as ocp
    from etils import epath

    meta = ocp.StandardCheckpointHandler().metadata(
        epath.Path(root) / str(step) / "default"
    )
    meta_tree = getattr(meta, "tree", meta)
    host = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
    return jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=host),
        meta_tree,
    )
