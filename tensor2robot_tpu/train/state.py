"""TrainState: the complete training snapshot as one pytree.

Holds model variables (params + mutable collections), optimizer state, step,
and — when the model requests moving-average params — an EMA copy. The EMA
replaces the reference's MovingAverageOptimizer + swapping-saver machinery
(models/optimizers.py:133-159): checkpoints persist both raw and averaged
params; export selects the EMA (see export/).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.struct
import jax
import jax.flatten_util  # registers jax.flatten_util.ravel_pytree
import jax.numpy as jnp
import optax


def _is_flat_ema(ema) -> bool:
    """True when the EMA is stored as one concatenated vector (the
    flatten_optimizer_update regime) rather than a params-shaped tree."""
    return hasattr(ema, "ndim") and ema.ndim == 1


def ema_as_tree(ema_params, params_tree):
    """EMA as a params-shaped tree, whatever the stored layout.

    Every consumer that reads ema_params — live state, restored
    checkpoints (predictors, warm start) — must route through this, not
    use the raw value: a flat-stored EMA (flatten_optimizer_update
    regime) is a single 1-D vector that only this unravel, against the
    matching params structure, turns back into variables. A flat EMA
    longer than the parameter count is the quantized-collective regime's
    block-padded layout (parallel/collectives.FlatShardLayout); the
    zero-gradient tail never moves and is dropped here."""
    if _is_flat_ema(ema_params):
        flat, unravel = jax.flatten_util.ravel_pytree(params_tree)
        if ema_params.shape[0] > flat.shape[0]:
            ema_params = ema_params[: flat.shape[0]]
        return unravel(ema_params)
    return ema_params


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    variables: Dict[str, Any]  # {'params': ..., 'batch_stats': ...}
    opt_state: Any
    ema_params: Optional[Any] = None
    #: Error-feedback residual of the quantized gradient collectives
    #: (parallel/collectives.py): {'grad': [N, padded] (dim 0 sharded over
    #: the data axis — each replica's untransmitted gradient remainder),
    #: 'update': [padded] (sharded — each owner-shard's untransmitted
    #: update remainder)}. None outside the quantized ZeRO-2 regime.
    #: Checkpointed with the state so restarts keep the exact trajectory.
    collective_residual: Optional[Any] = None

    @property
    def params(self):
        return self.variables["params"]

    def export_variables(self, use_ema: bool = False) -> Dict[str, Any]:
        """Variables to serve/export: EMA params when present and requested.

        A flat-stored EMA (one concatenated vector; see update_ema) is
        unraveled here against the live params' structure — export/eval
        is the only place the EMA is ever needed as a tree."""
        if use_ema and self.ema_params is not None:
            out = dict(self.variables)
            out["params"] = ema_as_tree(self.ema_params, self.params)
            return out
        return dict(self.variables)


def create_train_state(
    model,
    rng: jax.Array,
    example_features,
    optimizer: optax.GradientTransformation,
    flat_ema: bool = False,
) -> TrainState:
    """Initializes variables (with warm-start hook) + optimizer state.

    flat_ema stores the EMA as one concatenated vector (see update_ema);
    like optax.flatten it changes the checkpoint layout, so it is only
    set by the flatten_optimizer_update regime."""
    variables = model.init_variables(rng, example_features)
    variables = model.maybe_init_from_checkpoint(variables)
    opt_state = optimizer.init(variables["params"])
    if getattr(model, "use_avg_model_params", False):
        ema = (
            jax.flatten_util.ravel_pytree(variables["params"])[0]
            if flat_ema
            else jax.tree_util.tree_map(jnp.copy, variables["params"])
        )
    else:
        ema = None
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        variables=variables,
        opt_state=opt_state,
        ema_params=ema,
    )


def update_ema(ema_params, new_params, decay: float):
    """One EMA step. Tree-shaped EMA updates leaf-wise; a flat-stored EMA
    (flatten_optimizer_update regime) updates as ONE fused axpy over the
    concatenated parameter vector — the per-leaf form compiles to one
    small kernel per parameter, which on a backend with fixed per-kernel
    latency costs more than the math (same rationale as optax.flatten,
    CompiledModel docstring)."""
    if _is_flat_ema(ema_params):
        flat = jax.flatten_util.ravel_pytree(new_params)[0]
        return ema_params * decay + flat.astype(ema_params.dtype) * (
            1.0 - decay
        )
    return jax.tree_util.tree_map(
        lambda e, p: e * decay + p.astype(e.dtype) * (1.0 - decay),
        ema_params,
        new_params,
    )


def checkpoint_metadata_template(root, step):
    """Abstract restore template read from a checkpoint's OWN metadata,
    with every leaf placed on the local host.

    Restoring with this template makes the read independent of (a) the
    topology the trainer ran on — leaving shardings unset replays the
    checkpoint's sharding file, which cannot be reconstructed on a host
    with a different device count — and (b) the consumer's own guess at
    the saved structure (e.g. which optimizer layout the trainer used).
    Returns a pytree of jax.ShapeDtypeStruct mirroring the on-disk tree.
    """
    import orbax.checkpoint as ocp
    from etils import epath

    meta = ocp.StandardCheckpointHandler().metadata(
        epath.Path(root) / str(step) / "default"
    )
    meta_tree = getattr(meta, "tree", meta)
    host = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
    return jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=host),
        meta_tree,
    )
