"""train_eval_model: the orchestration entry point.

Compiles the model's hooks into pjit train/eval steps over a device mesh,
runs the host loop with checkpointing (orbax), metrics, hooks, periodic
evaluation and exporting. The JAX re-architecture of the reference's
utils/train_eval.py:423-612 (TPUEstimator + train_and_evaluate):

  reference                        | here
  ---------------------------------+----------------------------------------
  TPUT2RModelWrapper auto-wrap     | same decision, same wrapper (:476-479)
  Estimator input_fn               | input generator batch iterator
  model_fn(TRAIN) traced by TF     | jitted train_step over the mesh
  CrossShardOptimizer all-reduce   | psum inserted by GSPMD sharded autodiff
  iterations_per_loop infeed       | host loop w/ async dispatch (XLA queues
                                   | steps; host never blocks except on logs)
  Saver/checkpoint listeners       | orbax CheckpointManager + hook protocol
  train_and_evaluate + exporters   | periodic eval + create_exporters_fn
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading

from tensor2robot_tpu.testing import locksmith
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import jax
import jax.flatten_util  # registers jax.flatten_util.ravel_pytree
import jax.numpy as jnp
import numpy as np
import optax
import orbax.checkpoint as ocp

from tensor2robot_tpu import flags
from tensor2robot_tpu.hooks.golden_values_hook_builder import GOLDEN_PREFIX
from tensor2robot_tpu.hooks.hook_builder import Hook, HookBuilder, HookContext
from tensor2robot_tpu.models.abstract_model import (
    MODE_EVAL,
    MODE_PREDICT,
    MODE_TRAIN,
    AbstractT2RModel,
)
from tensor2robot_tpu.models.tpu_model_wrapper import TPUT2RModelWrapper
from tensor2robot_tpu.parallel import collectives
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel import planner as planner_lib
from tensor2robot_tpu.specs import TensorSpecStruct, make_example_args
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.train import durability, infeed
from tensor2robot_tpu.train.metrics import (
    DeferredFetch,
    MetricsWriter,
    collective_record,
)
from tensor2robot_tpu.train.state import TrainState, create_train_state, update_ema


#: Metric-key prefixes whose values carry a leading batch dimension
#: (concatenated, not averaged, when recombining grad-accum microbatches).
BATCH_CARRYING_METRIC_PREFIXES = (GOLDEN_PREFIX, "per_example/")

#: One process-wide ENQUEUE lock for multi-device (mesh-spanning) jitted
#: programs. XLA runs each device's queue in order; two host threads
#: enqueueing collective programs concurrently can interleave so the
#: device queues disagree on program order — then each program sits at
#: its collective rendezvous waiting for participants queued behind the
#: OTHER program (queue-order inversion: a deadlock, observed between a
#: threaded trainer and an in-process continuous_eval job). Dispatch is
#: asynchronous, so the lock is held for the microseconds of enqueue,
#: never for execution — trainer/eval overlap is preserved; only the
#: ORDER every device sees becomes consistent. Production trainer and
#: eval jobs live in separate processes and never contend here.
_DISPATCH_LOCK = locksmith.make_lock("train_eval._DISPATCH_LOCK", budget_ms=0)


def _serialize_dispatch(fn):
    """Routes calls to a jitted mesh program through _DISPATCH_LOCK; jit
    introspection (`lower`) passes through for AOT/census tests."""

    def locked(*args, **kwargs):
        with _DISPATCH_LOCK:
            return fn(*args, **kwargs)

    locked.lower = fn.lower
    locked.__wrapped__ = fn
    return locked


@jax.jit
def _init_metric_totals(metrics):
    """Eval accumulator seed, f32 (bf16 scalars would saturate — spacing
    2 past 256 — over long eval runs)."""
    return {key: value.astype(jnp.float32) for key, value in metrics.items()}


@jax.jit
def _accumulate_metric_totals(totals, metrics):
    return {
        key: totals[key] + metrics[key].astype(jnp.float32)
        for key in metrics
    }


# The eval accumulation runs on mesh-resident arrays — a multi-device
# program like the steps themselves, so it takes the same enqueue lock.
_init_metric_totals = _serialize_dispatch(_init_metric_totals)
_accumulate_metric_totals = _serialize_dispatch(_accumulate_metric_totals)


def _is_batch_carrying_metric(path) -> bool:
    """True when any key along the metric's tree path declares a
    batch-carrying value via BATCH_CARRYING_METRIC_PREFIXES."""
    for entry in path:
        key = getattr(entry, "key", None)
        if isinstance(key, str) and key.startswith(
            BATCH_CARRYING_METRIC_PREFIXES
        ):
            return True
    return False


def print_specification(model: AbstractT2RModel) -> None:
    """Startup spec dump (reference train_eval.py:72-93)."""
    for mode in (MODE_TRAIN, MODE_EVAL):
        print(f"*** Specifications for mode={mode} ***")
        for name, spec_fn in (
            ("features", model.get_feature_specification),
            ("labels", model.get_label_specification),
        ):
            for key, spec in spec_fn(mode).items():
                print(f"  {name}/{key}: {spec}")


def provide_input_generator_with_model_information(
    input_generator, model: AbstractT2RModel, mode: str
):
    """Binds the model's (preprocessor's) in-specs onto the generator
    (reference :96-127)."""
    input_generator.set_specification_from_model(model, mode)
    return input_generator


def maybe_wrap_for_tpu(model: AbstractT2RModel) -> AbstractT2RModel:
    if model.is_device_tpu and not isinstance(model, TPUT2RModelWrapper):
        return TPUT2RModelWrapper(model)
    return model


def _is_flat_stats(stats) -> bool:
    """True when batch_stats is the fused one-vector form
    (CompiledModel(fuse_batch_stats_update=True) live states)."""
    return getattr(stats, "ndim", None) == 1


def _stats_update_trees(template, new_col):
    """(new_stats, decay) trees in `template`'s structure, pulled from the
    deferred 'batch_stats_new' collection — whose entries mirror the
    batch_stats paths with 'mean'/'var' plus a per-layer 'momentum'."""

    def lookup(path, _leaf):
        node = new_col
        for entry in path:
            node = node[entry.key]
        return node

    def decay(path, leaf):
        node = new_col
        for entry in path[:-1]:
            node = node[entry.key]
        return jnp.broadcast_to(
            node["momentum"], getattr(leaf, "shape", ())
        )

    new_tree = jax.tree_util.tree_map_with_path(lookup, template)
    decay_tree = jax.tree_util.tree_map_with_path(decay, template)
    return new_tree, decay_tree


def _apply_stats_update(old_stats, new_col, flat_template):
    """Batch-norm running-stats EMA from the deferred collection.

    Flat old stats (fused regime): the whole network's update is ONE
    concatenated axpy — ~2 kernels instead of ~2 tiny [C]-vector kernels
    per BN layer (the same fixed-per-kernel-latency rationale as
    optax.flatten; measured shapes in tests/test_train_eval.py's
    kernel-count pin). Tree old stats: per-leaf axpys, the
    flax-equivalent fallback for a non-fused trainer driving a model
    whose deferral switch another CompiledModel enabled. Both forms
    compute momentum*old + (1-momentum)*new per element."""
    if _is_flat_stats(old_stats):
        new_tree, decay_tree = _stats_update_trees(flat_template, new_col)
        flat_new = jax.flatten_util.ravel_pytree(new_tree)[0]
        flat_decay = jax.flatten_util.ravel_pytree(decay_tree)[0]
        return old_stats * flat_decay + flat_new * (1.0 - flat_decay)
    new_tree, decay_tree = _stats_update_trees(old_stats, new_col)
    return jax.tree_util.tree_map(
        lambda o, n, d: o * d + n * (1.0 - d),
        old_stats,
        new_tree,
        decay_tree,
    )


def _batch_labels(batch):
    """The batch's labels subtree, or None for label-less (self-supervised)
    models whose generators emit no 'labels' keys — grasp2vec's empty
    label spec is the in-repo case; preprocessors and model fns already
    accept labels=None."""
    try:
        return batch["labels"]
    except KeyError:
        return None


def _validate_model_matches_plan(model, plan) -> None:
    """A plan can PLACE layouts but cannot retrofit model structure: a
    sequence- or pipeline-parallel plan requires the model BUILT with the
    matching mesh / pipeline stages (plan.model_kwargs()). Without this
    check a mismatch trains silently replicated — the regime degrades to
    'replicated', whose layout audit is green, so nothing else would
    catch it."""
    candidates = [model, getattr(model, "_model", None)]
    candidates = [m for m in candidates if m is not None]
    if plan.pipe > 1:
        stages = next(
            (
                getattr(m, "_pipeline_stages")
                for m in candidates
                if hasattr(m, "_pipeline_stages")
            ),
            None,
        )
        if stages != plan.pipe:
            raise ValueError(
                f"plan {plan.name!r} runs {plan.pipe} pipeline stages but "
                f"the model was built with pipeline_stages={stages}; "
                "construct the model with plan.model_kwargs() (and the "
                "plan's mesh)"
            )
    if plan.sequence > 1:
        model_mesh = next(
            (
                getattr(m, "_mesh")
                for m in candidates
                if getattr(m, "_mesh", None) is not None
            ),
            None,
        )
        seq = (
            dict(model_mesh.shape).get(mesh_lib.SEQUENCE_AXIS, 1)
            if model_mesh is not None
            else None
        )
        if seq != plan.sequence:
            raise ValueError(
                f"plan {plan.name!r} shards the sequence {plan.sequence}-"
                f"way but the model's mesh carries sequence axis {seq}; "
                "construct the model with the plan's mesh "
                "(plan.build_mesh()) so attention actually runs "
                "sequence-parallel"
            )


# -- the measured plan-search probe (planner.measured_rerank's tier 2) --------

#: Monotonic count of train-step compiles paid by measure_plan_candidate.
#: The planner's zero-compile warm-cache contract is audited against this
#: counter (planner.last_search()['probe_compiles'], bench.py plan).
_PLAN_PROBE_COMPILES = 0


def plan_probe_compile_count() -> int:
    return _PLAN_PROBE_COMPILES


def _reset_compile_cache_state() -> None:
    # jax memoizes the persistent compilation cache's enabled state at
    # the first compile; reset_cache() drops the memo so the config
    # flip below actually takes (serving/compile_cache.py documents the
    # latch).
    try:
        from jax._src import compilation_cache as _compilation_cache
    except ImportError:  # pragma: no cover - future jax relayout
        return
    reset = getattr(_compilation_cache, "reset_cache", None)
    if reset is not None:
        reset()


@contextlib.contextmanager
def _plan_probe_compile_cache_bypass():
    """Disables jax's persistent compilation cache around a plan-search
    compile (the export/aot.py build-side discipline): a cache HIT hands
    back an executable with no fresh object code and near-zero compile
    time, which poisons both the timing and the compile counter the
    search ranks and audits with. Restores the prior config — and resets
    the latched cache state again — on the way out."""
    prev_enabled = bool(jax.config.jax_enable_compilation_cache)
    prev_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_enable_compilation_cache", False)
    if prev_dir:
        jax.config.update("jax_compilation_cache_dir", None)
    _reset_compile_cache_state()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev_enabled)
        if prev_dir:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
        _reset_compile_cache_state()


def _executable_memory(executable):
    """compiled.memory_analysis() -> (total per-device bytes, fields).

    The TRUE HBM accounting the analytic estimate is audited against.
    Backends without the analysis (CPU builds, older runtimes) return
    (None, None) — the caller records the analytic estimate unaudited
    rather than failing the probe."""
    try:
        analysis = executable.memory_analysis()
    except Exception as err:  # noqa: BLE001 - backend-optional surface
        return None, {"unavailable": f"{type(err).__name__}: {err}"}
    if analysis is None:
        return None, {"unavailable": "memory_analysis() returned None"}
    fields = {}
    for key in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        value = getattr(analysis, key, None)
        if isinstance(value, (int, float)):
            fields[key] = int(value)
    total = (
        fields.get("argument_size_in_bytes", 0)
        + fields.get("output_size_in_bytes", 0)
        + fields.get("temp_size_in_bytes", 0)
        - fields.get("alias_size_in_bytes", 0)
    )
    return (total if total > 0 else None), (fields or None)


def measure_plan_candidate(
    model,
    plan: planner_lib.ShardingPlan,
    example_batch,
    *,
    steps: int = 3,
    warmup: int = 1,
) -> Dict[str, Any]:
    """Compile-and-measure probe for ONE shortlisted plan: builds the
    plan's mesh and CompiledModel (donated state — the real train-step
    economics), compiles the train step with the persistent compile
    cache bypassed, reads compiled.memory_analysis(), and times `steps`
    real steps after `warmup` (median). Returns a record for the ranked
    table; a plan the model cannot run (pipe/sequence mismatch) or a
    probe failure comes back as {'skipped': reason} — the search skips
    it loudly, it never kills the run."""
    global _PLAN_PROBE_COMPILES
    record: Dict[str, Any] = {"name": plan.name}
    try:
        _validate_model_matches_plan(model, plan)
    except ValueError as err:
        record["skipped"] = str(err)
        return record
    with _plan_probe_compile_cache_bypass():
        try:
            mesh = plan.build_mesh()
            compiled = CompiledModel(
                model, mesh=mesh, donate_state=True, plan=plan
            )
            state = compiled.init_state(jax.random.PRNGKey(0), example_batch)
            rng = jax.random.PRNGKey(1)
            start = time.perf_counter()
            executable = compiled.train_step.lower(
                state, example_batch, rng
            ).compile()
            _PLAN_PROBE_COMPILES += 1
            record["compile_ms"] = (time.perf_counter() - start) * 1e3
        except Exception as err:  # noqa: BLE001 - recorded, search goes on
            record["skipped"] = f"{type(err).__name__}: {err}"
            return record
        memory_total, memory_fields = _executable_memory(executable)
        record["memory_per_device_bytes"] = memory_total
        record["memory_analysis"] = memory_fields
        times_ms: List[float] = []
        try:
            for i in range(warmup + max(steps, 1)):
                start = time.perf_counter()
                state, _ = executable(state, example_batch, rng)
                jax.block_until_ready(state)
                if i >= warmup:
                    times_ms.append((time.perf_counter() - start) * 1e3)
        except Exception as err:  # noqa: BLE001 - recorded, search goes on
            record["skipped"] = f"{type(err).__name__}: {err}"
            return record
    times_ms.sort()
    record["step_time_ms"] = times_ms[len(times_ms) // 2]
    record["steps_timed"] = len(times_ms)
    return record


class CompiledModel:
    """The model's hooks compiled into mesh-placed pure step functions."""

    def __init__(
        self,
        model: AbstractT2RModel,
        mesh=None,
        donate_state: bool = True,
        param_min_shard_size: int = mesh_lib.MIN_WEIGHT_SIZE,
        remat: bool = False,
        grad_accum_steps: int = 1,
        shard_weight_update: bool = False,
        flatten_optimizer_update: bool = False,
        fuse_batch_stats_update: Optional[bool] = None,
        collective_quant: Optional[str] = None,
        collective_block: Optional[int] = None,
        weight_update_axes: Optional[Sequence[str]] = None,
        plan: Optional[planner_lib.ShardingPlan] = None,
    ):
        """Args beyond the model/mesh:

        remat: rematerialize the forward pass under autodiff
          (jax.checkpoint) — activations are recomputed in the backward
          instead of stored, trading ~1/3 more FLOPs for O(depth) less
          HBM; the standard lever when a big batch or long episode
          doesn't fit.
        shard_weight_update: in pure data parallelism, shard optimizer
          moments and the EMA mirror over the data axis (cross-replica
          weight-update sharding, arXiv:2004.13336 / ZeRO-2) — params
          stay replicated for compute while optimizer-state memory drops
          by the data-axis size; GSPMD rewrites the gradient all-reduce
          into reduce-scatter + sharded update + all-gather. Ignored when
          the fsdp/model axes already shard parameters.
        grad_accum_steps: K>1 splits each batch into K microbatches,
          accumulates gradients over them in a lax.scan, and applies ONE
          optimizer update of their mean — the effective batch stays the
          same while peak activation memory drops by ~K. Caveat: batch
          norm computes statistics per MICRObatch (the standard
          grad-accumulation behavior), so BN models are not bit-identical
          to the unaccumulated step.
        flatten_optimizer_update: apply the optimizer on ONE concatenated
          parameter vector (optax.flatten) instead of leaf by leaf. For
          elementwise transforms (Adam & friends) the math is identical,
          but the update compiles to a handful of whole-model fused ops
          instead of ~3 small kernels PER PARAMETER — the round-3 TPU
          profile showed those small per-leaf update kernels costing
          0.9-4 ms each (a 4 ms Adam update on a 28 KB entry-conv kernel)
          on a backend where tiny ops pay a fixed latency. The EMA mirror
          is stored flat in the same regime (one fused axpy per step
          instead of one kernel per parameter; unraveled only at
          export/eval — train/state.py update_ema). Changes the
          opt_state/ema pytree structure (checkpoints are not
          interchangeable with the unflattened layout) and is rejected in
          sharded-param regimes, where moments must follow the parameter
          sharding.
        fuse_batch_stats_update: same per-kernel-latency rationale applied
          to batch-norm running statistics. The LIVE train state stores
          'batch_stats' as ONE concatenated vector; layers.batch_norm
          defers each layer's stats to the 'batch_stats_new' collection
          and the step applies every layer's EMA in one fused axpy
          (~2 kernels) instead of ~2 tiny kernels per BN layer. Train-mode
          forwards never read running stats, so nothing else in the step
          changes. The ON-DISK checkpoint layout is unchanged: saves go
          through persistable_state (tree form) and restores through
          fuse_state, and eval/export unravel on the fly. Defaults to
          flatten_optimizer_update; requires the model's BNs to be
          layers.batch_norm.BatchNorm (the Grasping44 tower is) — a
          plain flax BN under this regime raises at trace time rather
          than silently freezing its stats. Caveat: enabling this sets
          the deferral switch ON THE MODEL OBJECT, so a non-fused
          CompiledModel constructed later over the SAME model instance
          traces the deferred collection too (its train_step then
          applies the per-leaf fallback of _apply_stats_update —
          numerically the same EMA, different fusion). Use separate
          model instances when exact cross-trainer HLO stability
          matters.
        collective_quant / collective_block: wire format for the ZeRO-2
          gradient collectives (parallel/collectives.py). None reads the
          central T2R_COLLECTIVE_QUANT / T2R_COLLECTIVE_BLOCK flags;
          'none' (the default) keeps today's GSPMD-inserted psum
          byte-for-byte. 'fp16'/'int8'/'fp8_e4m3'/'fp8_e5m2' switch the
          shard_weight_update regime to an EXPLICIT shard_map step:
          blockwise-quantized reduce-scatter of gradients + all-gather
          of updates with per-block scales, and an error-feedback
          residual carried in the train state (re-injected next step,
          so the compression bias cancels and convergence is
          preserved). The fp8 formats move the same 1 byte/element as
          int8 but round RELATIVE per value (e4m3 ~2^-4, e5m2 ~2^-3)
          instead of absolute per block. Only engages in
          the pure data-parallel ZeRO-2 regime (shard_weight_update on,
          data axis > 1, all other axes 1) — ignored elsewhere, so the
          env flag can stay set fleet-wide. In this regime optimizer
          state and the EMA mirror live on the flat block-padded
          parameter vector (per-shard elementwise optimizer update —
          Adam & friends; tree-structure-aware transforms like
          global-norm clipping see one shard and are unsupported), and
          per-replica batch-norm statistics average across the data
          axis (the local-BN caveat, same family as grad-accum's
          per-microbatch stats).
        weight_update_axes: replica axes the ZeRO-2 weight update shards
          across (mesh.weight_update_sharding's generalization). None =
          ("data",), byte-for-byte today's layout; a composed 3D plan
          passes every axis the params are replicated over, e.g.
          ("data", "sequence").
        plan: a planner_lib.ShardingPlan as the single source of
          sharding truth. The plan is AUTHORITATIVE for the mesh (when
          `mesh` is None), shard_weight_update, weight_update_axes,
          collective_quant/block (pinned — the env flags are not
          consulted), and param_min_shard_size; after init_state places
          the TrainState, the layout is audited leaf-for-leaf against
          the plan's predictions and a mismatch raises. None (the
          default, and the T2R_PLAN=off path) keeps the explicit kwargs
          exactly as before.
        """
        self.model = model
        self.plan = plan
        if plan is not None:
            if mesh is None:
                mesh = plan.build_mesh()
            elif not plan.matches_mesh(mesh):
                raise ValueError(
                    f"mesh axes {dict(mesh.shape)} disagree with plan "
                    f"{plan.name!r} axes {plan.axes_dict()}"
                )
            _validate_model_matches_plan(model, plan)
            shard_weight_update = plan.shard_weight_update
            weight_update_axes = plan.weight_update_axes
            collective_quant = plan.collective_quant
            collective_block = plan.collective_block
            param_min_shard_size = plan.param_min_shard_size
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.preprocessor = model.preprocessor
        self.optimizer = model.create_optimizer()
        if flatten_optimizer_update:
            if (
                self.mesh.shape[mesh_lib.FSDP_AXIS] > 1
                or self.mesh.shape[mesh_lib.MODEL_AXIS] > 1
                or shard_weight_update
            ):
                raise ValueError(
                    "flatten_optimizer_update concatenates all parameters "
                    "into one replicated vector, which defeats "
                    "fsdp/tensor-parallel parameter sharding and ZeRO-2 "
                    "weight-update sharding; use it only in replicated-"
                    "parameter regimes."
                )
            self.optimizer = optax.flatten(self.optimizer)
        self._flat_ema = flatten_optimizer_update
        self._fuse_stats = (
            flatten_optimizer_update
            if fuse_batch_stats_update is None
            else fuse_batch_stats_update
        )
        if self._fuse_stats:
            # The deferral switch lives on the model (the wrapper delegates
            # inference to the inner model, so set both): TRAIN applies
            # open 'batch_stats_new' and layers.batch_norm defers.
            for m in (model, getattr(model, "_model", None)):
                if m is not None:
                    m.defer_batch_stats_update = True
        # Set by init_state when the model actually carries batch stats.
        self._stats_template = None
        self._stats_unravel = None
        self._donate = donate_state
        self._param_min_shard_size = param_min_shard_size
        self._shard_weight_update = shard_weight_update
        self._weight_update_axes = tuple(
            weight_update_axes
            if weight_update_axes is not None
            else (mesh_lib.DATA_AXIS,)
        )
        if grad_accum_steps < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")

        # Quantized gradient collectives (parallel/collectives.py): only
        # the pure data-parallel ZeRO-2 regime has the reduce-scatter /
        # all-gather pair to compress; everywhere else the flag is inert
        # so it can stay exported fleet-wide.
        quant_name = (
            collective_quant
            if collective_quant is not None
            else flags.get_enum("T2R_COLLECTIVE_QUANT")
        )
        quant_block = (
            collective_block
            if collective_block is not None
            else flags.get_int("T2R_COLLECTIVE_BLOCK")
        )
        pure_data_parallel = all(
            self.mesh.shape[axis] == 1
            for axis in (
                mesh_lib.FSDP_AXIS,
                mesh_lib.MODEL_AXIS,
                mesh_lib.SEQUENCE_AXIS,
                mesh_lib.PIPE_AXIS,
                mesh_lib.EXPERT_AXIS,
            )
        )
        self._quant_collective = None
        if (
            quant_name != "none"
            and shard_weight_update
            and pure_data_parallel
            and self.mesh.shape[mesh_lib.DATA_AXIS] > 1
        ):
            if self._fuse_stats:
                raise ValueError(
                    "fuse_batch_stats_update is unsupported with quantized "
                    "collectives: the quantized ZeRO-2 step already runs "
                    "per-shard on the flat parameter vector and averages "
                    "batch-norm statistics across replicas itself."
                )
            self._quant_collective = collectives.get_collective(
                quant_name, quant_block
            )
        # Set by init_state in the quantized-collective regime.
        self._flat_layout = None
        self._flat_unravel = None
        self._quant_state_specs = None

        # The layout plan this trainer ACTUALLY runs: the explicit plan,
        # or an ad-hoc one distilled from the resolved kwargs. Either
        # way, init_state's placement rules come from here — the planner
        # is the single source of sharding truth; the hand-wired kwargs
        # are just one way of naming a plan.
        mesh_axes = dict(self.mesh.shape)
        self._layout = planner_lib.ShardingPlan(
            name=plan.name if plan is not None else "adhoc",
            data=mesh_axes.get(mesh_lib.DATA_AXIS, 1),
            fsdp=mesh_axes.get(mesh_lib.FSDP_AXIS, 1),
            model=mesh_axes.get(mesh_lib.MODEL_AXIS, 1),
            sequence=mesh_axes.get(mesh_lib.SEQUENCE_AXIS, 1),
            pipe=mesh_axes.get(mesh_lib.PIPE_AXIS, 1),
            expert=mesh_axes.get(mesh_lib.EXPERT_AXIS, 1),
            shard_weight_update=self._shard_weight_update,
            weight_update_axes=self._weight_update_axes,
            collective_quant=(
                self._quant_collective.name
                if self._quant_collective is not None
                else "none"
            ),
            collective_block=(
                self._quant_collective.block
                if self._quant_collective is not None
                else quant_block
            ),
            param_min_shard_size=self._param_min_shard_size,
        )

        def forward_loss(params, variables, features, labels, rng_net):
            variables = dict(variables)
            variables["params"] = params
            f, l, outputs, mutable = model.packed_inference(
                variables, features, MODE_TRAIN, labels=labels, rng=rng_net
            )
            loss, train_metrics = model.model_train_fn(
                f, l, outputs, MODE_TRAIN
            )
            return loss, (train_metrics, mutable)

        if remat:
            # Differentiating through the checkpointed forward recomputes
            # activations in the backward pass instead of storing them.
            forward_loss = jax.checkpoint(
                forward_loss, static_argnums=(), policy=None
            )

        def compute_grads(state, features, labels, rng_net):
            """(loss, metrics, mutable, grads) for one (micro)batch."""
            (loss, (train_metrics, mutable)), grads = jax.value_and_grad(
                forward_loss, has_aux=True
            )(state.params, state.variables, features, labels, rng_net)
            return loss, train_metrics, mutable, grads

        def _microbatch(tree, index):
            """Slice microbatch `index` out of every batch-carrying leaf.

            Mirrors shard_batch's tolerance: leaves whose leading dim
            divides K split; 0-d and unit-leading leaves replicate into
            every microbatch; a >1 leading dim that does not divide is a
            real batch that cannot split — raise.
            """

            def take(leaf):
                shape = getattr(leaf, "shape", ())
                if len(shape) == 0 or shape[0] == 1:
                    return leaf
                if shape[0] % grad_accum_steps != 0:
                    raise ValueError(
                        f"Leaf batch {shape[0]} not divisible by "
                        f"grad_accum_steps={grad_accum_steps}"
                    )
                size = shape[0] // grad_accum_steps
                return jax.lax.dynamic_slice_in_dim(
                    leaf, index * size, size, axis=0
                )

            return jax.tree_util.tree_map(take, tree)

        def accumulated_grads(state, features, labels, rng_net):
            """Grads averaged over K microbatches via lax.scan — one
            microbatch's activations alive at a time, ONE traced copy of
            the model (the accumulator is seeded with zeros shaped via
            eval_shape; microbatches are dynamic slices of the full
            batch, so the forward/backward graph exists only in the scan
            body). Metrics come back stacked per microbatch and are
            recombined by KEY afterwards (see combine_metric /
            BATCH_CARRYING_METRIC_PREFIXES).
            """
            if grad_accum_steps == 1:
                return compute_grads(state, features, labels, rng_net)

            def grads_at(index):
                return compute_grads(
                    state,
                    _microbatch(features, index),
                    _microbatch(labels, index),
                    # Independent stochasticity (dropout masks) per
                    # microbatch, as one large-batch draw would have.
                    jax.random.fold_in(rng_net, index),
                )

            shapes = jax.eval_shape(grads_at, jnp.int32(0))
            zeros = jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                (shapes[0], shapes[2], shapes[3]),
            )

            def body(carry, index):
                loss, metrics, mutable, grads = grads_at(index)
                acc_loss, _, acc_grads = carry
                new_carry = (
                    acc_loss + loss / grad_accum_steps,
                    mutable,  # last microbatch's batch-norm stats win
                    jax.tree_util.tree_map(
                        lambda a, g: a + g / grad_accum_steps,
                        acc_grads,
                        grads,
                    ),
                )
                return new_carry, metrics

            (loss, mutable, grads), stacked_metrics = jax.lax.scan(
                body, zeros, jnp.arange(grad_accum_steps)
            )

            def combine_metric(path, stacked):
                # Per-metric stacked leaves are [K, ...]. Batch-carrying
                # metrics are identified by KEY, not shape (a fixed-size
                # vector metric could coincide with B/K): keys under the
                # `golden/` (add_golden_tensor) or `per_example/` prefix
                # concatenate back to the full batch; everything else is
                # reduced over the K axis shape-preserving — floats
                # average (mean of per-microbatch means == full-batch
                # mean), integer counts sum. Contract documented on
                # AbstractT2RModel.model_train_fn.
                if _is_batch_carrying_metric(path) and stacked.ndim >= 2:
                    return stacked.reshape((-1,) + stacked.shape[2:])
                if jnp.issubdtype(stacked.dtype, jnp.floating):
                    return jnp.mean(stacked, axis=0)
                return jnp.sum(stacked, axis=0)

            train_metrics = jax.tree_util.tree_map_with_path(
                combine_metric, stacked_metrics
            )
            return loss, train_metrics, mutable, grads

        def train_step(state: TrainState, batch, rng):
            step_rng = jax.random.fold_in(rng, state.step)
            rng_pre, rng_net = jax.random.split(step_rng)
            features, labels = self.preprocessor.preprocess(
                batch["features"], _batch_labels(batch),
                mode=MODE_TRAIN, rng=rng_pre,
            )
            # Fused-stats regime: the live state's batch_stats is one flat
            # vector. The train forward never READS running stats, but
            # flax needs the collection tree present — hand it dead zeros
            # (DCE'd by XLA) and drop the unchanged tree from the mutable
            # merge below.
            live_stats = state.variables.get("batch_stats")
            stats_fused = _is_flat_stats(live_stats)
            fwd_state = state
            if stats_fused:
                fwd_variables = dict(state.variables)
                fwd_variables["batch_stats"] = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype),
                    self._stats_template,
                )
                fwd_state = state.replace(variables=fwd_variables)
            loss, train_metrics, mutable, grads = accumulated_grads(
                fwd_state, features, labels, rng_net
            )
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            new_stats = mutable.pop("batch_stats_new", None)
            if stats_fused:
                mutable.pop("batch_stats", None)
                if not new_stats:
                    raise ValueError(
                        "fuse_batch_stats_update is on but no layer wrote "
                        "'batch_stats_new' — the model's batch norms must "
                        "be layers.batch_norm.BatchNorm (plain flax "
                        "BatchNorm would silently freeze its running "
                        "stats in this regime)."
                    )
            variables = dict(state.variables)
            variables.update(mutable)
            variables["params"] = params
            if new_stats:
                variables["batch_stats"] = _apply_stats_update(
                    variables["batch_stats"],
                    new_stats,
                    self._stats_template if stats_fused else None,
                )
            ema = state.ema_params
            if ema is not None:
                ema = update_ema(ema, params, model.avg_model_params_decay)
            metrics = {"loss": loss}
            metrics.update(train_metrics)
            new_state = state.replace(
                step=state.step + 1,
                variables=variables,
                opt_state=opt_state,
                ema_params=ema,
            )
            return new_state, metrics

        def eval_step(state: TrainState, batch, use_ema: bool):
            features, labels = self.preprocessor.preprocess(
                batch["features"], _batch_labels(batch),
                mode=MODE_EVAL, rng=None,
            )
            variables = dict(state.export_variables(use_ema=use_ema))
            if _is_flat_stats(variables.get("batch_stats")):
                # Fused live state: eval DOES read running stats —
                # unravel to the canonical tree (slices; eval cadence
                # only, never inside the train step).
                variables["batch_stats"] = self._stats_unravel(
                    variables["batch_stats"]
                )
            f, l, outputs, _ = model.packed_inference(
                variables, features, MODE_EVAL, labels=labels
            )
            return model.model_eval_fn(f, l, outputs)

        def predict_step(variables, features):
            f, _, outputs, _ = model.packed_inference(
                variables, features, MODE_PREDICT
            )
            return model.create_export_outputs_fn(f, outputs)

        def train_scan(state: TrainState, stacked_batch, rng):
            """K train steps under one dispatch: lax.scan over the leading
            [K, B, ...] axis (the iterations_per_loop equivalent — reference
            models/abstract_model.py:76-77 TPUConfig.iterations_per_loop)."""
            return jax.lax.scan(
                lambda s, b: train_step(s, b, rng), state, stacked_batch
            )

        def quant_train_step(state: TrainState, batch, rng):
            """ZeRO-2 step with EXPLICIT quantized collectives.

            The GSPMD regime lets sharded autodiff insert the gradient
            reduce-scatter and the update all-gather; to compress those
            wires the step goes manual instead: shard_map over the data
            axis, each replica computing grads on its local batch shard,
            then (1) error-feedback residual added to the raveled
            gradient, (2) blockwise-quantized reduce-scatter — each
            replica encodes one chunk per peer, all_to_all, receivers
            decode and sum exactly in fp32, (3) per-shard elementwise
            optimizer update on this replica's contiguous slice of the
            flat parameter vector (the ZeRO-2 sharded update), (4)
            blockwise-quantized all-gather of the UPDATE (not the params:
            every replica applies the same dequantized update, so params
            never drift apart), (5) both quantization errors carried to
            the next step in state.collective_residual. The payloads in
            (2)/(4) are the gradient exchange — the traffic that scales
            with parameter count and what wire_summary counts; metric
            pmeans and batch-carrying metric gathers ride alongside
            uncompressed and uncounted.
            """
            coll = self._quant_collective
            layout = self._flat_layout
            axis = mesh_lib.DATA_AXIS
            num_shards = self.mesh.shape[axis]

            def batch_spec(leaf):
                # Mirrors shard_batch's tolerance (planner-owned spec).
                return mesh_lib.batch_partition_spec(
                    self.mesh, getattr(leaf, "shape", ())
                )

            def local_step(state, batch, rng):
                device = collectives.axis_index(axis)
                step_rng = jax.random.fold_in(rng, state.step)
                rng_pre, rng_net = jax.random.split(step_rng)
                # Independent stochasticity per replica, as one global
                # large-batch draw would have (the microbatch fold_in
                # precedent in accumulated_grads).
                rng_pre = jax.random.fold_in(rng_pre, device)
                rng_net = jax.random.fold_in(rng_net, device)
                features, labels = self.preprocessor.preprocess(
                    batch["features"], _batch_labels(batch),
                    mode=MODE_TRAIN, rng=rng_pre,
                )
                loss, train_metrics, mutable, grads = accumulated_grads(
                    state, features, labels, rng_net
                )
                residual = state.collective_residual
                flat_grads = jax.flatten_util.ravel_pytree(grads)[0]
                grads_fb = layout.pad(flat_grads) + residual["grad"][0]
                rows = layout.rows(grads_fb)
                reduced, sent = coll.reduce_scatter(rows, axis)
                grad_residual = (rows - sent).reshape(1, layout.padded)
                # Local losses are means over the LOCAL shard; the global
                # mean gradient is the cross-replica sum / N.
                grad_shard = reduced / num_shards
                flat_params = layout.pad(
                    jax.flatten_util.ravel_pytree(state.params)[0]
                )
                param_shard = layout.rows(flat_params)[device]
                updates, opt_state = self.optimizer.update(
                    grad_shard, state.opt_state, param_shard
                )
                update_fb = updates + residual["update"]
                full_update, sent_update = coll.all_gather_shard(
                    update_fb, axis
                )
                update_residual = update_fb - sent_update
                params = self._flat_unravel(
                    layout.unpad(flat_params + full_update)
                )
                new_stats = mutable.pop("batch_stats_new", None)
                # Per-replica batch-norm statistics average across the
                # data axis — exact for the means; the variance-of-means
                # term is the standard local-BN caveat (same family as
                # grad-accum's per-microbatch statistics).
                mutable = collectives.pmean(mutable, axis)
                if new_stats is not None:
                    new_stats = collectives.pmean(new_stats, axis)
                variables = dict(state.variables)
                variables.update(mutable)
                variables["params"] = params
                if new_stats:
                    variables["batch_stats"] = _apply_stats_update(
                        variables["batch_stats"], new_stats, None
                    )
                ema = state.ema_params
                if ema is not None:
                    # The EMA mirror follows the flat sharded layout: each
                    # replica advances its own shard with the update it
                    # just applied (dequantized, so the mirror tracks the
                    # params every replica actually holds).
                    decay = model.avg_model_params_decay
                    new_param_shard = param_shard + sent_update
                    ema = ema * decay + new_param_shard * (1.0 - decay)
                metrics = {"loss": loss}
                metrics.update(train_metrics)

                def combine(path, value):
                    # Same key-driven contract as the grad-accum
                    # recombination: batch-carrying metrics concatenate
                    # back to the global batch, floats average, integer
                    # counts sum.
                    if (
                        _is_batch_carrying_metric(path)
                        and getattr(value, "ndim", 0) >= 1
                    ):
                        return collectives.all_gather(
                            value, axis, tiled=True
                        )
                    if jnp.issubdtype(
                        jnp.result_type(value), jnp.floating
                    ):
                        return collectives.pmean(value, axis)
                    return collectives.psum(value, axis)

                metrics = jax.tree_util.tree_map_with_path(
                    combine, metrics
                )
                new_state = state.replace(
                    step=state.step + 1,
                    variables=variables,
                    opt_state=opt_state,
                    ema_params=ema,
                    collective_residual={
                        "grad": grad_residual,
                        "update": update_residual,
                    },
                )
                return new_state, metrics

            in_specs = (
                self._quant_state_specs,
                jax.tree_util.tree_map(batch_spec, batch),
                mesh_lib.REPLICATED_SPEC,
            )
            out_specs = (self._quant_state_specs, mesh_lib.REPLICATED_SPEC)
            return collectives.smap(
                local_step, self.mesh, in_specs, out_specs
            )(state, batch, rng)

        def quant_train_scan(state: TrainState, stacked_batch, rng):
            return jax.lax.scan(
                lambda s, b: quant_train_step(s, b, rng),
                state,
                stacked_batch,
            )

        if self._quant_collective is not None:
            step_fn, scan_fn = quant_train_step, quant_train_scan
        else:
            step_fn, scan_fn = train_step, train_scan
        self.train_step = _serialize_dispatch(jax.jit(
            step_fn, donate_argnums=(0,) if donate_state else ()
        ))
        self.train_scan = _serialize_dispatch(jax.jit(
            scan_fn, donate_argnums=(0,) if donate_state else ()
        ))
        self.eval_step = _serialize_dispatch(
            jax.jit(eval_step, static_argnums=(2,))
        )
        self.predict_step = _serialize_dispatch(jax.jit(predict_step))
        # The un-jitted forward, for callers that must control tracing
        # themselves: a serving fn that rewrites the forward at trace
        # time (serve_quant.native_lowering's flax interception) cannot
        # go through the jitted version — an eager call with avals the
        # jit cache has already seen would silently execute the OLD
        # program, interception skipped.
        self.predict_step_fn = predict_step

    def init_state(self, rng: jax.Array, example_batch) -> TrainState:
        # The model initializes at its own (post-preprocess) contract: run the
        # preprocessor on the example batch outside jit once, in TRAIN mode so
        # init shapes match exactly what train_step will feed the network.
        features, _ = self.preprocessor.preprocess(
            example_batch["features"],
            _batch_labels(example_batch),
            mode=MODE_TRAIN,
            rng=jax.random.PRNGKey(0),
        )
        state = create_train_state(
            self.model, rng, features, self.optimizer,
            flat_ema=self._flat_ema,
        )
        if self._fuse_stats:
            stats = state.variables.get("batch_stats")
            if isinstance(stats, dict) and stats:
                self._stats_template = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), stats
                )
                flat, unravel = jax.flatten_util.ravel_pytree(stats)
                self._stats_unravel = unravel
                variables = dict(state.variables)
                variables["batch_stats"] = flat
                state = state.replace(variables=variables)
            else:
                # No batch statistics in this model; nothing to fuse.
                self._fuse_stats = False

        def place(tree, base_rule):
            # Pipeline-stage placement layers over every regime: leaves
            # under the pipe_stages key shard dim 0 over `pipe` (a
            # passthrough to base_rule when the pipe axis is 1).
            rule = mesh_lib.pipe_stage_param_rule(self.mesh, base_rule)
            return jax.tree_util.tree_map_with_path(
                lambda path, x: jax.device_put(x, rule(path, x)), tree
            )

        # Placement rules come from the layout plan — the regime branch
        # below mirrors ShardingPlan.regime() exactly, so a plan-driven
        # trainer and a kwargs-driven one place identically (the preset
        # byte-equality contract; audited below when a plan is set).
        regime = self._layout.regime()
        if regime == "quant_zero2":
            return self._audited(self._init_quant_state(state, place))

        if regime == "sharded_params":
            # Sharded-parameter regimes: fsdp shards large leaves (and the
            # mirrored optimizer/EMA copies) ZeRO-style; the model axis
            # column-splits kernels for tensor parallelism. GSPMD
            # propagates these shardings through the optimizer update, so
            # params stay sharded across steps.
            return self._audited(
                place(state, self._layout.base_param_rule(self.mesh))
            )
        # Replicate onto the mesh so jitted steps see mesh-placed inputs.
        replicate_rule = self._layout.base_param_rule(self.mesh)
        if regime == "zero2":
            # Cross-replica weight-update sharding (ZeRO-2): only the
            # optimizer-side mirrors shard; params/variables stay
            # replicated for the forward/backward. The mirrors go straight
            # to their sharded layout — materializing them replicated
            # first would need the very memory this mode exists to avoid.
            opt_state, ema_params = place(
                (state.opt_state, state.ema_params),
                self._layout.weight_update_rule(self.mesh),
            )
            state = state.replace(opt_state=(), ema_params=None)
            state = place(state, replicate_rule)
            return self._audited(
                state.replace(opt_state=opt_state, ema_params=ema_params)
            )
        return self._audited(place(state, replicate_rule))

    def _audited(self, state: TrainState) -> TrainState:
        """Leaf-for-leaf layout audit against the plan's predictions —
        only when an EXPLICIT plan drives this trainer (the hand-wired
        path stays exactly as cheap as before)."""
        if self.plan is None:
            return state
        audit = planner_lib.audit_state_layout(self._layout, self.mesh, state)
        if audit["mismatches"]:
            raise RuntimeError(
                f"plan {self.plan.name!r} layout audit failed on "
                f"{len(audit['mismatches'])} of {audit['leaves']} leaves: "
                f"{audit['mismatches'][:5]}"
            )
        return state

    def _init_quant_state(self, state: TrainState, place) -> TrainState:
        """Quantized-collective (ZeRO-2) state layout.

        Params/variables stay replicated for the forward/backward exactly
        as in the GSPMD regime; optimizer state and the EMA mirror move to
        the FLAT block-padded parameter vector, sharded over the data axis
        (each replica owns the slice its shard_map step updates), and the
        error-feedback residual joins the state as zeros. Like
        flatten_optimizer_update, this changes the opt-state checkpoint
        layout — checkpoints are not interchangeable with the tree-layout
        regimes.
        """
        mesh = self.mesh
        num_shards = mesh.shape[mesh_lib.DATA_AXIS]
        flat, unravel = jax.flatten_util.ravel_pytree(state.params)
        self._flat_unravel = unravel
        layout = collectives.FlatShardLayout(
            flat.size, num_shards, self._quant_collective.block
        )
        self._flat_layout = layout
        replicated = mesh_lib.replicated(mesh)
        sharded = mesh_lib.flat_shard_sharding(mesh)

        def mirror_sharding(leaf):
            if getattr(leaf, "ndim", 0) == 0:
                return replicated
            return sharded

        ema = state.ema_params
        state = state.replace(opt_state=(), ema_params=None)
        state = place(state, lambda leaf: replicated)
        # The flat mirrors are born on their sharded layout: computing
        # them through jit with sharded out_shardings lets SPMD emit each
        # device's slice directly, so no device ever holds a full-size
        # padded Adam mu/nu (or the [N, padded] residual — N x params!)
        # the way materialize-then-device_put would transiently require.
        # That transient is exactly what ZeRO-2 sharding exists to avoid.
        opt_shardings = jax.tree_util.tree_map(
            mirror_sharding,
            jax.eval_shape(lambda f: self.optimizer.init(layout.pad(f)), flat),
        )
        opt_state = jax.jit(
            lambda f: self.optimizer.init(layout.pad(f)),
            out_shardings=opt_shardings,
        )(flat)
        if ema is not None:
            flat_ema = jax.flatten_util.ravel_pytree(ema)[0]
            ema = jax.jit(layout.pad, out_shardings=sharded)(flat_ema)
        residual = jax.jit(
            lambda: {
                # Per-replica untransmitted gradient remainder; dim 0 is
                # the data axis, so each replica sees its own [1, padded]
                # slice.
                "grad": jnp.zeros(
                    (num_shards, layout.padded), jnp.float32
                ),
                # Per-owner untransmitted update remainder on the flat
                # layout.
                "update": jnp.zeros((layout.padded,), jnp.float32),
            },
            out_shardings={"grad": sharded, "update": sharded},
        )()
        spec = mesh_lib.FLAT_SHARD_SPEC
        self._quant_state_specs = TrainState(
            step=mesh_lib.REPLICATED_SPEC,
            variables=jax.tree_util.tree_map(
                lambda _: mesh_lib.REPLICATED_SPEC, state.variables
            ),
            opt_state=jax.tree_util.tree_map(
                lambda leaf: (
                    mesh_lib.REPLICATED_SPEC
                    if getattr(leaf, "ndim", 0) == 0
                    else spec
                ),
                opt_state,
            ),
            ema_params=None if ema is None else spec,
            collective_residual={"grad": spec, "update": spec},
        )
        return state.replace(
            opt_state=opt_state,
            ema_params=ema,
            collective_residual=residual,
        )

    def collective_log_record(self, measure: bool = True) -> Dict[str, float]:
        """The gradient-collective observability channel: pre/post
        compression bytes of the GRADIENT EXCHANGE per device-step
        (analytic — the reduce-scatter/all-gather payloads; metric
        pmeans/gathers ride alongside uncounted) and, when `measure`, the
        measured wall-time of one exchange. {} outside the quantized
        regime. Key names are shared with `bench.py comms` via
        metrics.collective_record."""
        if self._quant_collective is None or self._flat_layout is None:
            return {}
        pre, post = collectives.wire_summary(
            self._quant_collective, self._flat_layout.padded
        )
        wall_ms = self.measure_collective_ms() if measure else None
        return collective_record(pre, post, wall_ms)

    def measure_collective_ms(self, repeats: int = 5) -> float:
        """Median wall-time of one gradient exchange (quantized
        reduce-scatter + update all-gather) in isolation, on a zeros
        payload of the real layout — compile excluded, timed per call."""
        coll, layout = self._quant_collective, self._flat_layout
        axis = mesh_lib.DATA_AXIS

        def local(flat):
            reduced, _ = coll.reduce_scatter(layout.rows(flat), axis)
            full, _ = coll.all_gather_shard(
                reduced / layout.num_shards, axis
            )
            return full

        fn = _serialize_dispatch(jax.jit(
            collectives.smap(
                local,
                self.mesh,
                (mesh_lib.REPLICATED_SPEC,),
                mesh_lib.REPLICATED_SPEC,
            )
        ))
        payload = jnp.zeros((layout.padded,), jnp.float32)
        jax.block_until_ready(fn(payload))
        times = []
        for _ in range(repeats):
            start = time.perf_counter()
            jax.block_until_ready(fn(payload))
            times.append((time.perf_counter() - start) * 1000.0)
        times.sort()
        return times[len(times) // 2]

    def shard_batch(self, batch):
        return mesh_lib.shard_batch(batch, self.mesh)

    def export_variables(self, state: TrainState, use_ema: bool = False):
        """state.export_variables with fused (flat) batch_stats unraveled
        to the canonical tree — the form every serving/export consumer
        expects. Identity on non-fused states."""
        variables = dict(state.export_variables(use_ema=use_ema))
        if _is_flat_stats(variables.get("batch_stats")):
            variables["batch_stats"] = self._stats_unravel(
                variables["batch_stats"]
            )
        return variables

    def persistable_state(self, state: TrainState) -> TrainState:
        """Checkpoint/hook-boundary form of a fused-stats state: the flat
        batch_stats vector back as the canonical tree, so the ON-DISK
        layout never changes and hooks/exporters see ordinary variables.
        No-op for non-fused states."""
        stats = state.variables.get("batch_stats")
        if not _is_flat_stats(stats):
            return state
        variables = dict(state.variables)
        variables["batch_stats"] = jax.device_put(
            self._stats_unravel(stats), mesh_lib.replicated(self.mesh)
        )
        return state.replace(variables=variables)

    def fuse_state(self, state: TrainState) -> TrainState:
        """Inverse of persistable_state: tree batch_stats raveled into the
        live fused form (applied after a checkpoint restore)."""
        stats = state.variables.get("batch_stats")
        if not self._fuse_stats or not isinstance(stats, dict) or not stats:
            return state
        variables = dict(state.variables)
        variables["batch_stats"] = jax.device_put(
            jax.flatten_util.ravel_pytree(stats)[0],
            mesh_lib.replicated(self.mesh),
        )
        return state.replace(variables=variables)


# -- checkpointing ------------------------------------------------------------


def create_checkpoint_manager(
    model_dir: str,
    save_interval_steps: int,
    keep_checkpoint_max: int = 5,
) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(os.path.join(model_dir, "checkpoints")),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=keep_checkpoint_max,
            save_interval_steps=save_interval_steps,
            create=True,
            enable_async_checkpointing=True,
        ),
    )


# Re-exported from durability (its importable, orbax-free home) for the
# trainer-side callers below and existing importers.
latest_durable_step_in = durability.latest_durable_step_in


def restore_or_init_state(
    manager: ocp.CheckpointManager, compiled: CompiledModel, rng, example_batch
) -> TrainState:
    state = compiled.init_state(rng, example_batch)
    latest = latest_durable_step_in(manager)
    if latest is not None:
        # Chaos site: `restore` (slow-restore delay / exception injection).
        chaos.maybe_fire("restore")
        # Checkpoints always hold the PERSISTABLE (tree-stats) layout;
        # restore against that form, then refuse back into the live fused
        # form if this trainer runs one.
        template = compiled.persistable_state(state)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            template,
        )
        state = compiled.fuse_state(
            manager.restore(latest, args=ocp.args.StandardRestore(abstract))
        )
    return state


# -- evaluation ---------------------------------------------------------------


def normalize_eval_generators(input_generator_eval) -> Dict[str, Any]:
    """Normalizes the eval-generator argument to a {name: generator} map.

    None -> {}; a bare generator -> {"": generator}; a mapping passes
    through (multi-eval: one named dataset per entry, reference
    utils/train_eval.py:541-566).
    """
    if input_generator_eval is None:
        return {}
    if isinstance(input_generator_eval, dict):
        if "" in input_generator_eval and len(input_generator_eval) > 1:
            raise ValueError(
                "Multi-eval maps require every eval to be named (got an "
                "empty-string name alongside others)."
            )
        return dict(input_generator_eval)
    return {"": input_generator_eval}


def eval_dir_name(name: str) -> str:
    """'eval' for the unnamed eval, 'eval_<name>' per named dataset (the
    reference's per-eval-name output dirs)."""
    return "eval" if not name else f"eval_{name}"


def run_named_evals(
    compiled: "CompiledModel",
    state: "TrainState",
    eval_generators: Dict[str, Any],
    eval_steps: Optional[int],
    use_ema: bool,
    step: Optional[int] = None,
    writers: Optional[Dict[str, MetricsWriter]] = None,
) -> Dict[str, float]:
    """Evaluates every named dataset; returns merged metrics.

    The FIRST entry is the primary eval: its metrics keep unprefixed keys —
    that is what exporter compare_fns gate on. The primary never silently
    changes: if it returns no results this round, no unprefixed metrics are
    emitted (a Best gate must not compare across datasets). Every named
    eval's metrics are also recorded under '<name>/<key>'.
    """
    merged: Dict[str, float] = {}
    for i, (name, generator) in enumerate(eval_generators.items()):
        metrics = evaluate(
            compiled,
            state,
            iter(generator.create_dataset(MODE_EVAL)),
            eval_steps=eval_steps,
            use_ema=use_ema,
        )
        if not metrics:
            continue
        if writers is not None and step is not None and name in writers:
            writers[name].write(step, metrics)
        if i == 0:
            merged.update(metrics)
        if name:
            merged.update({f"{name}/{k}": v for k, v in metrics.items()})
    return merged


def evaluate(
    compiled: CompiledModel,
    state: TrainState,
    eval_batches: Iterator,
    eval_steps: Optional[int] = None,
    use_ema: bool = False,
) -> Dict[str, float]:
    """Averages model_eval_fn metrics over up to eval_steps batches.

    Accumulates on-device: steps dispatch back-to-back (transfers
    double-buffered) and the host reads the totals once at the end, rather
    than a blocking device_get per batch.
    """
    if eval_steps is not None:
        eval_batches = itertools.islice(eval_batches, eval_steps)
    totals: Optional[Dict[str, jax.Array]] = None
    count = 0
    deferred = DeferredFetch()
    for batch in infeed.device_prefetch(
        eval_batches, compiled.shard_batch, depth=infeed.resolve_depth()
    ):
        metrics = compiled.eval_step(state, batch, use_ema)
        # On-device f32 accumulation through the locked jitted helpers:
        # these adds are mesh-spanning programs like the steps, so they
        # must enqueue under the same dispatch lock (see _DISPATCH_LOCK).
        if totals is None:
            totals = _init_metric_totals(metrics)
        else:
            totals = _accumulate_metric_totals(totals, metrics)
        count += 1
        if count % 32 == 0:
            # Periodic sync: without it nothing bounds the dispatch queue
            # and long evals pile batches up on the device. Deferred by
            # one window: enqueue this window's accumulator handle and
            # drain the PREVIOUS one (finished ~32 steps ago, so the
            # readback returns immediately instead of serializing
            # dispatch behind the newest computation).
            deferred.push(next(iter(totals.values())))
    if not count or totals is None:
        return {}
    host_totals = jax.device_get(totals)
    return {key: float(value) / count for key, value in host_totals.items()}


# -- the entry point ----------------------------------------------------------


def train_eval_model(
    t2r_model: AbstractT2RModel,
    input_generator_train=None,
    input_generator_eval=None,
    model_dir: str = "/tmp/t2r_tpu_model",
    max_train_steps: int = 1000,
    eval_steps: Optional[int] = 100,
    save_checkpoints_steps: int = 500,
    keep_checkpoint_max: int = 5,
    log_every_steps: int = 100,
    create_exporters_fn: Optional[Callable] = None,
    hook_builders: Optional[List[HookBuilder]] = None,
    mesh=None,
    seed: int = 0,
    use_ema_for_eval: Optional[bool] = None,
    use_tensorboard: Optional[bool] = None,
    iterations_per_loop: int = 1,
    infeed_depth: Optional[int] = None,
    remat: bool = False,
    grad_accum_steps: int = 1,
    shard_weight_update: bool = False,
    flatten_optimizer_update: bool = False,
    plan: Optional[planner_lib.ShardingPlan] = None,
) -> Dict[str, float]:
    """Trains (and periodically evaluates/exports) the model.

    Returns the final eval metrics (empty dict when no eval generator).
    Resumes from the latest checkpoint in model_dir if present.

    iterations_per_loop > 1 runs K device steps per host dispatch via a
    jitted lax.scan (reference TPUConfig.iterations_per_loop); per-step
    hooks then observe loop granularity, exactly as reference SessionRunHooks
    did under TPUEstimator. infeed_depth batches are kept device-resident
    ahead of the consumer (None reads T2R_INFEED_DEPTH; default 2 =
    double-buffered host->device transfer).
    remat / grad_accum_steps / shard_weight_update are the memory levers
    (see CompiledModel): recompute activations in the backward, split
    each batch into K gradient-accumulation microbatches, and/or shard
    optimizer state across data-parallel replicas (ZeRO-2).
    plan: a planner_lib.ShardingPlan driving mesh + regime (see
    CompiledModel); None consults the T2R_PLAN flag ('off' = the
    hand-wired kwargs path, byte-for-byte; a preset name or 'auto'
    resolves a plan through parallel/planner.py).
    """
    model = maybe_wrap_for_tpu(t2r_model)
    print_specification(model)
    os.makedirs(model_dir, exist_ok=True)
    _save_operative_config(model_dir)

    infeed_depth = infeed.resolve_depth(infeed_depth)
    if use_ema_for_eval is None:
        use_ema_for_eval = getattr(model, "use_avg_model_params", False)

    if input_generator_train is None:
        raise ValueError("train_eval_model requires input_generator_train.")
    provide_input_generator_with_model_information(
        input_generator_train, model, MODE_TRAIN
    )
    train_batches = iter(input_generator_train.create_dataset(MODE_TRAIN))
    # Multi-eval: a {name: generator} map evaluates every named dataset per
    # eval round (reference multi-eval-name -> EvalSpec override,
    # utils/train_eval.py:541-566). A bare generator is the single-eval case.
    eval_generators = normalize_eval_generators(input_generator_eval)
    for generator in eval_generators.values():
        provide_input_generator_with_model_information(
            generator, model, MODE_EVAL
        )

    # Writer-side durability sweep BEFORE the manager opens: torn step
    # dirs (a SIGKILL mid-save, a half-copied restore source) move to
    # checkpoints.quarantine/ so the resumed run re-saves the replayed
    # window without colliding with the wreckage, and latest_step can
    # never name them. The trainer owns this dir — readers only skip.
    for torn_name, torn_reason in durability.sweep_torn_checkpoints(model_dir):
        print(
            f"Quarantined torn checkpoint {torn_name!r}: {torn_reason}",
            flush=True,
        )
    manager = create_checkpoint_manager(
        model_dir, save_interval_steps=save_checkpoints_steps,
        keep_checkpoint_max=keep_checkpoint_max,
    )
    rng = jax.random.PRNGKey(seed)
    rng_init, rng_train = jax.random.split(rng)
    first_batch = next(train_batches)
    if plan is None:
        # The T2R_PLAN gate: 'off' (default) returns None and the kwargs
        # below drive the trainer exactly as before; a preset name or
        # 'auto' makes the planner the source of mesh + regime.
        plan = planner_lib.resolve_plan_from_flag(model, first_batch)
    compiled = CompiledModel(
        model, mesh=mesh, remat=remat, grad_accum_steps=grad_accum_steps,
        shard_weight_update=shard_weight_update,
        flatten_optimizer_update=flatten_optimizer_update,
        plan=plan,
    )
    state = restore_or_init_state(manager, compiled, rng_init, first_batch)
    start_step = int(jax.device_get(state.step))

    writer = MetricsWriter(
        os.path.join(model_dir, "train"),
        use_tensorboard=(
            use_tensorboard
            if use_tensorboard is not None
            else model.use_summaries
        ),
    )
    eval_writers = {
        name: MetricsWriter(
            os.path.join(model_dir, eval_dir_name(name)),
            use_tensorboard=False,
        )
        for name in eval_generators
    }

    hooks: List[Hook] = []
    for builder in hook_builders or []:
        hooks.extend(builder.create_hooks(model, trainer=compiled))
    ctx = HookContext(model=model, model_dir=model_dir, step=start_step,
                      state=compiled.persistable_state(state))
    for hook in hooks:
        hook.on_train_begin(ctx)

    exporters = (
        create_exporters_fn(model) if create_exporters_fn is not None else []
    )

    def run_eval_and_export(state, step: int) -> Dict[str, float]:
        eval_metrics = run_named_evals(
            compiled,
            state,
            eval_generators,
            eval_steps=eval_steps,
            use_ema=use_ema_for_eval,
            step=step,
            writers=eval_writers,
        )
        for exporter in exporters:
            exporter.maybe_export(
                step=step,
                state=state,
                eval_metrics=eval_metrics,
                compiled=compiled,
                model_dir=model_dir,
            )
        ctx.step = step
        ctx.state = state
        ctx.eval_metrics = eval_metrics
        for hook in hooks:
            hook.after_eval(ctx)
        return eval_metrics

    final_eval: Dict[str, float] = {}
    step = start_step
    t_last = time.time()
    last_log_step = start_step
    last_saved_step = start_step
    host_batches = itertools.chain([first_batch], train_batches)
    if start_step > 0:
        # Crash-consistency contract: step k of a RESUMED run must see
        # the same batch step k of an uninterrupted run saw, or the
        # replayed trajectory diverges from the one the crash
        # interrupted. Deterministic generators restart their stream
        # from batch 0 each process, so skip the batches the restored
        # steps already consumed. (Linear in start_step — the price of
        # replay-exactness; shuffled real-data pipelines were never
        # bitwise-resumable and merely skip cheap host parses here.)
        host_batches = itertools.islice(host_batches, start_step, None)

    # Collective observability (quantized ZeRO-2 regime only): byte
    # counters plus a one-off wall-time probe, merged into every log
    # record so the metrics stream carries the comms cost alongside
    # steps_per_sec. Empty dict everywhere else.
    collective_info = compiled.collective_log_record()

    def log_metrics(step: int, metrics) -> Dict[str, float]:
        nonlocal t_last, last_log_step
        host_metrics = {
            key: float(value)
            for key, value in jax.device_get(metrics).items()
            if getattr(value, "ndim", 0) == 0
        }
        now = time.time()
        host_metrics["steps_per_sec"] = (
            (step - last_log_step) / max(now - t_last, 1e-9)
        )
        host_metrics.update(collective_info)
        t_last = now
        last_log_step = step
        writer.write(step, host_metrics)
        return host_metrics

    # after_checkpoint_saved's contract is a DURABLE on-disk checkpoint
    # (backup/eval hooks read ctx.checkpoint_path); only when such a hook
    # is actually installed does the loop pay a finalize barrier. Plain
    # runs let the async save overlap the next train window and finalize
    # at exit (the `finally` below) or at the next save (orbax serializes
    # saves internally).
    ckpt_hooks_present = any(
        type(hook).after_checkpoint_saved is not Hook.after_checkpoint_saved
        for hook in hooks
    )

    def checkpoint_and_eval(state, step: int) -> Dict[str, float]:
        nonlocal last_saved_step
        # Fused-stats states persist (and face hooks/exporters/eval) in
        # the canonical tree layout — the on-disk format never changes.
        state = compiled.persistable_state(state)
        previous_saved = last_saved_step
        # Async save: orbax snapshots device arrays to host memory before
        # returning, then writes in the background — the next scan window
        # dispatches immediately instead of stalling on serialization.
        manager.save(step, args=ocp.args.StandardSave(state), force=True)
        # Issuing this save was the commit barrier for the PREVIOUS one
        # (orbax serializes saves): publish its durability manifest.
        # No-op when no prior save exists (previous_saved is start_step
        # on the first call; publish_durable ignores absent dirs).
        durability.publish_durable(model_dir, previous_saved)
        # Chaos site: the async write for `step` is now in flight — a
        # `kill` clause here is the SIGKILL-mid-orbax-save fault the
        # crash-consistency suite injects. (After the previous step's
        # blessing: a crash mid-save must not cost the durable past.)
        chaos.maybe_fire("save")
        last_saved_step = step
        ctx.checkpoint_path = str(
            os.path.join(model_dir, "checkpoints", str(step))
        )
        if ckpt_hooks_present:
            manager.wait_until_finished()
            durability.publish_durable(model_dir, step)
        for hook in hooks:
            hook.after_checkpoint_saved(ctx)
        return run_eval_and_export(state, step)

    try:
        if iterations_per_loop <= 1:
            device_batches = infeed.device_prefetch(
                host_batches, compiled.shard_batch, depth=infeed_depth
            )
            for batch in device_batches:
                if step >= max_train_steps:
                    break
                ctx.step = step
                for hook in hooks:
                    hook.before_step(ctx)
                state, metrics = compiled.train_step(state, batch, rng_train)
                step += 1
                ctx.step = step
                ctx.state = state
                # Full per-step metric tree as device arrays (hooks fetch
                # lazily; golden-value capture reads non-scalar entries).
                ctx.device_metrics = metrics
                if step % log_every_steps == 0 or step == max_train_steps:
                    ctx.metrics = log_metrics(step, metrics)
                else:
                    ctx.metrics = None
                for hook in hooks:
                    hook.after_step(ctx)
                if step % save_checkpoints_steps == 0 or step == max_train_steps:
                    final_eval = checkpoint_and_eval(state, step)
        else:
            # Multi-step regime: chunk sizes clamp at checkpoint boundaries
            # so every checkpoint still lands on its exact step.
            def chunk_sizes():
                s = step
                while s < max_train_steps:
                    boundary = min(
                        max_train_steps,
                        (s // save_checkpoints_steps + 1) * save_checkpoints_steps,
                    )
                    k = min(iterations_per_loop, boundary - s)
                    yield k
                    s += k

            def stacked_chunks():
                for k in chunk_sizes():
                    chunk = list(itertools.islice(host_batches, k))
                    if len(chunk) < k:
                        return  # host data exhausted
                    yield infeed.stack_batches(chunk)

            device_chunks = infeed.device_prefetch(
                stacked_chunks(),
                lambda s: infeed.shard_stacked_batch(s, compiled.mesh),
                depth=infeed_depth,
            )
            for device_chunk in device_chunks:
                k = int(jax.tree_util.tree_leaves(device_chunk)[0].shape[0])
                ctx.step = step
                for hook in hooks:
                    hook.before_step(ctx)
                state, stacked_metrics = compiled.train_scan(
                    state, device_chunk, rng_train
                )
                step += k
                ctx.step = step
                ctx.state = state
                # Hooks observe loop granularity: the final step's metrics.
                ctx.device_metrics = jax.tree_util.tree_map(
                    lambda leaf: leaf[-1], stacked_metrics
                )
                if step % log_every_steps < k or step == max_train_steps:
                    ctx.metrics = log_metrics(step, ctx.device_metrics)
                else:
                    ctx.metrics = None
                for hook in hooks:
                    hook.after_step(ctx)
                if step % save_checkpoints_steps == 0 or step == max_train_steps:
                    final_eval = checkpoint_and_eval(state, step)
                if step >= max_train_steps:
                    break

        if step > last_saved_step:
            # Host data exhausted mid-interval: checkpoint the trained steps
            # instead of silently dropping them.
            final_eval = checkpoint_and_eval(state, step)

    finally:
        # The last per-step assignment may have left the live fused form
        # on the context; terminal hooks (e.g. the async exporter's final
        # synchronous export) get the canonical layout.
        if ctx.state is not None:
            ctx.state = compiled.persistable_state(ctx.state)
        for hook in hooks:
            hook.on_train_end(ctx)
        writer.close()
        for eval_writer in eval_writers.values():
            eval_writer.close()
        manager.wait_until_finished()
        # Exit barrier: the final async save is committed — publish its
        # durability manifest so the next run restores from it without
        # falling back to the structural check (no-op when nothing saved).
        durability.publish_durable(model_dir, last_saved_step)
        manager.close()
        _save_operative_config(model_dir)
    return final_eval


def _save_operative_config(model_dir: str) -> None:
    """Persists the operative config artifact (gin parity: the reference's
    GinConfigSaverHook wrote the operative config on the chief,
    models/abstract_model.py:772-775)."""
    from tensor2robot_tpu import config as cfg_mod

    try:
        cfg_mod.save_operative_config(model_dir)
    except OSError as e:
        import logging

        logging.warning("Could not write operative config to %s: %s", model_dir, e)


def predict_from_model(
    t2r_model: AbstractT2RModel,
    input_generator,
    model_dir: str,
    mesh=None,
) -> Iterator[TensorSpecStruct]:
    """Restores the latest checkpoint and yields export outputs per batch
    (reference predict_from_model :389-419)."""
    model = maybe_wrap_for_tpu(t2r_model)
    compiled = CompiledModel(model, mesh=mesh, donate_state=False)
    provide_input_generator_with_model_information(
        input_generator, model, MODE_PREDICT
    )
    batches = iter(input_generator.create_dataset(MODE_PREDICT))
    first = next(batches)
    manager = create_checkpoint_manager(model_dir, save_interval_steps=1)
    if latest_durable_step_in(manager) is None:
        raise FileNotFoundError(
            f"No durable checkpoint found under {model_dir!r}; refusing to "
            "serve randomly-initialized (or torn) weights. Use init_randomly "
            "on a predictor if that is intended."
        )
    state = restore_or_init_state(
        manager, compiled, jax.random.PRNGKey(0), first
    )
    use_ema = getattr(model, "use_avg_model_params", False)
    variables = state.export_variables(use_ema=use_ema)

    def predict(batch):
        batch = compiled.shard_batch(batch)
        features, _ = compiled.preprocessor.preprocess(
            batch["features"],
            batch.get("labels"),
            mode=MODE_PREDICT,
            rng=None,
        )
        return jax.device_get(compiled.predict_step(variables, features))

    yield predict(first)
    for batch in batches:
        yield predict(batch)
