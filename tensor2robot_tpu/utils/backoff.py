"""One jittered-backoff implementation for every retry loop.

Before this module, three subsystems each hand-rolled the same
exponential-backoff-with-jitter formula — the fleet router's
retry-elsewhere path (serving/router.py), the replay client's
retry-through-restart path (replay/service.py), and the actor gateway's
serving-brown-out fallback (replay/actor.py) — with three subtly
different cap disciplines, and the replay client's with NO total-time
bound at all: a dead replay service could hold an actor in backoff past
its episode deadline. Retry pacing is a fleet-wide contract, not a
per-module style choice, so it lives here once.

The schedule is DETERMINISTIC given the seed: delay k is

    min(base * factor**(k-1) * (1 + U[0,1)), cap)        (k = attempt, 1-based)

with ``U`` drawn from a private ``random.Random(seed)`` in call order —
a fixed seed replays the exact pacing, which is what lets the chaos
suites assert timing-adjacent behavior without wall-clock flakiness.

Two hard caps, both explicit:

  * ``cap_ms`` bounds any single delay (None = uncapped; the router's
    deadline already bounds it there);
  * ``total_ms`` bounds the SUM of time this instance may spend —
    sleeping or waiting — across one logical operation: ``start()``
    arms the budget, ``remaining_s()``/``expired()`` read it, and
    ``sleep()`` refuses (returns False) rather than overshoot it.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional

__all__ = ["Backoff", "poll_loop"]


def poll_loop(fn: Callable) -> Callable:
    """Allowlist marker for a sanctioned FIXED-INTERVAL monitor loop.

    The `sleep-retry-outside-backoff` lint (analysis/lints.py) bans bare
    `time.sleep` retry/poll loops in serving/ and replay/ — every
    bounded wait must ride a seeded Backoff schedule with a hard total
    bound. The exception is a daemon monitor that ticks forever at a
    fixed cadence by design (a respawn watcher, a queue drain): those
    declare themselves with this decorator, which makes the exemption
    grep-able and reviewable instead of implicit.
    """
    fn.__poll_loop__ = True
    return fn


class Backoff:
    """Seeded jittered exponential backoff with per-delay and total caps.

    Typical retry-loop shape::

        backoff = Backoff(base_ms=50, cap_ms=2000, total_ms=15000, seed=7)
        backoff.start()
        for attempt in range(retries + 1):
            if attempt and not backoff.sleep(attempt):
                break                       # total budget exhausted
            ...one attempt, bounded by min(op_timeout, backoff.remaining_s())
        raise Unavailable(...)

    Schedulers that never sleep (the router posts a timer instead) use
    ``delay_s(attempt)`` alone.
    """

    def __init__(
        self,
        base_ms: float = 50.0,
        cap_ms: Optional[float] = 2000.0,
        factor: float = 2.0,
        total_ms: Optional[float] = None,
        seed: int = 0,
    ):
        if base_ms < 0:
            raise ValueError(f"base_ms must be >= 0, got {base_ms}")
        if cap_ms is not None and cap_ms < 0:
            raise ValueError(f"cap_ms must be >= 0, got {cap_ms}")
        if factor < 1.0:
            raise ValueError(f"factor must be >= 1, got {factor}")
        self.base_ms = base_ms
        self.cap_ms = cap_ms
        self.factor = factor
        self.total_ms = total_ms
        self._rng = random.Random(seed)
        self._deadline: Optional[float] = None

    def delay_s(self, attempt: int) -> float:
        """The next delay in seconds for 1-based retry `attempt`.

        Draws one jitter sample per call — the deterministic schedule is
        a property of (seed, call order), so callers must request
        delays in the order they apply them.
        """
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        delay_ms = (
            self.base_ms
            * (self.factor ** (attempt - 1))
            * (1.0 + self._rng.random())
        )
        if self.cap_ms is not None:
            delay_ms = min(delay_ms, self.cap_ms)
        return delay_ms / 1e3

    # -- the total-time budget -------------------------------------------------

    def start(self, total_s: Optional[float] = None) -> "Backoff":
        """Arms (or re-arms) the total-time budget for one logical
        operation. `total_s` overrides the constructor's total_ms for
        THIS arming (callers whose bound arrives per call, like a
        wait_ready timeout). A no-op when neither is set."""
        if total_s is not None:
            self._deadline = time.monotonic() + total_s
        elif self.total_ms is not None:
            self._deadline = time.monotonic() + self.total_ms / 1e3
        else:
            self._deadline = None
        return self

    def remaining_s(self) -> float:
        """Seconds left in the budget (inf when unbounded). Callers use
        this to clip per-attempt waits so the LAST attempt cannot
        overshoot the budget either."""
        if self._deadline is None:
            return float("inf")
        return max(0.0, self._deadline - time.monotonic())

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def sleep(self, attempt: int) -> bool:
        """Sleeps the schedule's delay for `attempt`; returns False —
        WITHOUT sleeping past the budget — when the total budget cannot
        cover the delay (the caller should stop retrying)."""
        delay = self.delay_s(attempt)
        remaining = self.remaining_s()
        if remaining <= 0.0:
            return False
        if delay > remaining:
            # Sleeping the remainder then attempting would overshoot:
            # the budget is a promise to the CALLER's caller (an actor's
            # episode deadline), so refuse instead.
            return False
        time.sleep(delay)
        return True

    def poll(self, predicate: Callable[[], object],
             total_s: Optional[float] = None):
        """Calls `predicate()` on the seeded schedule until it returns a
        truthy value or the total budget expires; returns the FINAL
        predicate value (one last call after the schedule refuses, so a
        condition that lands during the closing delay is not missed).
        Every poll is bounded by construction: raises ValueError when
        neither total_ms nor `total_s` supplies a budget — an unbounded
        predicate wait is exactly the hang this module exists to ban.
        Poll cadence wants a roughly-fixed interval, so construct with
        factor=1.0 (jitter alone spreads concurrent pollers)."""
        self.start(total_s)
        if self._deadline is None:
            raise ValueError(
                "Backoff.poll needs a total budget (total_ms or total_s)"
            )
        attempt = 0
        while True:
            result = predicate()
            if result:
                return result
            attempt += 1
            if not self.sleep(attempt):
                return predicate()
