"""Continuous on-robot collect/eval loop.

The robot-process side of the async actor/learner topology: poll-restore the
newest exported policy, run collection episodes into the replay bus, run
eval episodes, repeat until the learner passes max_steps (reference
utils/continuous_collect_eval.py:28-108; process topology README.md:44-51).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Optional

from tensor2robot_tpu.config import configurable


@configurable("collect_eval_loop")
def collect_eval_loop(
    root_dir: str,
    policy,
    run_agent_fn: Callable,
    collect_env=None,
    eval_env=None,
    num_collect: int = 10,
    num_eval: int = 5,
    min_global_step: int = 0,
    max_steps: int = 1_000_000,
    idle_sleep_secs: float = 10.0,
    init_randomly_on_timeout: bool = False,
    max_cycles: Optional[int] = None,
) -> int:
    """Runs collect+eval cycles; returns the last seen global step.

    Per cycle: restore the policy's newest weights; if the learner hasn't
    advanced (or is below min_global_step), sleep and re-poll; otherwise run
    `run_agent_fn(env, policy, num_episodes, output_dir, global_step)` on
    the collect env then the eval env. Stops once global_step >= max_steps
    (reference :80-108).

    Args:
      root_dir: collect episodes land in <root_dir>/policy_collect, eval
        episodes in <root_dir>/policy_eval (reference dir layout).
      policy: a policies.Policy.
      run_agent_fn: the episode runner (research/run_env.run_env adapted:
        fn(env, policy, num_episodes, output_dir, global_step)).
      init_randomly_on_timeout: serve random weights when no export appears
        (bring-up mode).
      max_cycles: optional cycle cap for tests.
    """
    collect_dir = os.path.join(root_dir, "policy_collect")
    eval_dir = os.path.join(root_dir, "policy_eval")
    os.makedirs(collect_dir, exist_ok=True)
    os.makedirs(eval_dir, exist_ok=True)

    last_global_step = -1
    cycles = 0
    while True:
        if not policy.restore():
            if init_randomly_on_timeout and last_global_step < 0:
                logging.warning("No exported policy yet; initializing randomly.")
                policy.init_randomly()
            else:
                logging.info("No new policy available; sleeping.")
                time.sleep(idle_sleep_secs)
                cycles += 1
                if max_cycles is not None and cycles >= max_cycles:
                    return last_global_step
                continue
        global_step = policy.global_step
        if global_step == last_global_step or global_step < min_global_step:
            time.sleep(idle_sleep_secs)
            cycles += 1
            if max_cycles is not None and cycles >= max_cycles:
                return last_global_step
            continue
        last_global_step = global_step
        if collect_env is not None:
            run_agent_fn(
                collect_env,
                policy=policy,
                num_episodes=num_collect,
                output_dir=collect_dir,
                global_step=global_step,
            )
        if eval_env is not None:
            run_agent_fn(
                eval_env,
                policy=policy,
                num_episodes=num_eval,
                output_dir=eval_dir,
                global_step=global_step,
            )
        cycles += 1
        if global_step >= max_steps:
            return global_step
        if max_cycles is not None and cycles >= max_cycles:
            return last_global_step
