"""Cross-entropy method (CEM) optimizer for action selection.

Generic sample/objective/update loop with elite selection and optional
early termination — the action-optimization engine behind CEMPolicy
(reference utils/cross_entropy.py:31-155). Runs in numpy on the robot host:
at 1-10 Hz control rates the accelerator-bound piece is the batched critic
evaluation inside `objective_fn`, which scores a whole population in one
forward pass (the action-tiling path, models/base_models.py
tile_actions_for_cem).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np


class CrossEntropyMethod:
    """Iterative elite-refit optimizer over a diagonal-Gaussian proposal."""

    def __init__(
        self,
        sample_fn: Optional[Callable] = None,
        update_fn: Optional[Callable] = None,
        elite_fraction: float = 0.1,
        num_samples: int = 64,
        num_iterations: int = 3,
        early_termination_stddev: Optional[float] = None,
        seed: Optional[int] = None,
        smoothing: float = 0.3,
    ):
        """Args:
        sample_fn: (mean, stddev, n, rng) -> [n, ...] candidate batch;
          defaults to an (unclipped) diagonal Gaussian — callers with box
          bounds pass a clipping sample_fn (see CEMPolicy).
        update_fn: (elites) -> (mean, stddev); defaults to moment matching.
        elite_fraction: top fraction refit each iteration.
        num_samples: population size per iteration.
        num_iterations: refit rounds.
        early_termination_stddev: stop once max(stddev) falls below this
          (reference early-terminate threshold, cross_entropy.py:120-130).
        seed: rng seed (None = nondeterministic).
        smoothing: exponential smoothing applied AFTER update_fn (next =
          (1-a)*update + a*previous). Small elite sets (QT-Opt runs ~3)
          make moment-matched stddev a noisy underestimate that collapses
          the proposal around an early suboptimal mean; smoothing keeps
          exploration alive (at 32 samples/3 elites/8 iterations the
          miss rate drops ~25% of seeds -> <1%). Keep in sync with the
          jitted engine, ops/cem.py. 0 restores raw refit.
        """
        self._sample_fn = sample_fn or self._default_sample
        self._update_fn = update_fn or self._default_update
        self._elite_fraction = elite_fraction
        self._num_samples = num_samples
        self._num_iterations = num_iterations
        self._early_stddev = early_termination_stddev
        self._smoothing = smoothing
        self._rng = np.random.RandomState(seed)

    @staticmethod
    def _default_sample(mean, stddev, n, rng):
        samples = rng.normal(
            loc=mean[None, ...], scale=stddev[None, ...], size=(n,) + mean.shape
        )
        return samples.astype(mean.dtype, copy=False)

    @staticmethod
    def _default_update(elites):
        return elites.mean(axis=0), elites.std(axis=0) + 1e-6

    def run(
        self,
        objective_fn: Callable[[np.ndarray], np.ndarray],
        initial_mean: np.ndarray,
        initial_stddev: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, float]:
        """Maximizes objective_fn.

        Args:
          objective_fn: [n, ...] candidates -> [n] scores (bigger = better).
          initial_mean / initial_stddev: proposal distribution seeds.

        Returns:
          (mean, stddev, best_sample, best_score) after the final iteration.
        """
        mean = np.asarray(initial_mean, dtype=np.float64).copy()
        stddev = np.asarray(initial_stddev, dtype=np.float64).copy()
        num_elites = max(1, int(self._num_samples * self._elite_fraction))
        best_sample, best_score = mean, -np.inf
        for _ in range(self._num_iterations):
            samples = self._sample_fn(mean, stddev, self._num_samples, self._rng)
            scores = np.asarray(objective_fn(samples), dtype=np.float64)
            if scores.shape != (len(samples),):
                raise ValueError(
                    f"objective_fn must return [{len(samples)}] scores, got "
                    f"{scores.shape}."
                )
            elite_idx = np.argsort(scores)[-num_elites:]
            if scores[elite_idx[-1]] > best_score:
                best_score = float(scores[elite_idx[-1]])
                best_sample = samples[elite_idx[-1]].copy()
            new_mean, new_stddev = self._update_fn(samples[elite_idx])
            alpha = self._smoothing
            mean = (1.0 - alpha) * np.asarray(new_mean) + alpha * mean
            stddev = (1.0 - alpha) * np.asarray(new_stddev) + alpha * stddev
            if self._early_stddev is not None and np.max(stddev) < self._early_stddev:
                break
        return mean, stddev, best_sample, best_score


def cem_maximize(
    objective_fn: Callable[[np.ndarray], np.ndarray],
    initial_mean: np.ndarray,
    initial_stddev: np.ndarray,
    num_samples: int = 64,
    num_iterations: int = 3,
    elite_fraction: float = 0.1,
    seed: Optional[int] = None,
    smoothing: float = 0.3,
) -> Tuple[np.ndarray, float]:
    """One-call CEM: returns (best_sample, best_score)."""
    cem = CrossEntropyMethod(
        num_samples=num_samples,
        num_iterations=num_iterations,
        elite_fraction=elite_fraction,
        seed=seed,
        smoothing=smoothing,
    )
    _, _, best, score = cem.run(objective_fn, initial_mean, initial_stddev)
    return best, score
