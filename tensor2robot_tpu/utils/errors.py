"""Sanctioned best-effort execution for teardown/cleanup paths.

The `swallowed-exception` lint (analysis/lints.py) bans silent handlers
(`except: pass`, `except Exception: pass`) inside `serving/`, `train/`
and `predictors/` — in a fault-tolerant fleet, an invisible swallow is
how a real failure (a replica that cannot reply, a checkpoint that
cannot finalize) degrades into an unexplained hang or a silent data
loss. But teardown paths legitimately do not care: returning a slot to
a queue the router already closed, closing shared memory the other end
unlinked. Those sites say so EXPLICITLY, one of two ways:

  * call through :func:`best_effort` — no except block at the call site
    at all, and the one sanctioned swallow lives here, greppable; or
  * decorate the enclosing function with :func:`best_effort_cleanup`,
    the lint's allowlist marker, when the handler needs structure a
    plain call wrapper cannot express.

Either way the intent is in the code, not in a linter ignore comment.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, TypeVar

F = TypeVar("F", bound=Callable[..., Any])

__all__ = ["best_effort", "best_effort_cleanup"]


def best_effort_cleanup(fn: F) -> F:
    """Marks `fn` as an allowlisted swallow site for the
    `swallowed-exception` lint: silent broad handlers inside it are
    accepted. Use only on small, single-purpose cleanup functions — the
    allowlist covers the whole decorated body."""
    fn.__t2r_best_effort__ = True
    return fn


@best_effort_cleanup
def best_effort(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Optional[Any]:
    """Calls ``fn(*args, **kwargs)`` swallowing ``Exception`` (never
    ``BaseException`` — KeyboardInterrupt/SystemExit still propagate).
    Returns the call's result, or None when it raised."""
    try:
        return fn(*args, **kwargs)
    except Exception:  # the one sanctioned swallow; see module docstring
        return None
