"""Schedules of global_step usable as any scalar hyperparameter.

Behavioral reference: tensor2robot/utils/global_step_functions.py:28-123
(`piecewise_linear`, `exponential_decay`). The reference materialized the
schedule as a graph tensor reading the global-step variable; here schedules
are pure functions step -> value (optax-convention), gin-bindable as
factories.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.config import configurable


@configurable("piecewise_linear")
def piecewise_linear(
    boundaries: Sequence[float], values: Sequence[float]
) -> Callable:
    """Linear interpolation through (boundaries, values) knots; clamped to
    values[0] before the first boundary and values[-1] after the last
    (reference piecewise_linear :28-96)."""
    boundaries = np.asarray(boundaries, np.float32)
    values = np.asarray(values, np.float32)
    if boundaries.size == 0 or values.size == 0:
        raise ValueError("Need more than 0 boundaries/values.")
    if boundaries.size != values.size:
        raise ValueError("boundaries and values must be of same size.")
    if np.any(np.diff(boundaries) <= 0):
        raise ValueError("boundaries must be strictly increasing.")

    def schedule(step):
        x = jnp.asarray(step, jnp.float32)
        return jnp.interp(
            x, jnp.asarray(boundaries), jnp.asarray(values)
        )

    return schedule


@configurable("exponential_decay_value")
def exponential_decay(
    initial_value: float = 0.0001,
    decay_steps: int = 10000,
    decay_rate: float = 0.9,
    staircase: bool = True,
) -> Callable:
    """initial_value * decay_rate ** (step / decay_steps)
    (reference exponential_decay :99-123)."""

    def schedule(step):
        exponent = jnp.asarray(step, jnp.float32) / decay_steps
        if staircase:
            exponent = jnp.floor(exponent)
        return initial_value * jnp.power(decay_rate, exponent)

    return schedule
