"""Image encoding helpers (reference tensor2robot/utils/image.py:23-49)."""

from __future__ import annotations

import io

import numpy as np


def jpeg_string(image, jpeg_quality: int = 90) -> bytes:
    """Returns a JPEG-encoded bytestring of a PIL image
    (reference jpeg_string :23-37)."""
    output = io.BytesIO()
    image.save(output, format="JPEG", quality=jpeg_quality)
    return output.getvalue()


def numpy_to_image_string(
    image_array: np.ndarray, image_format: str = "jpeg", dtype=np.uint8
) -> bytes:
    """Encodes a numpy HWC array as an image bytestring
    (reference numpy_to_image_string :40-49)."""
    from PIL import Image

    pil_image = Image.fromarray(np.asarray(image_array, dtype=dtype))
    output = io.BytesIO()
    pil_image.save(output, format=image_format.upper())
    return output.getvalue()
