"""Keypath helpers shared by pytree-path-addressed features (PCGrad
allow/deny masks, MAML var_scope adaptation filters)."""

from __future__ import annotations


def path_string(path) -> str:
    """'/'-joins a jax.tree_util keypath into the familiar variable-name
    form, e.g. ('params', 'dense', 'kernel') -> 'params/dense/kernel'."""
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)
