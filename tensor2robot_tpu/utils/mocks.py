"""Mock models and input generators — the backbone of the test suite.

Parity with tensor2robot/utils/mocks.py: `MockT2RModel` is a 3-layer MLP
with batch norm over a 3-vector input predicting one logit;
`MockInputGenerator` emits a deterministic linearly-separable dataset so a
few hundred steps of training must converge (the reference's
train_eval_test gate).
"""

from __future__ import annotations

from typing import Iterator, Optional

import flax.linen as nn

from tensor2robot_tpu.layers.batch_norm import BatchNorm
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.data.input_generators import AbstractInputGenerator
from tensor2robot_tpu.models.base_models import ClassificationModel
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

_FEATURE_DIM = 3


class _MockNetwork(nn.Module):
    """3-layer MLP + batch norm (mirrors the mock network's capacity)."""

    use_batch_norm: bool = True

    @nn.compact
    def __call__(self, features, mode: str):
        x = features["x"]
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        for width in (100, 100):
            x = nn.Dense(width)(x)
            if self.use_batch_norm:
                x = BatchNorm(
                    use_running_average=(mode != "train"), momentum=0.9
                )(x)
            x = nn.relu(x)
        logit = nn.Dense(1)(x)
        out = TensorSpecStruct()
        out["a_predicted"] = logit
        return out


class MockT2RModel(ClassificationModel):
    """Minimal end-to-end-trainable model (reference mocks.py:99-189)."""

    def __init__(self, device_type: str = "cpu", use_batch_norm: bool = True,
                 multi_dataset: bool = False, **kwargs):
        super().__init__(device_type=device_type, **kwargs)
        self._use_batch_norm = use_batch_norm
        self._multi_dataset = multi_dataset

    def create_network(self):
        return _MockNetwork(use_batch_norm=self._use_batch_norm)

    def get_feature_specification(self, mode: str) -> TensorSpecStruct:
        spec = TensorSpecStruct()
        if self._multi_dataset:
            spec["x"] = ExtendedTensorSpec(
                shape=(_FEATURE_DIM,), dtype=np.float32, name="measured_position",
                dataset_key="dataset1",
            )
        else:
            spec["x"] = ExtendedTensorSpec(
                shape=(_FEATURE_DIM,), dtype=np.float32, name="measured_position"
            )
        return spec

    def get_label_specification(self, mode: str) -> TensorSpecStruct:
        spec = TensorSpecStruct()
        if self._multi_dataset:
            spec["a_target"] = ExtendedTensorSpec(
                shape=(1,), dtype=np.float32, name="valid_position",
                dataset_key="dataset2",
            )
        else:
            spec["a_target"] = ExtendedTensorSpec(
                shape=(1,), dtype=np.float32, name="valid_position"
            )
        return spec


class MockInputGenerator(AbstractInputGenerator):
    """Deterministic linearly-separable data: label = x0 + x1 + x2 > 0
    (reference mocks.py:43-96)."""

    def __init__(self, batch_size: int = 32, seed: int = 0):
        super().__init__(batch_size=batch_size)
        self._seed = seed

    def _create_dataset(self, mode: str) -> Iterator[TensorSpecStruct]:
        rng = np.random.RandomState(self._seed)
        while True:
            x = rng.uniform(-1.0, 1.0, size=(self._batch_size, _FEATURE_DIM))
            y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
            batch = TensorSpecStruct()
            batch["features/x"] = x.astype(np.float32)
            batch["labels/a_target"] = y
            yield batch

    def create_numpy_data(self, num_examples: int = 256):
        rng = np.random.RandomState(self._seed)
        x = rng.uniform(-1.0, 1.0, size=(num_examples, _FEATURE_DIM))
        y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
        return x.astype(np.float32), y
