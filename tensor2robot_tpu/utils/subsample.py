"""Fixed-length random subsampling of padded sequences.

Behavioral reference: tensor2robot/utils/subsample.py:23-191
(`get_subsample_indices`, `get_subsample_indices_randomized_boundary`).
Sampling always keeps the first and last valid frame; middle frames sample
without replacement when the sequence is long enough, with replacement
otherwise; min_length==1 picks one random frame.

TPU notes: the reference's per-sequence tf.cond/map_fn becomes branchless
masked sampling under vmap — one fused program with static shapes, no
dynamic control flow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _single_sequence_indices(
    rng: jax.Array,
    sequence_length: jax.Array,
    min_length: int,
    max_sequence_length: int,
) -> jax.Array:
    """Indices for one sequence; jit/vmap-safe (static min/max lengths)."""
    sequence_length = sequence_length.astype(jnp.int32)
    if min_length == 1:
        u = jax.random.uniform(rng, (1,))
        return jnp.floor(u * sequence_length).astype(jnp.int32)

    num_middle = min_length - 2
    rng_perm, rng_unif = jax.random.split(rng)

    # Without replacement: the num_middle smallest-random-keyed positions of
    # [1, seq_len-1) — a branchless random shuffle with invalid (padding)
    # candidates pushed to +inf.
    positions = jnp.arange(1, max_sequence_length + 1, dtype=jnp.int32)
    valid = positions < sequence_length - 1
    keys = jnp.where(
        valid, jax.random.uniform(rng_perm, positions.shape), jnp.inf
    )
    order = jnp.argsort(keys)
    middle_wo = jnp.sort(positions[order[:num_middle]])

    # With replacement: uniform draws over [0, seq_len).
    u = jax.random.uniform(rng_unif, (num_middle,))
    middle_w = jnp.sort(jnp.floor(u * sequence_length).astype(jnp.int32))

    middle = jnp.where(sequence_length >= min_length, middle_wo, middle_w)
    first = jnp.zeros((1,), jnp.int32)
    last = jnp.maximum(sequence_length - 1, 0)[None]
    return jnp.concatenate([first, middle, last])


def get_subsample_indices(
    rng: jax.Array,
    sequence_lengths: jax.Array,
    min_length: int,
    max_sequence_length: int = 512,
) -> jax.Array:
    """[B] lengths -> [B, min_length] subsample indices
    (reference get_subsample_indices :23-79).

    Args:
      rng: random key.
      sequence_lengths: [B] valid lengths (tensors are padded beyond them).
      min_length: output frames per sequence; first/last always kept.
      max_sequence_length: static bound on sequence length (sets the
        candidate-buffer width; any padded batch length fits the default).
    """
    sequence_lengths = jnp.asarray(sequence_lengths)
    rngs = jax.random.split(rng, sequence_lengths.shape[0])
    return jax.vmap(
        lambda r, n: _single_sequence_indices(
            r, n, min_length, max_sequence_length
        )
    )(rngs, sequence_lengths)


def get_subsample_indices_randomized_boundary(
    rng: jax.Array,
    sequence_lengths: jax.Array,
    min_length: int,
    min_delta_t: int,
    max_delta_t: int,
    max_sequence_length: int = 512,
) -> jax.Array:
    """Like get_subsample_indices but over a random [start, start+dt) window
    of each sequence (reference :82-152)."""
    sequence_lengths = jnp.asarray(sequence_lengths).astype(jnp.int32)

    def one(rng, sequence_length):
        rng_dt, rng_start, rng_sample = jax.random.split(rng, 3)
        episode_delta_t = jax.random.randint(
            rng_dt, (), min_delta_t, max_delta_t + 1
        )
        episode_delta_t = jnp.minimum(episode_delta_t, sequence_length)
        episode_start = jax.random.randint(
            rng_start, (), 0,
            jnp.maximum(sequence_length - episode_delta_t + 1, 1),
        )
        window_indices = _single_sequence_indices(
            rng_sample, episode_delta_t, min_length, max_sequence_length
        )
        return episode_start + window_indices

    rngs = jax.random.split(rng, sequence_lengths.shape[0])
    return jax.vmap(one)(rngs, sequence_lengths)
