"""Test fixture running the REAL trainer on tiny workloads.

Behavioral reference: tensor2robot/utils/t2r_test_fixture.py:36-195
(`T2RModelFixture`): `random_train` / `recordio_train` / `random_predict`
run the actual `train_eval_model` for a couple of steps at tiny batch size;
`train_and_check_golden_predictions` trains on a fixed record and numpy-
compares captured golden values against a stored golden file, catching
data->checkpoint regressions.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from tensor2robot_tpu.data.input_generators import (
    DefaultRandomInputGenerator,
    DefaultRecordInputGenerator,
)
from tensor2robot_tpu.hooks.golden_values_hook_builder import (
    GoldenValuesHookBuilder,
    load_golden_values,
)
from tensor2robot_tpu.train import train_eval

TRAIN_STEPS = 2
BATCH_SIZE = 2


class T2RModelFixture:
    """Runs models through the real trainer (reference :36-112)."""

    def __init__(self, test_case=None, use_tpu: bool = False):
        self._test_case = test_case
        self._use_tpu = use_tpu

    def random_train(
        self,
        model,
        model_dir: str,
        train_steps: int = TRAIN_STEPS,
        batch_size: int = BATCH_SIZE,
        **kwargs,
    ) -> Dict[str, float]:
        """Trains on spec-conforming random data (reference :56-83)."""
        return train_eval.train_eval_model(
            t2r_model=model,
            input_generator_train=DefaultRandomInputGenerator(
                batch_size=batch_size
            ),
            model_dir=model_dir,
            max_train_steps=train_steps,
            save_checkpoints_steps=max(train_steps, 1),
            log_every_steps=1,
            **kwargs,
        )

    def recordio_train(
        self,
        model,
        model_dir: str,
        file_patterns: Sequence[str],
        train_steps: int = TRAIN_STEPS,
        batch_size: int = BATCH_SIZE,
        **kwargs,
    ) -> Dict[str, float]:
        """Trains on record files (reference :85-112)."""
        return train_eval.train_eval_model(
            t2r_model=model,
            input_generator_train=DefaultRecordInputGenerator(
                file_patterns=list(file_patterns),
                batch_size=batch_size,
                # Deterministic shuffle: golden-value comparison requires
                # identical data order across runs.
                seed=0,
            ),
            model_dir=model_dir,
            max_train_steps=train_steps,
            save_checkpoints_steps=max(train_steps, 1),
            log_every_steps=1,
            **kwargs,
        )

    def random_predict(self, model, model_dir: str, batch_size: int = BATCH_SIZE):
        """One prediction pass over random inputs (reference :114-140)."""
        generator = DefaultRandomInputGenerator(batch_size=batch_size)
        return next(
            iter(
                train_eval.predict_from_model(
                    t2r_model=model,
                    input_generator=generator,
                    model_dir=model_dir,
                )
            )
        )

    def train_and_check_golden_predictions(
        self,
        model,
        model_dir: str,
        file_patterns: Sequence[str],
        golden_data_path: str,
        train_steps: int = TRAIN_STEPS,
        batch_size: int = BATCH_SIZE,
        update_golden: bool = False,
        decimal: int = 5,
    ) -> List[Dict[str, np.ndarray]]:
        """Trains while recording golden tensors, then compares against the
        stored golden file (reference :142-195). With update_golden=True the
        stored file is (re)written instead of compared."""
        self.recordio_train(
            model,
            model_dir,
            file_patterns,
            train_steps=train_steps,
            batch_size=batch_size,
            hook_builders=[GoldenValuesHookBuilder(model_dir)],
        )
        values = load_golden_values(model_dir)
        if update_golden or not os.path.exists(golden_data_path):
            os.makedirs(os.path.dirname(golden_data_path), exist_ok=True)
            np.save(golden_data_path, np.asarray(values, dtype=object))
            return values
        golden = np.load(golden_data_path, allow_pickle=True)
        assert len(golden) == len(values), (
            f"Golden has {len(golden)} steps, run produced {len(values)}."
        )
        for step_index, (expected, actual) in enumerate(zip(golden, values)):
            assert set(expected.keys()) == set(actual.keys()), (
                f"Step {step_index}: keys {set(actual.keys())} != golden "
                f"{set(expected.keys())}"
            )
            for key in expected:
                np.testing.assert_almost_equal(
                    actual[key],
                    expected[key],
                    decimal=decimal,
                    err_msg=f"step {step_index} tensor {key!r}",
                )
        return values
