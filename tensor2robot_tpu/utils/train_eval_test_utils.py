"""Assertions + gin-config smoke harness for trainer outputs.

Behavioral reference: tensor2robot/utils/train_eval_test_utils.py:27-148
(`assert_output_files`, `test_train_eval_gin`): every shipped gin config
must run for a few steps and leave the standard artifact set behind.
"""

from __future__ import annotations

import glob
import os
from typing import Optional, Sequence

from tensor2robot_tpu import config as cfg


def assert_output_files(
    model_dir: str,
    expected_output_filename_patterns: Optional[Sequence[str]] = None,
) -> None:
    """Asserts the standard trainer artifacts exist
    (reference assert_output_files :27-67): checkpoints, operative config,
    train/eval metric streams."""
    if expected_output_filename_patterns is None:
        expected_output_filename_patterns = [
            "checkpoints/*",
            "operative_config.gin",
            "train/metrics.jsonl",
        ]
    for pattern in expected_output_filename_patterns:
        matches = glob.glob(os.path.join(model_dir, pattern))
        assert matches, (
            f"No files match {pattern!r} under {model_dir}; contents: "
            f"{sorted(glob.glob(os.path.join(model_dir, '**'), recursive=True))}"
        )


def test_train_eval_gin(
    model_dir: str,
    full_gin_path: str,
    max_train_steps: int = 3,
    eval_steps: int = 2,
    gin_overwrites_fn=None,
    assert_train_output_files: bool = True,
) -> None:
    """Executes a shipped gin config for a few steps
    (reference test_train_eval_gin :70-148)."""
    import tensor2robot_tpu.config.defaults  # noqa: F401  (registers surface)

    cfg.clear_config()
    try:
        cfg.parse_config_files_and_bindings([full_gin_path], [])
        if gin_overwrites_fn is not None:
            gin_overwrites_fn()
        cfg.bind_parameter("train_eval_model.model_dir", model_dir)
        cfg.bind_parameter("train_eval_model.max_train_steps", max_train_steps)
        cfg.bind_parameter("train_eval_model.eval_steps", eval_steps)
        train_eval_model = cfg.get_configurable("train_eval_model")
        train_eval_model()
        if assert_train_output_files:
            assert_output_files(model_dir)
    finally:
        cfg.clear_config()
