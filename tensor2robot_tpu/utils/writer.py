"""Replay writers: episode sinks for the collect/eval loop.

TFRecordReplayWriter appends serialized tf.Example transitions to sharded
TFRecord files — the robot-side half of the filesystem data bus the learner
reads (reference utils/writer.py:27-61). Uses the framework's native
TFRecord codec (data/tfrecord.py), no TF dependency.
"""

from __future__ import annotations

import abc
import os
import time
from typing import Iterable, Optional, Sequence, Union

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.data.tfrecord import TFRecordWriter


class ReplayWriter(abc.ABC):
    """open/write/close episode-sink contract."""

    @abc.abstractmethod
    def open(self, path: str) -> None:
        ...

    @abc.abstractmethod
    def write(self, serialized_records: Union[bytes, Sequence[bytes]]) -> None:
        ...

    @abc.abstractmethod
    def close(self) -> None:
        ...


def timestamped_record_path(
    output_dir: str, global_step: int, suffix: str = ""
) -> str:
    """The shard-naming convention learners glob for:
    <output_dir>/gs<step>_<timestamp>[_<suffix>] (reference run_env's
    root_dir/record_name layout). Shared by run_env and run_meta_env so the
    layouts cannot drift apart."""
    import datetime

    timestamp = datetime.datetime.now().strftime("%Y-%m-%d-%H-%M-%S")
    name = f"gs{global_step}_{timestamp}"
    if suffix:
        name = f"{name}_{suffix}"
    return os.path.join(output_dir, name)


def serialize_transition_records(records) -> list:
    """Protos -> bytes for the replay writer; passes bytes through and
    rejects unserializable entries with a clear error."""
    out = []
    for record in records:
        if isinstance(record, (bytes, bytearray)):
            out.append(bytes(record))
        elif hasattr(record, "SerializeToString"):
            out.append(record.SerializeToString())
        else:
            raise ValueError(
                "Replay records must be serialized bytes or protos with "
                f"SerializeToString; got {type(record).__name__}. Supply a "
                "transition_to_record_fn or a converter producing protos."
            )
    return out


@configurable("TFRecordReplayWriter")
class TFRecordReplayWriter(ReplayWriter):
    """Writes transition records to <path>-<timestamp>.tfrecord shards."""

    def __init__(self):
        self._writer: Optional[TFRecordWriter] = None
        self._path: Optional[str] = None

    def open(self, path: str) -> None:
        """Starts a new shard; `path` is a prefix, the shard gets a unique
        timestamp suffix so concurrent collectors never collide."""
        self.close()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        shard = f"{path}-{int(time.time() * 1e6)}.tfrecord"
        self._writer = TFRecordWriter(shard)
        self._path = shard

    @property
    def current_shard(self) -> Optional[str]:
        return self._path

    def write(self, serialized_records: Union[bytes, Sequence[bytes]]) -> None:
        if self._writer is None:
            raise ValueError("TFRecordReplayWriter.write before open().")
        if isinstance(serialized_records, (bytes, bytearray)):
            serialized_records = [serialized_records]
        for record in serialized_records:
            self._writer.write(bytes(record))

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
