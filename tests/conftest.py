"""Test configuration: run all tests on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding semantics are
validated on XLA's host platform with 8 virtual devices, which exercises the
same GSPMD partitioner and collective lowering paths as a real TPU slice.

Note: this image's sitecustomize imports jax at interpreter startup, so env
vars set here are too late for jax's config — we must go through
jax.config.update (safe as long as no backend has been initialized yet,
which holds at conftest-import time).
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax
import pytest

jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def locksmith_sanitizer(monkeypatch):
    """Runs a test with the lock sanitizer armed (testing/locksmith.py).

    The chaos suites opt in with a module-local autouse fixture so every
    seeded fault run doubles as a deadlock hunt: teardown FAILS the test
    on any lock-order cycle or hold-budget violation observed at
    runtime. Blocking-under-lock events are reported, not failed — chaos
    `delay` clauses land inside critical sections by design and the
    report is the point.
    """
    monkeypatch.setenv("T2R_LOCK_SANITIZER", "1")
    from tensor2robot_tpu.testing import locksmith

    locksmith.reset()
    yield locksmith
    cycles = locksmith.violations(locksmith.ORDER_CYCLE)
    over_budget = locksmith.violations(locksmith.HOLD_BUDGET)
    locksmith.reset()
    assert not cycles, f"lock-order cycle(s) observed at runtime: {cycles}"
    assert not over_budget, (
        f"lock hold-time budget exceeded: {over_budget}"
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-resolution / multi-step integration tests"
    )


def pytest_sessionstart(session):
    devices = jax.devices()
    assert devices[0].platform == "cpu", (
        f"Tests must run on the virtual CPU mesh, got {devices[0]}"
    )
    assert len(devices) == 8, f"Expected 8 virtual devices, got {len(devices)}"
