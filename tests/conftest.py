"""Test configuration: run all tests on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding semantics are
validated on XLA's host platform with 8 virtual devices, which exercises the
same GSPMD partitioner and collective lowering paths as a real TPU slice.
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
