"""Test configuration: run all tests on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding semantics are
validated on XLA's host platform with 8 virtual devices, which exercises the
same GSPMD partitioner and collective lowering paths as a real TPU slice.

Note: this image's sitecustomize imports jax at interpreter startup, so env
vars set here are too late for jax's config — we must go through
jax.config.update (safe as long as no backend has been initialized yet,
which holds at conftest-import time).
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-resolution / multi-step integration tests"
    )


def pytest_sessionstart(session):
    devices = jax.devices()
    assert devices[0].platform == "cpu", (
        f"Tests must run on the virtual CPU mesh, got {devices[0]}"
    )
    assert len(devices) == 8, f"Expected 8 virtual devices, got {len(devices)}"
