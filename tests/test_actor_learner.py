"""Concurrent actor/learner topology: two live processes, filesystem bus.

The reference's distributed-RL shape (SURVEY §2.7 async actor/learner
row; README:44-51): a learner exports SavedModels on a timer while
robots poll-restore and write episode shards. The sequential CLI test
(test_cli.py) proves each stage; this test runs learner and collector
CONCURRENTLY so the real races happen: the collector polls while exports
are being written (tmp-dir rename atomicity), observes a MOVING global
step, and its replay shards land while the learner still trains.
"""

import glob
import os
import re
import subprocess
import sys

import pytest

_LEARNER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
model_dir, export_dir = sys.argv[1], sys.argv[2]
from tensor2robot_tpu.data.input_generators import DefaultRandomInputGenerator
from tensor2robot_tpu.hooks.async_export_hook_builder import AsyncExportHookBuilder
from tensor2robot_tpu.research.pose_env.pose_env_models import PoseEnvRegressionModel
from tensor2robot_tpu.train.train_eval import train_eval_model

train_eval_model(
    PoseEnvRegressionModel(device_type="cpu"),
    input_generator_train=DefaultRandomInputGenerator(batch_size=2),
    model_dir=model_dir,
    max_train_steps=120,
    eval_steps=None,
    save_checkpoints_steps=1000,
    log_every_steps=50,
    hook_builders=[AsyncExportHookBuilder(export_dir=export_dir, save_secs=2.0)],
)
print("LEARNER_DONE", flush=True)
"""

_COLLECTOR = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
root_dir, export_dir = sys.argv[1], sys.argv[2]
import functools
from tensor2robot_tpu.policies import RegressionPolicy
from tensor2robot_tpu.predictors.exported_savedmodel_predictor import (
    ExportedSavedModelPredictor,
)
from tensor2robot_tpu.research.pose_env.episode_to_transitions import (
    episode_to_transitions_pose_toy,
)
from tensor2robot_tpu.research.pose_env.pose_env import PoseToyEnv
from tensor2robot_tpu.research.run_env import run_env
from tensor2robot_tpu.utils.continuous_collect_eval import collect_eval_loop
from tensor2robot_tpu.utils.writer import TFRecordReplayWriter

predictor = ExportedSavedModelPredictor(export_dir=export_dir, timeout=120)
policy = RegressionPolicy(predictor)
last = collect_eval_loop(
    root_dir=root_dir,
    policy=policy,
    collect_env=PoseToyEnv(seed=3),
    eval_env=None,
    num_collect=2,
    run_agent_fn=functools.partial(
        run_env,
        episode_to_transitions_fn=episode_to_transitions_pose_toy,
        replay_writer=TFRecordReplayWriter(),
    ),
    idle_sleep_secs=1.0,
    max_cycles=40,
)
print("COLLECTOR_DONE", last, flush=True)
"""


@pytest.mark.slow
def test_concurrent_actor_learner(tmp_path):
    model_dir = str(tmp_path / "learner")
    export_dir = str(tmp_path / "exports")
    collect_root = str(tmp_path / "robot")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    # File-backed stdout: the OS drains both processes without the test
    # having to, so neither child can block on a full pipe and silently
    # serialize the "concurrent" run.
    learner_log = open(tmp_path / "learner.log", "w+")
    collector_log = open(tmp_path / "collector.log", "w+")
    learner = subprocess.Popen(
        [sys.executable, "-c", _LEARNER, model_dir, export_dir],
        stdout=learner_log, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=cwd,
    )
    collector = subprocess.Popen(
        [sys.executable, "-c", _COLLECTOR, collect_root, export_dir],
        stdout=collector_log, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=cwd,
    )

    def read(log):
        log.flush()
        log.seek(0)
        return log.read()

    try:
        try:
            learner.wait(timeout=600)
        except subprocess.TimeoutExpired:
            pytest.fail(f"learner hung; output: {read(learner_log)[-2000:]}")
        # Surface a learner crash immediately, before burning the
        # collector's poll timeouts.
        learner_out = read(learner_log)
        assert learner.returncode == 0, learner_out[-2000:]
        assert "LEARNER_DONE" in learner_out
        try:
            collector.wait(timeout=300)
        except subprocess.TimeoutExpired:
            pytest.fail(
                f"collector hung; output: {read(collector_log)[-2000:]}"
            )
    finally:
        for proc in (learner, collector):
            if proc.poll() is None:
                proc.kill()
        collector_out = read(collector_log)
        learner_log.close()
        collector_log.close()

    assert collector.returncode == 0, collector_out[-2000:]
    match = re.search(r"COLLECTOR_DONE (-?\d+)", collector_out)
    assert match, collector_out[-1500:]
    # The collector observed a live (nonzero) global step from an export
    # written WHILE training ran, and wrote replay shards.
    assert int(match.group(1)) > 0, collector_out[-1500:]
    shards = glob.glob(os.path.join(collect_root, "policy_collect", "*"))
    assert shards, "collector wrote no replay shards"
