"""Serialized AOT executables: artifact layout, the restore ladder, and
the loud-fallback contract.

The load-bearing claims, each pinned here:

  * an AOT-hit restore serves BIT-IDENTICALLY to the fresh-compile path
    (the executable is the compile of the rehydrated serving program —
    same bytes a cold restore would compile);
  * zero fresh compiles on an AOT-hit boot (`fresh_trace_calls == 0`
    after a full prewarm, recording-predictor bucket discipline intact);
  * every mismatch — artifact fingerprint, device topology, jax
    version, truncated/bitflipped file (analysis/corpus.py corruption
    families) — falls back to the next tier LOUDLY (typed, logged,
    counted, surfaced per bucket in `snapshot()["prewarm_source"]` /
    `aot_fallbacks`) and the fallback serves the CORRECT artifact's
    outputs, never a stale executable's;
  * `T2R_SERVE_AOT=0` (or an artifact without `aot/`) reproduces the
    pre-AOT restore path.
"""

import json
import logging
import os
import shutil

import jax
import numpy as np
import pytest

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.analysis import corpus
from tensor2robot_tpu.export import aot as aot_lib
from tensor2robot_tpu.export.exporters import LatestExporter
from tensor2robot_tpu.export.saved_model import (
    ExportedModel,
    latest_export_dir,
)
from tensor2robot_tpu.predictors import ExportedSavedModelPredictor
from tensor2robot_tpu.serving import PolicyServer
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BUCKETS = (1, 2, 4)


@pytest.fixture(scope="module")
def trained():
    model = MockT2RModel(device_type="cpu")
    generator = MockInputGenerator(batch_size=8)
    generator.set_specification_from_model(model, "train")
    batches = iter(generator.create_dataset("train"))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
    return compiled, state


def _export(trained, model_dir, *, step=1, state=None, **kwargs):
    compiled, default_state = trained
    exporter = LatestExporter(
        name="latest", warmup_batch_sizes=BUCKETS, **kwargs
    )
    exporter.maybe_export(
        step=step,
        state=default_state if state is None else state,
        eval_metrics={"loss": 1.0},
        compiled=compiled,
        model_dir=model_dir,
    )
    return exporter.export_root(model_dir)


@pytest.fixture(scope="module")
def export_root(trained, tmp_path_factory):
    """One AOT-carrying export (flag-default path: T2R_AOT_EXPORT=1)."""
    return _export(trained, str(tmp_path_factory.mktemp("aot_export")))


@pytest.fixture(scope="module")
def quant_export_root(trained, tmp_path_factory):
    """int8 + fp8_e4m3 regimes — both NATIVE by default since round 16
    (eligible kernels contract in the storage dtype), so the AOT tests
    below also pin the restore ladder for native-compute artifacts."""
    return _export(
        trained,
        str(tmp_path_factory.mktemp("aot_quant")),
        serve_quant=("int8", "fp8_e4m3"),
    )


def _copy_export(export_root, tmp_path):
    """Private writable copy of the newest export dir (corruption tests
    must never mutate the module-scoped artifact)."""
    src = latest_export_dir(export_root)
    dst = os.path.join(str(tmp_path), os.path.basename(src))
    shutil.copytree(src, dst)
    return dst


def _example(n=2, seed=0):
    return {
        "x": np.random.RandomState(seed)
        .uniform(-1, 1, (n, 3))
        .astype(np.float32)
    }


def _fresh_outputs(export_dir, features, quant_regime=None, monkeypatch=None):
    """The compile-tier twin: same artifact, T2R_SERVE_AOT=0."""
    monkeypatch.setenv("T2R_SERVE_AOT", "0")
    try:
        loaded = ExportedModel(export_dir, quant_regime=quant_regime)
        assert not loaded.aot_executables
        return loaded.predict(features)
    finally:
        monkeypatch.delenv("T2R_SERVE_AOT")


class TestArtifactLayout:
    def test_aot_dir_and_metadata_contract(self, export_root):
        path = latest_export_dir(export_root)
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            meta = json.load(f)
        aot = meta["aot"]
        assert aot["format_version"] == aot_lib.AOT_FORMAT_VERSION
        assert aot["topology"] == aot_lib.device_topology()
        assert aot["buckets"]["none"] == list(BUCKETS)
        assert aot["nbytes"]["none"] > 0
        assert len(aot["fingerprint"]["none"]) == 64
        for bucket in BUCKETS:
            assert os.path.exists(
                os.path.join(path, aot_lib.aot_relpath("none", bucket))
            )

    def test_quant_regimes_get_their_own_executables(self, quant_export_root):
        path = latest_export_dir(quant_export_root)
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            meta = json.load(f)
        assert meta["aot"]["buckets"]["int8"] == list(BUCKETS)
        assert (
            meta["aot"]["fingerprint"]["int8"]
            != meta["aot"]["fingerprint"]["none"]
        )
        for bucket in BUCKETS:
            assert os.path.exists(
                os.path.join(path, aot_lib.aot_relpath("int8", bucket))
            )

    def test_export_flag_off_writes_pre_aot_layout(
        self, trained, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("T2R_AOT_EXPORT", "0")
        root = _export(trained, str(tmp_path))
        path = latest_export_dir(root)
        assert not os.path.exists(os.path.join(path, aot_lib.AOT_DIR))
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            assert "aot" not in json.load(f)
        # ... and the loader serves it exactly like any pre-AOT artifact.
        loaded = ExportedModel(path)
        assert loaded.aot_declared == ()
        assert not loaded.aot_executables
        assert loaded.predict(_example())["a_predicted"].shape[0] == 2

    def test_failed_default_program_still_exports_quant_executables(
        self, trained, tmp_path, monkeypatch
    ):
        """A failed DEFAULT StableHLO export must not silently drop the
        quant regimes' executables (their programs serialized fine) —
        and the skipped regime must leave a breadcrumb in metadata."""
        import tensor2robot_tpu.export.saved_model as sm

        original = sm._export_stablehlo

        def default_only_fails(predict_fn, example_features,
                               variables_in_args=None):
            if variables_in_args is None:  # the closure-style default
                raise RuntimeError("default lowering exploded")
            return original(
                predict_fn, example_features,
                variables_in_args=variables_in_args,
            )

        monkeypatch.setattr(sm, "_export_stablehlo", default_only_fails)
        root = _export(trained, str(tmp_path), serve_quant=("int8",))
        path = latest_export_dir(root)
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            meta = json.load(f)
        assert meta["stablehlo"] is False
        aot = meta["aot"]
        assert aot["buckets"]["int8"] == list(BUCKETS)
        assert "none" not in aot["buckets"]
        assert "no serving program" in aot["errors"]["none"]
        loaded = ExportedModel(path, quant_regime="int8")
        assert sorted(loaded.aot_executables) == list(BUCKETS)

    def test_exporter_config_validation(self):
        with pytest.raises(ValueError, match="warmup_batch_sizes"):
            LatestExporter(name="latest", aot_executables=True)
        with pytest.raises(ValueError, match="serialize_stablehlo"):
            LatestExporter(
                name="latest",
                warmup_batch_sizes=BUCKETS,
                aot_executables=True,
                serialize_stablehlo=False,
            )


class TestRestoreLadder:
    def test_aot_hit_is_bitwise_equal_to_fresh_compile(
        self, export_root, monkeypatch
    ):
        path = latest_export_dir(export_root)
        loaded = ExportedModel(path)
        assert sorted(loaded.aot_executables) == list(BUCKETS)
        assert loaded.aot_fallbacks == {}
        features = _example()
        got = loaded.predict(features)
        assert loaded.fresh_trace_calls == 0  # never touched the trace path
        want = _fresh_outputs(path, features, monkeypatch=monkeypatch)
        for key in want:
            np.testing.assert_array_equal(got[key], want[key])

    @pytest.mark.parametrize("regime", ["int8", "fp8_e4m3"])
    def test_quant_regime_aot_hit_bitwise(
        self, quant_export_root, monkeypatch, regime
    ):
        path = latest_export_dir(quant_export_root)
        loaded = ExportedModel(path, quant_regime=regime)
        assert sorted(loaded.aot_executables) == list(BUCKETS)
        # The regime under test is genuinely NATIVE (its program carries
        # int8/fp8 contractions) — the claim is an AOT cold boot of a
        # native-compute artifact with zero fresh compiles, not just a
        # dequant payload riding serialized executables.
        assert loaded.native_dot_layers, loaded.metadata["serve_quant"]
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            audit = json.load(f)["serve_quant"]["dot_audit"][regime]
        native_key = {"int8": "i8", "fp8_e4m3": "f8e4m3"}[regime]
        assert audit.get(native_key, 0) >= 1, audit
        features = _example()
        got = loaded.predict(features)
        assert loaded.fresh_trace_calls == 0
        want = _fresh_outputs(
            path, features, quant_regime=regime, monkeypatch=monkeypatch
        )
        for key in want:
            np.testing.assert_array_equal(got[key], want[key])

    def test_serve_aot_off_reproduces_the_pre_aot_path(
        self, export_root, monkeypatch
    ):
        monkeypatch.setenv("T2R_SERVE_AOT", "0")
        loaded = ExportedModel(latest_export_dir(export_root))
        assert not loaded.aot_enabled
        assert not loaded.aot_executables
        assert not loaded.aot_fallbacks  # off is a choice, not a fallback
        out = loaded.predict(_example())
        assert loaded.fresh_trace_calls > 0  # the trace path served it
        assert out["a_predicted"].shape[0] == 2

    def test_novel_batch_size_rides_the_fresh_path(
        self, export_root, monkeypatch
    ):
        path = latest_export_dir(export_root)
        loaded = ExportedModel(path)
        features = _example(n=3)  # 3 is not a bucket
        got = loaded.predict(features)
        assert loaded.fresh_trace_calls == 1
        want = _fresh_outputs(path, features, monkeypatch=monkeypatch)
        np.testing.assert_array_equal(got["a_predicted"], want["a_predicted"])

    def test_transplanted_aot_dir_never_serves_stale_weights(
        self, trained, export_root, tmp_path, monkeypatch, caplog
    ):
        """The fingerprint check: aot/ from artifact A spliced into
        artifact B (different weights) must fall back on every bucket —
        and the fallback must serve B's outputs, not A's executables."""
        compiled, _ = trained
        generator = MockInputGenerator(batch_size=8)
        generator.set_specification_from_model(compiled.model, "train")
        batch = next(iter(generator.create_dataset("train")))
        other_state = compiled.init_state(jax.random.PRNGKey(7), batch)
        other_root = _export(
            trained, str(tmp_path / "other"), step=2, state=other_state
        )
        victim = _copy_export(other_root, tmp_path)
        stale = os.path.join(latest_export_dir(export_root), aot_lib.AOT_DIR)
        shutil.rmtree(os.path.join(victim, aot_lib.AOT_DIR))
        shutil.copytree(stale, os.path.join(victim, aot_lib.AOT_DIR))
        with caplog.at_level(logging.WARNING):
            loaded = ExportedModel(victim)
        assert loaded.aot_executables == {}
        assert set(loaded.aot_fallbacks) == set(BUCKETS)
        assert all(
            reason == "AOTKeyMismatch"
            for reason in loaded.aot_fallbacks.values()
        )
        assert any("fingerprint" in r.message for r in caplog.records)
        features = _example()
        got = loaded.predict(features)
        want = _fresh_outputs(victim, features, monkeypatch=monkeypatch)
        np.testing.assert_array_equal(got["a_predicted"], want["a_predicted"])

    def test_topology_mismatch_never_loads_silently(
        self, export_root, tmp_path, monkeypatch, caplog
    ):
        """An executable lowered for another mesh must not deserialize —
        one loud line, every bucket counted, fresh path serves."""
        path = _copy_export(export_root, tmp_path)
        real = aot_lib.device_topology()
        monkeypatch.setattr(
            aot_lib,
            "device_topology",
            lambda: {**real, "device_count": real["device_count"] + 8},
        )
        with caplog.at_level(logging.WARNING):
            loaded = ExportedModel(path)
        assert loaded.aot_executables == {}
        assert all(
            reason == "topology_mismatch"
            for reason in loaded.aot_fallbacks.values()
        )
        assert set(loaded.aot_fallbacks) == set(BUCKETS)
        assert any("topology" in r.message for r in caplog.records)
        assert loaded.predict(_example())["a_predicted"].shape[0] == 2
        assert loaded.fresh_trace_calls > 0

    def test_per_file_topology_key_is_checked(self, export_root, tmp_path):
        """Even with a lying metadata block, the per-file header key
        refuses a foreign-topology executable (defense in depth: the
        file is the thing that deserializes)."""
        path = _copy_export(export_root, tmp_path)
        target = os.path.join(path, aot_lib.aot_relpath("none", 1))
        with open(target, "rb") as f:
            header, payload = aot_lib._unpack(f.read())
        header["topology"] = {**header["topology"], "device_kind": "tpu-v4"}
        with open(target, "wb") as f:
            f.write(aot_lib._pack(header, payload))
        with open(target, "rb") as f:
            blob = f.read()
        with pytest.raises(aot_lib.AOTKeyMismatch, match="topology"):
            aot_lib.load_executable(
                blob, expect_topology=aot_lib.device_topology()
            )
        loaded = ExportedModel(path)
        assert 1 not in loaded.aot_executables
        assert loaded.aot_fallbacks == {1: "AOTKeyMismatch"}
        assert sorted(loaded.aot_executables) == [2, 4]  # siblings intact

    def test_jax_version_mismatch_is_a_typed_fallback(
        self, export_root, tmp_path
    ):
        path = _copy_export(export_root, tmp_path)
        target = os.path.join(path, aot_lib.aot_relpath("none", 2))
        with open(target, "rb") as f:
            header, payload = aot_lib._unpack(f.read())
        header["jax"] = "0.0.0-foreign"
        with open(target, "wb") as f:
            f.write(aot_lib._pack(header, payload))
        loaded = ExportedModel(path)
        assert loaded.aot_fallbacks == {2: "AOTKeyMismatch"}
        assert sorted(loaded.aot_executables) == [1, 4]

    def test_every_corruption_variant_is_typed_never_partial(
        self, export_root
    ):
        """analysis/corpus.py discipline over the envelope: structural
        truncations, seeded bitflips, forged/past-EOF lengths, bad magic
        — each must raise AOTCorrupt from load_executable (whole-file-
        or-nothing; no partial deserialize, no unpickle of bad bytes)."""
        path = latest_export_dir(export_root)
        with open(os.path.join(path, aot_lib.aot_relpath("none", 1)), "rb") as f:
            blob = f.read()
        variants = corpus.corrupt_frame_variants(blob)
        assert len(variants) >= 15
        for name, bad in variants.items():
            with pytest.raises(aot_lib.AOTCorrupt):
                aot_lib.load_executable(bad)
            # corrupt bytes must be rejected at integrity, BEFORE the
            # key check could even run
            with pytest.raises(aot_lib.AOTCorrupt):
                aot_lib.load_executable(
                    bad,
                    expect_fingerprint="0" * 64,
                    expect_topology=aot_lib.device_topology(),
                )

    @pytest.mark.parametrize(
        "variant", ["frame_trunc", "frame_bitflip", "frame_bad_magic"]
    )
    def test_corrupt_file_falls_back_and_serves_correctly(
        self, export_root, tmp_path, monkeypatch, caplog, variant
    ):
        path = _copy_export(export_root, tmp_path)
        target = os.path.join(path, aot_lib.aot_relpath("none", 1))
        with open(target, "rb") as f:
            blob = f.read()
        name, bad = next(
            (n, b)
            for n, b in sorted(corpus.corrupt_frame_variants(blob).items())
            if n.startswith(variant)
        )
        with open(target, "wb") as f:
            f.write(bad)
        with caplog.at_level(logging.WARNING):
            loaded = ExportedModel(path)
        assert loaded.aot_fallbacks == {1: "AOTCorrupt"}, name
        assert sorted(loaded.aot_executables) == [2, 4]
        features = _example(n=1, seed=3)
        got = loaded.predict(features)  # bucket 1 -> fresh path
        assert loaded.fresh_trace_calls == 1
        want = _fresh_outputs(path, features, monkeypatch=monkeypatch)
        np.testing.assert_array_equal(got["a_predicted"], want["a_predicted"])

    def test_require_mode_fails_loudly_instead_of_falling_back(
        self, export_root, tmp_path, monkeypatch
    ):
        path = _copy_export(export_root, tmp_path)
        monkeypatch.setenv("T2R_AOT_REQUIRE", "1")
        assert ExportedModel(path).aot_covered  # clean artifact boots
        os.remove(os.path.join(path, aot_lib.aot_relpath("none", 2)))
        with pytest.raises(aot_lib.AOTError, match="T2R_AOT_REQUIRE"):
            ExportedModel(path)

    def test_require_with_serve_aot_off_names_the_flag_conflict(
        self, export_root, monkeypatch
    ):
        """REQUIRE + SERVE_AOT=0 is an operator contradiction: the error
        must blame the flag pair, never the (perfectly good) artifact."""
        monkeypatch.setenv("T2R_AOT_REQUIRE", "1")
        monkeypatch.setenv("T2R_SERVE_AOT", "0")
        with pytest.raises(aot_lib.AOTError, match="conflicts with"):
            ExportedModel(latest_export_dir(export_root))


class _RecordingPredictor:
    """Served-batch-size recorder (the test_serving discipline)."""

    def __init__(self, inner):
        self._inner = inner
        self.batch_sizes = []

    def _record(self, features):
        sizes = {int(np.asarray(v).shape[0]) for v in features.values()}
        assert len(sizes) == 1, f"ragged batch: {sizes}"
        self.batch_sizes.append(sizes.pop())

    def predict(self, features):
        self._record(features)
        return self._inner.predict(features)

    def predict_versioned(self, features):
        self._record(features)
        return self._inner.predict_versioned(features)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestServerIntegration:
    def test_aot_boot_prewarm_source_and_zero_fresh_compiles(
        self, export_root
    ):
        inner = ExportedSavedModelPredictor(export_dir=export_root)
        assert inner.restore()
        predictor = _RecordingPredictor(inner)
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            response = server.call(
                {"x": np.zeros((3,), np.float32)}, timeout=30
            )
            snap = server.snapshot()
        # Every bucket prewarmed (recording predictor saw the ladder) ...
        assert sorted(set(predictor.batch_sizes)) == list(BUCKETS)
        # ... from deserialized executables, with ZERO fresh compiles.
        assert snap["prewarm_source"] == {
            str(b): "aot" for b in BUCKETS
        }
        assert snap["counters"]["aot_hits"] == len(BUCKETS)
        assert snap["counters"]["aot_misses"] == 0
        assert "aot_fallbacks" not in snap
        assert inner.loaded_model.fresh_trace_calls == 0
        assert response.outputs["a_predicted"].shape == (1,)

    def test_fallback_bucket_is_counted_and_surfaced(
        self, export_root, tmp_path
    ):
        root = os.path.join(str(tmp_path), "root")
        os.makedirs(root)
        _copy_export(export_root, root)
        path = latest_export_dir(root)
        target = os.path.join(path, aot_lib.aot_relpath("none", 4))
        with open(target, "rb") as f:
            blob = f.read()
        with open(target, "wb") as f:
            f.write(blob[: len(blob) // 2])  # torn mid-payload
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            snap = server.snapshot()
        assert snap["prewarm_source"]["1"] == "aot"
        assert snap["prewarm_source"]["2"] == "aot"
        assert snap["prewarm_source"]["4"] in ("cache", "compile")
        assert snap["counters"]["aot_hits"] == 2
        assert snap["counters"]["aot_misses"] == 1
        assert snap["aot_fallbacks"] == {"4": "AOTCorrupt"}

    def test_failed_swap_prewarm_keeps_serving_version_sources(
        self, export_root
    ):
        """A swap aborted by a failed prewarm keeps the OLD version
        serving — its prewarm_source record and aot counters must not
        be overwritten by a version that never served."""
        predictor = ExportedSavedModelPredictor(export_dir=export_root)
        assert predictor.restore()
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            before = server.snapshot()
            assert before["prewarm_source"] == {
                str(b): "aot" for b in BUCKETS
            }

            class _IncomingWithoutAOT:
                aot_executables = {}
                aot_enabled = True

            def broken_serve_fn(batch):
                raise RuntimeError("incoming version cannot serve")

            with pytest.raises(RuntimeError, match="cannot serve"):
                server._prewarm_restored(_IncomingWithoutAOT(), broken_serve_fn)
            after = server.snapshot()
        assert after["prewarm_source"] == before["prewarm_source"]
        assert after["counters"]["aot_hits"] == before["counters"]["aot_hits"]
        assert (
            after["counters"]["aot_misses"]
            == before["counters"]["aot_misses"]
        )

    def test_hot_swap_records_incoming_version_sources(
        self, trained, tmp_path
    ):
        root = _export(trained, str(tmp_path))
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            v1 = predictor.model_version
            _export(trained, str(tmp_path), step=2)
            assert server.hot_swap(wait=True)
            assert predictor.model_version > v1
            response = server.call(
                {"x": np.zeros((3,), np.float32)}, timeout=30
            )
            snap = server.snapshot()
        # Swap prewarm re-recorded the (AOT) sources for the incoming
        # version and the counters accumulated across boot + swap.
        assert snap["prewarm_source"] == {str(b): "aot" for b in BUCKETS}
        assert snap["counters"]["aot_hits"] == 2 * len(BUCKETS)
        assert predictor.loaded_model.fresh_trace_calls == 0
        assert response.model_version > v1


class TestCompileTierEngagement:
    """The cache-skip must be exactly as wide as the AOT coverage of the
    ladder that will actually SERVE — a serving ladder wider than the
    warmup ladder (T2R_SERVE_BUCKETS or explicit batch_buckets) has
    compile-tier buckets, and skipping the cache for them would
    silently un-amortize every boot (review regression)."""

    @pytest.fixture(autouse=True)
    def _restore_jax_cache_config(self):
        previous_dir = jax.config.jax_compilation_cache_dir
        previous_min = jax.config.jax_persistent_cache_min_compile_time_secs
        yield
        jax.config.update("jax_compilation_cache_dir", previous_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", previous_min
        )
        try:
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except ImportError:  # pragma: no cover - future jax relayout
            pass

    def test_flag_ladder_beyond_aot_engages_cache(
        self, export_root, tmp_path, monkeypatch
    ):
        from tensor2robot_tpu.serving.compile_cache import (
            enable_compile_cache_for,
        )

        loaded = ExportedModel(latest_export_dir(export_root))
        assert loaded.aot_covered
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        # Resolved ladder == warmup ladder, fully AOT-covered -> skip.
        assert enable_compile_cache_for(loaded) is None
        # T2R_SERVE_BUCKETS adds a bucket with no executable -> the
        # compile tier is live and the cache must engage.
        monkeypatch.setenv("T2R_SERVE_BUCKETS", "1,2,4,8")
        assert enable_compile_cache_for(loaded) == str(tmp_path)

    def test_explicit_server_ladder_beyond_aot_engages_cache(
        self, export_root, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        predictor = ExportedSavedModelPredictor(export_dir=export_root)
        assert predictor.restore()
        with PolicyServer(
            predictor, batch_buckets=(1, 2, 4, 8), max_wait_ms=1
        ).start() as server:
            snap = server.snapshot()
        # The constructor ladder's extra bucket rides the cache tier —
        # labeled as such AND actually engaged (start() re-engages for
        # any bucket outside the AOT table).
        assert snap["prewarm_source"]["8"] == "cache"
        assert {snap["prewarm_source"][str(b)] for b in BUCKETS} == {"aot"}
        assert snap["counters"]["aot_misses"] == 1
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)


class TestWarmCompileCacheBuild:
    """A WARM persistent compilation cache must never serve the AOT
    build's compiles: a cache HIT returns an executable whose
    serialization drops its object code, and the shipped blob then
    fails every deserialize_and_load with "Symbols not found" — in the
    exporting process too, so every boot of the artifact becomes a
    logged fallback. Any process that compiled the same program before
    exporting (a bench re-run, a serving replica that exports) is a
    warm-cache exporter."""

    @pytest.fixture(autouse=True)
    def _restore_jax_cache_config(self):
        previous_dir = jax.config.jax_compilation_cache_dir
        previous_min = jax.config.jax_persistent_cache_min_compile_time_secs
        yield
        jax.config.update("jax_compilation_cache_dir", previous_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", previous_min
        )
        try:
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except ImportError:  # pragma: no cover - future jax relayout
            pass

    def test_build_under_warm_cache_round_trips(self, export_root, tmp_path):
        from jax import export as jax_export

        from tensor2robot_tpu.export.saved_model import (
            STABLEHLO_DIR,
            STABLEHLO_FILENAME,
        )
        from tensor2robot_tpu.serving.compile_cache import (
            enable_compile_cache,
        )

        with open(
            os.path.join(
                latest_export_dir(export_root), STABLEHLO_DIR,
                STABLEHLO_FILENAME,
            ),
            "rb",
        ) as f:
            program_bytes = f.read()
        cache_dir = str(tmp_path / "jaxcache")
        enable_compile_cache(cache_dir)
        # Warm the cache with this exact program/bucket OUTSIDE the
        # build — the position every re-exporting process is in.
        batch = _example(2)
        jax.jit(jax_export.deserialize(program_bytes).call).lower(
            batch
        ).compile()
        assert os.listdir(cache_dir), "cache never engaged — no warm hit"

        timings = {}
        blobs = aot_lib.build_bucket_executables(
            program_bytes, [batch], regime="none", fingerprint="0" * 64,
            timings_ms=timings,
        )
        # Pre-fix, this deserialize died with "Symbols not found".
        _compiled, header = aot_lib.load_executable(blobs[2])
        assert header["bucket"] == 2
        assert timings[2] > 0
        # SECOND build, same process, cache still configured: jax folds
        # config state into the cache key, so a build that merely
        # flipped the enable flag would have WRITTEN re-keyed entries
        # above and would HIT them here — the re-export scenario (bench
        # re-run, online-loop learner) that corrupts every bucket
        # unless reads AND writes are both dead during the build.
        blobs2 = aot_lib.build_bucket_executables(
            program_bytes, [batch], regime="none", fingerprint="0" * 64,
        )
        aot_lib.load_executable(blobs2[2])
        # The bypass is scoped to the builds: the cache is back on.
        assert jax.config.jax_enable_compilation_cache
        assert jax.config.jax_compilation_cache_dir == cache_dir


class TestFlagsDeclared:
    def test_aot_flags_in_registry(self):
        assert t2r_flags.get_flag("T2R_SERVE_AOT").kind == "bool"
        assert t2r_flags.get_flag("T2R_AOT_EXPORT").kind == "bool"
        assert t2r_flags.get_flag("T2R_AOT_REQUIRE").kind == "bool"
        assert t2r_flags.get_bool("T2R_SERVE_AOT") is True
        assert t2r_flags.get_bool("T2R_AOT_EXPORT") is True
        assert t2r_flags.get_bool("T2R_AOT_REQUIRE") is False
