"""export/artifact_store.py: the content-addressed multi-policy store.

Pins the round-20 storage contract: program blobs dedup by content
hash; sibling weights ship as quantized per-leaf deltas that
reconstruct BITWISE-STABLE and hash-verified; the per-leaf parity gate
demotes out-of-tolerance leaves to dense-exact (never a partial
policy); and every corruption/transplant of the delta envelope is a
TYPED refusal through the public read path — the analysis/corpus.py
frame family drives the corruption cases unchanged, because the
envelope deliberately rides the AOT frame shape (magic + u32 length +
u32 crc32).
"""

import hashlib
import json
import os
import time

import numpy as np
import pytest

from tensor2robot_tpu.analysis import corpus
from tensor2robot_tpu.export.artifact_store import (
    ArtifactCorrupt,
    ArtifactKeyMismatch,
    ArtifactStore,
    ArtifactStoreError,
    BaseArtifactMissing,
    PolicyExists,
    PolicyNotFound,
    program_fingerprint,
)

flax = pytest.importorskip("flax")
from flax import serialization  # noqa: E402


_PROGRAM = b"stablehlo-program-bytes " * 512  # shared across siblings


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "dense0": {
            "kernel": rng.standard_normal((16, 16)).astype(np.float32),
            "bias": rng.standard_normal((16,)).astype(np.float32),
        },
        "step": np.int64(7),
    }


def _perturb(params, seed, scale=1e-3):
    rng = np.random.RandomState(seed)
    out = {}
    for name, group in params.items():
        if isinstance(group, dict):
            out[name] = {
                k: v + rng.standard_normal(v.shape).astype(np.float32) * scale
                for k, v in group.items()
            }
        else:
            out[name] = group
    return out


def _write_export(dirname, params, program=_PROGRAM):
    os.makedirs(os.path.join(dirname, "stablehlo"), exist_ok=True)
    with open(os.path.join(dirname, "stablehlo", "forward.mlir"), "wb") as f:
        f.write(program)
    with open(os.path.join(dirname, "t2r_metadata.json"), "w") as f:
        json.dump({"test": "artifact_store"}, f)
    with open(os.path.join(dirname, "variables.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(params))


def _publish(store, tmp_path, policy_id, params, base_policy=None, **kw):
    export_dir = os.path.join(str(tmp_path), f"export-{policy_id}")
    _write_export(export_dir, params)
    return store.put(export_dir, policy_id, base_policy=base_policy, **kw)


def _swap_payload_blob(store, policy_id, data):
    """Point `policy_id`'s weights payload at `data`, stored under
    data's OWN content hash — the blob-level sha passes, so the read
    path exercises the envelope checks, not the blob checks."""
    sha = hashlib.sha256(data).hexdigest()
    with open(
        os.path.join(store.root, "blobs", f"sha256-{sha}"), "wb"
    ) as f:
        f.write(data)
    path = os.path.join(store.root, "policies", f"{policy_id}.json")
    with open(path) as f:
        manifest = json.load(f)
    manifest["payload"]["blob"] = sha
    manifest["payload"]["nbytes"] = len(data)
    with open(path, "w") as f:
        json.dump(manifest, f)


@pytest.fixture
def store(tmp_path):
    return ArtifactStore(str(tmp_path / "store"))


class TestRoundTrip:
    def test_dense_base_bitwise(self, store, tmp_path):
        params = _params(0)
        manifest = _publish(store, tmp_path, "base", params)
        assert manifest["payload"]["kind"] == "dense"
        want = serialization.to_bytes(params)
        assert store.load_weights("base") == want
        restored = serialization.msgpack_restore(store.load_weights("base"))
        np.testing.assert_array_equal(
            restored["dense0"]["kernel"], params["dense0"]["kernel"]
        )

    def test_sibling_delta_bitwise_stable_and_within_tolerance(
        self, store, tmp_path
    ):
        base = _params(0)
        sib = _perturb(base, seed=1)
        _publish(store, tmp_path, "base", base)
        manifest = _publish(
            store, tmp_path, "sib", sib, base_policy="base", regime="int8"
        )
        payload = manifest["payload"]
        assert payload["kind"] == "delta"
        assert payload["base"] == "base"
        assert payload["leaves"]["delta"] == 2  # kernel + bias
        # Bitwise-stable: two loads, identical bytes, matching the
        # manifest's recorded hash.
        first = store.load_weights("sib")
        assert store.load_weights("sib") == first
        assert hashlib.sha256(first).hexdigest() == payload["weights_sha"]
        # Within the declared parity tolerance of the ORIGINAL weights.
        restored = serialization.msgpack_restore(first)
        for group in ("dense0",):
            for leaf in ("kernel", "bias"):
                want = sib[group][leaf]
                got = restored[group][leaf]
                tol = 0.05 * max(float(np.max(np.abs(want))), 1e-8)
                assert float(np.max(np.abs(got - want))) <= tol
        # The non-float leaf ships dense-exact.
        assert restored["step"] == sib["step"]

    def test_program_blob_dedup_shrinks_the_store(self, store, tmp_path):
        base = _params(0)
        _publish(store, tmp_path, "base", base)
        for i in range(4):
            _publish(
                store, tmp_path, f"sib{i}", _perturb(base, seed=10 + i),
                base_policy="base",
            )
        stats = store.stats()
        assert stats["n_policies"] == 5
        assert stats["n_delta_policies"] == 4
        # ONE program blob for five policies: the program's content hash
        # appears exactly once under blobs/.
        sha = hashlib.sha256(_PROGRAM).hexdigest()
        assert os.path.exists(
            os.path.join(store.root, "blobs", f"sha256-{sha}")
        )
        assert stats["store_bytes"] < stats["dense_bytes"] * 0.5
        # Exactly one blob each for the shared program, the shared
        # metadata file, the base's dense weights — plus one delta
        # envelope per sibling. A second program copy would show up
        # here.
        assert stats["n_blobs"] == 3 + 4

    def test_materialize_reconstructs_the_export_dir(self, store, tmp_path):
        base = _params(0)
        sib = _perturb(base, seed=2)
        _publish(store, tmp_path, "base", base)
        _publish(store, tmp_path, "sib", sib, base_policy="base")
        dest = str(tmp_path / "rebuilt")
        store.materialize("sib", dest)
        with open(os.path.join(dest, "stablehlo", "forward.mlir"), "rb") as f:
            assert f.read() == _PROGRAM
        with open(os.path.join(dest, "variables.msgpack"), "rb") as f:
            assert f.read() == store.load_weights("sib")
        with pytest.raises(ArtifactStoreError):
            store.materialize("sib", dest)  # refuses to clobber

    def test_parity_gate_demotes_hot_leaf_to_dense_exact(
        self, store, tmp_path
    ):
        """A leaf whose diff cannot reconstruct within tolerance ships
        dense-exact — per leaf, while its siblings still ship delta."""
        base = _params(0)
        sib = _perturb(base, seed=3)
        # One leaf moves by a huge, high-dynamic-range delta that int8
        # blocks cannot hold to 0.1% — the gate must catch it.
        rng = np.random.RandomState(9)
        sib["dense0"]["bias"] = (
            base["dense0"]["bias"]
            + rng.standard_normal((16,)).astype(np.float32) * 50.0
        )
        _publish(store, tmp_path, "base", base)
        manifest = _publish(
            store, tmp_path, "sib", sib, base_policy="base",
            regime="int8", tolerance=1e-3,
        )
        leaves = manifest["payload"]["leaves"]
        assert leaves["dense"] >= 2  # the demoted leaf + the int64 step
        assert leaves["delta"] >= 1  # small-delta leaves still encode
        restored = serialization.msgpack_restore(store.load_weights("sib"))
        # Dense-exact means BITWISE for the demoted leaf.
        np.testing.assert_array_equal(
            restored["dense0"]["bias"], sib["dense0"]["bias"]
        )

    def test_tolerance_zero_demotes_everything_and_round_trips_exact(
        self, store, tmp_path
    ):
        base = _params(0)
        sib = _perturb(base, seed=4)
        _publish(store, tmp_path, "base", base)
        manifest = _publish(
            store, tmp_path, "sib", sib, base_policy="base", tolerance=0.0
        )
        assert manifest["payload"]["leaves"]["delta"] == 0
        # Every leaf ships dense-exact: bitwise equal to the original
        # (the serialized KEY ORDER may differ — identity is per leaf).
        restored = serialization.msgpack_restore(store.load_weights("sib"))
        np.testing.assert_array_equal(
            restored["dense0"]["kernel"], sib["dense0"]["kernel"]
        )
        np.testing.assert_array_equal(
            restored["dense0"]["bias"], sib["dense0"]["bias"]
        )
        assert restored["step"] == sib["step"]


class TestTypedRefusals:
    def test_every_corrupt_frame_variant_is_typed_never_partial(
        self, store, tmp_path
    ):
        """analysis/corpus.py discipline over the delta envelope:
        structural truncations, seeded bitflips, forged/past-EOF
        lengths, bad magic — each must raise ArtifactCorrupt from the
        public load path (whole-payload-or-nothing; the blob-level sha
        is re-addressed so the ENVELOPE checks are what fire)."""
        base = _params(0)
        _publish(store, tmp_path, "base", base)
        manifest = _publish(
            store, tmp_path, "sib", _perturb(base, seed=5),
            base_policy="base",
        )
        with open(
            os.path.join(
                store.root, "blobs",
                f"sha256-{manifest['payload']['blob']}",
            ),
            "rb",
        ) as f:
            envelope = f.read()
        variants = corpus.corrupt_frame_variants(envelope)
        assert len(variants) >= 15
        for name, bad in variants.items():
            _swap_payload_blob(store, "sib", bad)
            with pytest.raises(ArtifactCorrupt):
                store.load_weights("sib")
            with pytest.raises(ArtifactCorrupt):
                store.materialize("sib", str(tmp_path / f"dest-{name}"))

    def test_blob_bytes_corrupt_on_disk_refused(self, store, tmp_path):
        _publish(store, tmp_path, "base", _params(0))
        sha = store.manifest("base")["payload"]["blob"]
        path = os.path.join(store.root, "blobs", f"sha256-{sha}")
        with open(path, "rb") as f:
            data = bytearray(f.read())
        data[len(data) // 2] ^= 0x40
        with open(path, "wb") as f:
            f.write(data)
        with pytest.raises(ArtifactCorrupt):
            store.load_weights("base")
        os.unlink(path)
        with pytest.raises(ArtifactCorrupt):
            store.load_weights("base")  # missing blob is corrupt, typed

    def test_base_missing_is_typed(self, store, tmp_path):
        base = _params(0)
        _publish(store, tmp_path, "base", base)
        _publish(
            store, tmp_path, "sib", _perturb(base, seed=6),
            base_policy="base",
        )
        store.delete("base")
        with pytest.raises(BaseArtifactMissing):
            store.load_weights("sib")

    def test_cross_program_delta_refused_at_put(self, store, tmp_path):
        _publish(store, tmp_path, "base", _params(0))
        other_dir = str(tmp_path / "export-other-program")
        _write_export(
            other_dir, _perturb(_params(0), seed=7),
            program=b"a different program entirely " * 256,
        )
        with pytest.raises(ArtifactKeyMismatch):
            store.put(other_dir, "cross", base_policy="base")
        assert not store.has("cross")  # gate-fails-write-nothing

    def test_republished_base_weights_refused_at_read(
        self, store, tmp_path
    ):
        """The delta is keyed to the base WEIGHTS it was encoded
        against: silently decoding against republished base weights
        would materialize garbage under the sibling's name."""
        base = _params(0)
        _publish(store, tmp_path, "base", base)
        _publish(
            store, tmp_path, "sib", _perturb(base, seed=8),
            base_policy="base",
        )
        store.delete("base")
        _publish(store, tmp_path, "base", _params(99))  # same program
        with pytest.raises(ArtifactKeyMismatch):
            store.load_weights("sib")

    def test_transplanted_envelope_refused_by_fingerprint(
        self, store, tmp_path
    ):
        """An intact delta payload moved under a policy of a DIFFERENT
        program family fails the key check, not the integrity check."""
        base_a = _params(0)
        _publish(store, tmp_path, "base", base_a)
        man_a = _publish(
            store, tmp_path, "sibA", _perturb(base_a, seed=11),
            base_policy="base",
        )
        other = ArtifactStore(str(tmp_path / "storeB"))
        dir_b = str(tmp_path / "export-baseB")
        _write_export(dir_b, base_a, program=b"program B " * 1024)
        other.put(dir_b, "base")
        dir_sb = str(tmp_path / "export-sibB")
        _write_export(
            dir_sb, _perturb(base_a, seed=12), program=b"program B " * 1024
        )
        other.put(dir_sb, "sibB", base_policy="base")
        with open(
            os.path.join(
                store.root, "blobs", f"sha256-{man_a['payload']['blob']}"
            ),
            "rb",
        ) as f:
            envelope_a = f.read()
        _swap_payload_blob(other, "sibB", envelope_a)
        with pytest.raises(ArtifactKeyMismatch):
            other.load_weights("sibB")

    def test_publish_and_lookup_refusals(self, store, tmp_path):
        _publish(store, tmp_path, "base", _params(0))
        with pytest.raises(PolicyExists):
            _publish(store, tmp_path, "base", _params(1))
        with pytest.raises(PolicyNotFound):
            store.load_weights("nope")
        with pytest.raises(PolicyNotFound):
            store.delete("nope")
        with pytest.raises(BaseArtifactMissing):
            _publish(
                store, tmp_path, "orphan", _params(2),
                base_policy="never-published",
            )
        with pytest.raises(ValueError):
            store.put(str(tmp_path), "bad/id")
        not_export = str(tmp_path / "not-an-export")
        os.makedirs(not_export)
        with pytest.raises(ArtifactStoreError):
            store.put(not_export, "empty")


class TestFingerprint:
    def test_program_identity_ignores_weights(self):
        files_a = {
            "stablehlo/forward.mlir": b"prog",
            "variables.msgpack": b"weights-1",
        }
        files_b = {
            "stablehlo/forward.mlir": b"prog",
            "variables.msgpack": b"weights-2",
        }
        assert program_fingerprint(files_a) == program_fingerprint(files_b)
        files_c = {
            "stablehlo/forward.mlir": b"other prog",
            "variables.msgpack": b"weights-1",
        }
        assert program_fingerprint(files_a) != program_fingerprint(files_c)

    def test_programless_export_falls_back_to_non_weight_files(self):
        files = {"t2r_metadata.json": b"{}", "variables.msgpack": b"w"}
        other = {"t2r_metadata.json": b"{}", "variables.msgpack": b"x"}
        assert program_fingerprint(files) == program_fingerprint(other)
        changed = {"t2r_metadata.json": b"{!}", "variables.msgpack": b"w"}
        assert program_fingerprint(files) != program_fingerprint(changed)


def _age_blobs(store, seconds=7200.0):
    """Back-date every blob so the gc grace window does not shield it."""
    blob_dir = os.path.join(store.root, "blobs")
    past = time.time() - seconds
    for name in os.listdir(blob_dir):
        os.utime(os.path.join(blob_dir, name), (past, past))


def _blob_names(store):
    blob_dir = os.path.join(store.root, "blobs")
    return {n for n in os.listdir(blob_dir) if n.startswith("sha256-")}


class TestGC:
    def test_all_live_deletes_nothing(self, store, tmp_path):
        params = _params(0)
        _publish(store, tmp_path, "base", params)
        _publish(store, tmp_path, "sib", _perturb(params, 1), base_policy="base")
        _age_blobs(store)
        before = _blob_names(store)
        stats = store.gc()
        assert stats["deleted"] == 0
        assert stats["bytes_freed"] == 0
        assert stats["live"] == stats["scanned"] == len(before)
        assert _blob_names(store) == before

    def test_republish_then_rooted_sweep_reclaims_old_generation(
        self, store, tmp_path
    ):
        params = _params(0)
        _publish(store, tmp_path, "base-v1", params)
        _publish(
            store, tmp_path, "sib", _perturb(params, 1), base_policy="base-v1"
        )
        _publish(store, tmp_path, "base-v2", _perturb(params, 2, scale=5e-3))
        _age_blobs(store)
        stats = store.gc(roots=["base-v2"])
        assert stats["deleted"] > 0
        # Survivor still loads bitwise; the superseded generation's
        # unique payload blobs are gone, so its load is a typed refusal,
        # never a partial read.
        store.load_weights("base-v2")
        with pytest.raises(ArtifactStoreError):
            store.load_weights("base-v1")

    def test_delta_base_chain_is_reachable(self, store, tmp_path):
        params = _params(0)
        _publish(store, tmp_path, "base", params)
        sib = _perturb(params, 1)
        _publish(store, tmp_path, "sib", sib, base_policy="base")
        grand = _perturb(sib, 2)
        _publish(store, tmp_path, "grand", grand, base_policy="sib")
        _age_blobs(store)
        # Rooting ONLY the grandchild transitively pins both ancestors
        # through the delta-base chain — a rooted sibling must stay
        # reconstructable after the sweep.
        stats = store.gc(roots=["grand"])
        assert stats["deleted"] == 0
        # Still reconstructs through both ancestors, hash-verified.
        assert store.load_weights("grand") == store.load_weights("grand")

    def test_dry_run_counts_without_deleting(self, store, tmp_path):
        params = _params(0)
        _publish(store, tmp_path, "base-v1", params)
        _publish(store, tmp_path, "base-v2", _perturb(params, 2, scale=5e-3))
        _age_blobs(store)
        before = _blob_names(store)
        dry = store.gc(roots=["base-v2"], dry_run=True)
        assert dry["dry_run"] is True
        assert dry["deleted"] > 0
        assert dry["bytes_freed"] > 0
        assert _blob_names(store) == before
        real = store.gc(roots=["base-v2"])
        assert real["deleted"] == dry["deleted"]
        assert real["bytes_freed"] == dry["bytes_freed"]
        assert len(_blob_names(store)) == len(before) - real["deleted"]

    def test_grace_window_shields_inflight_put(self, store, tmp_path):
        params = _params(0)
        _publish(store, tmp_path, "base", params)
        _age_blobs(store)
        # A fresh blob with no manifest looks exactly like an in-flight
        # put whose manifest has not landed yet — kept, counted.
        orphan = os.path.join(store.root, "blobs", "sha256-" + "ab" * 32)
        with open(orphan, "wb") as f:
            f.write(b"manifest has not landed yet")
        stats = store.gc()
        assert os.path.exists(orphan)
        assert stats["kept_young"] == 1
        assert stats["deleted"] == 0
        stats = store.gc(grace_s=0.0)
        assert not os.path.exists(orphan)
        assert stats["deleted"] == 1

    def test_tmp_files_are_never_candidates(self, store, tmp_path):
        params = _params(0)
        _publish(store, tmp_path, "base", params)
        tmp_blob = os.path.join(store.root, "blobs", ".tmp-partial-write")
        with open(tmp_blob, "wb") as f:
            f.write(b"half a blob")
        _age_blobs(store)
        stats = store.gc(grace_s=0.0)
        assert os.path.exists(tmp_blob)
        assert stats["deleted"] == 0

    def test_corrupt_root_manifest_is_typed_refusal(self, store, tmp_path):
        params = _params(0)
        _publish(store, tmp_path, "base-v1", params)
        _publish(store, tmp_path, "base-v2", _perturb(params, 2, scale=5e-3))
        _age_blobs(store)
        mpath = os.path.join(store.root, "policies", "base-v2.json")
        with open(mpath, "w") as f:
            f.write("{ torn manifest")
        before = _blob_names(store)
        with pytest.raises(ArtifactCorrupt, match="repair or delete"):
            store.gc(grace_s=0.0)
        # Refusal deletes NOTHING — a torn mark set never drives a sweep.
        assert _blob_names(store) == before

    def test_missing_explicit_root_is_typed(self, store, tmp_path):
        params = _params(0)
        _publish(store, tmp_path, "base", params)
        with pytest.raises(PolicyNotFound):
            store.gc(roots=["absent"])

    def test_late_landing_manifest_is_remarked(
        self, store, tmp_path, monkeypatch
    ):
        params = _params(0)
        _publish(store, tmp_path, "base-v1", params)
        _publish(store, tmp_path, "base-v2", _perturb(params, 2, scale=5e-3))
        _age_blobs(store)
        # Simulate manifests-land-last: between mark and sweep, a put
        # completes whose manifest ADOPTS base-v1's (otherwise-dead)
        # blobs. The re-check must unmark exactly those candidates.
        v1_manifest = store.manifest("base-v1")
        real_policies = type(store).policies
        calls = {"n": 0}

        def racing_policies(self):
            ids = real_policies(self)
            calls["n"] += 1
            if calls["n"] == 2:  # the sweep-side re-listing
                path = os.path.join(
                    self.root, "policies", "late-lander.json"
                )
                with open(path, "w") as f:
                    json.dump(v1_manifest, f)
                ids = real_policies(self)
            return ids

        monkeypatch.setattr(type(store), "policies", racing_policies)
        stats = store.gc(roots=["base-v2"], grace_s=0.0)
        monkeypatch.undo()
        assert stats["deleted"] == 0
        # Both generations still load: the late lander pinned v1's blobs.
        store.load_weights("base-v2")
        store.load_weights("late-lander")
