"""layers.batch_norm.BatchNorm: bit-parity with flax + deferred stats.

The module replaces every `nn.BatchNorm` in the tree, so its normalize
numerics must be EXACTLY flax's in both modes and both dtypes — pinned
here directly (the module deliberately avoids flax's private
normalization helpers, so this test is the compatibility guarantee a
flax upgrade is checked against)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.layers.batch_norm import (
    NEW_STATS_COLLECTION,
    BatchNorm,
)


def _pair(use_scale, use_bias, momentum=0.9, epsilon=1e-3, dtype=None):
    kwargs = dict(
        momentum=momentum,
        epsilon=epsilon,
        use_scale=use_scale,
        use_bias=use_bias,
        dtype=dtype,
    )
    return BatchNorm(**kwargs), nn.BatchNorm(**kwargs)


@pytest.mark.parametrize("dtype", [None, jnp.bfloat16])
@pytest.mark.parametrize("use_scale", [True, False])
@pytest.mark.parametrize("train", [True, False])
def test_bit_parity_with_flax(dtype, use_scale, train):
    ours, theirs = _pair(use_scale=use_scale, use_bias=True, dtype=dtype)
    x = jax.random.normal(
        jax.random.PRNGKey(0), (4, 6, 6, 8),
        jnp.bfloat16 if dtype is not None else jnp.float32,
    )
    v_ours = ours.init(jax.random.PRNGKey(1), x, use_running_average=False)
    v_theirs = theirs.init(
        jax.random.PRNGKey(1), x, use_running_average=False
    )
    # Same variable structure (drop-in): params + batch_stats.
    assert jax.tree_util.tree_structure(
        v_ours
    ) == jax.tree_util.tree_structure(v_theirs)

    if train:
        (y_ours, updates_ours) = ours.apply(
            v_ours, x, use_running_average=False, mutable=["batch_stats"]
        )
        (y_theirs, updates_theirs) = theirs.apply(
            v_theirs, x, use_running_average=False, mutable=["batch_stats"]
        )
        # In-place EMA path must track flax exactly.
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            updates_ours["batch_stats"],
            updates_theirs["batch_stats"],
        )
    else:
        y_ours = ours.apply(v_ours, x, use_running_average=True)
        y_theirs = theirs.apply(v_theirs, x, use_running_average=True)
    assert y_ours.dtype == y_theirs.dtype
    np.testing.assert_array_equal(np.asarray(y_ours), np.asarray(y_theirs))


def test_deferred_stats_collection():
    """With 'batch_stats_new' mutable, raw batch stats (not an EMA) land
    in the new collection and running stats stay untouched."""
    ours, _ = _pair(use_scale=True, use_bias=True, momentum=0.8)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    variables = ours.init(jax.random.PRNGKey(1), x, use_running_average=False)
    y, updates = ours.apply(
        variables,
        x,
        use_running_average=False,
        mutable=["batch_stats", NEW_STATS_COLLECTION],
    )
    new = updates[NEW_STATS_COLLECTION]
    np.testing.assert_allclose(
        np.asarray(new["mean"]),
        np.asarray(x).mean(0),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(new["var"]),
        np.asarray(x).var(0),
        rtol=1e-5,
    )
    assert float(new["momentum"]) == pytest.approx(0.8)
    # Running stats untouched (still init values).
    np.testing.assert_array_equal(
        np.asarray(updates["batch_stats"]["mean"]), np.zeros(8)
    )
    np.testing.assert_array_equal(
        np.asarray(updates["batch_stats"]["var"]), np.ones(8)
    )
    # Deferral must not change the normalized output: same apply without
    # the new collection (flax-identical in-place path) agrees exactly.
    y_inplace, _ = ours.apply(
        variables, x, use_running_average=False, mutable=["batch_stats"]
    )
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_inplace))
