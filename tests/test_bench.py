"""bench.py contract smoke tests.

The driver runs `python bench.py` / `python bench.py data` at round end and
records the single JSON line; these tests pin that contract (one parseable
line, required keys, sane values) at toy sizes so a regression is caught
before the round-end artifact is produced.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(*args, env_extra=None, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + ":" + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"), *args],
        capture_output=True,
        text=True,
        env=env,
        timeout=timeout,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    return json.loads(line)


@pytest.mark.slow
def test_bench_mfu_contract():
    """The headline MFU path, on the CPU-proxy branch (reduced tower)."""
    payload = _run_bench(env_extra={"BENCH_BACKEND_WAIT": "60"})
    assert payload["metric"] == "qtopt_critic_train_mfu_cpu_proxy"
    assert payload["unit"] == "fraction_of_peak"
    assert 0 < payload["value"] <= 1.0
    assert "error" not in payload
    # CPU-proxy payloads must self-describe (VERDICT r4 weak #6): the
    # top-level proxy flag and the vs_baseline disclaimer, not just a
    # detail-channel backend note.
    assert payload["proxy"] is True
    assert "vs_baseline_note" in payload
    # The proxy self-description includes the on-chip pointer: this repo
    # carries committed TPU headline artifacts, so it must resolve.
    assert payload["last_onchip"] is not None
    assert payload["last_onchip"]["metric"].startswith("qtopt_critic_train_mfu")
    detail = payload["detail"]
    assert detail["steps_per_sec"] > 0
    assert detail["per_step_dispatch_avg_steps_per_sec"] > 0
    assert detail["flops_per_step"] > 0
    assert detail["timing"] == "median_of_windows_best_regime"
    assert detail["per_step_dispatch_best_steps_per_sec"] >= (
        detail["per_step_dispatch_steps_per_sec"]
    )
    assert detail["bf16_forward"] is True
    assert detail["tower_width"] == 64
    # Round-5 provenance fields: which pool VJP and stem lowering this
    # process traced with (the on-chip A/B legs key off these).
    assert detail["pool_backward"] in (
        "auto:native", "auto:scatterfree", "native", "scatterfree"
    )
    assert isinstance(detail["stem_s2d"], bool)
    # The clamped overlap headline can never exceed 1.0; the raw ratio
    # rides alongside whenever the infeed leg ran.
    assert detail["infeed_overlap_efficiency"] <= 1.0
    if detail["infeed_steps_per_sec"] > 0:
        assert "infeed_overlap_efficiency_raw" in detail
        if detail["infeed_overlap_efficiency_raw"] > 1.0:
            assert "infeed_overlap_note" in detail


def test_overlap_fields_clamp():
    """Unit-pins _overlap_fields: impossible >1.0 ratios are clamped and
    annotated; the raw value is preserved."""
    sys.path.insert(0, REPO_ROOT)
    import bench

    noisy = bench._overlap_fields(10.431, 10.0)
    assert noisy["infeed_overlap_efficiency"] == 1.0
    assert noisy["infeed_overlap_efficiency_raw"] == 1.0431
    assert "infeed_overlap_note" in noisy
    clean = bench._overlap_fields(9.8, 10.0)
    assert clean["infeed_overlap_efficiency"] == 0.98
    assert "infeed_overlap_note" not in clean
    assert bench._overlap_fields(1.0, 0.0) == {
        "infeed_overlap_efficiency": 0.0
    }


def test_last_onchip_pointer():
    """Unit-pins _last_onchip (VERDICT r5 next #7): the pointer finds the
    newest committed real-hardware artifact of a metric family, skips
    proxies/failures, and degrades to None for unknown families."""
    sys.path.insert(0, REPO_ROOT)
    import bench

    pointer = bench._last_onchip("qtopt_critic_train_mfu")
    assert pointer is not None
    assert pointer["metric"].startswith("qtopt_critic_train_mfu")
    assert "cpu_proxy" not in pointer["metric"]
    assert pointer["artifact"].endswith(".json")
    # Strict UTC ISO-8601 Zulu (sortable, timezone-unambiguous).
    assert pointer["utc"].endswith("Z") and "T" in pointer["utc"]
    assert bench._last_onchip("metric_family_that_never_existed") is None


def test_analytic_flops_width_scaling():
    """The width knob reaches the analytic FLOPs model: the c128 twin's
    conv tower must cost ~4x the reference 64-wide tower (c_in*c_out)."""
    sys.path.insert(0, REPO_ROOT)
    import bench

    base = bench._analytic_train_flops((472, 472), 64)
    wide = bench._analytic_train_flops((472, 472), 64, width=128)
    assert 3.5 < wide / base < 4.1


@pytest.mark.slow
def test_bench_data_contract():
    """bench.py data on the (default) fast path at toy sizes: one JSON
    line, the three-leg breakdown (fast+cache headline, cold fast,
    SpecParser oracle), and sane values."""
    payload = _run_bench(
        "data",
        env_extra={
            "BENCH_DATA_RECORDS": "8",
            "BENCH_DATA_BATCH": "4",
            "BENCH_DATA_BATCHES": "2",
        },
    )
    assert payload["metric"] == "qtopt_input_pipeline_images_per_sec"
    assert payload["unit"] == "images_per_sec"
    assert payload["value"] > 0
    detail = payload["detail"]
    assert detail["records_per_sec"] > 0
    assert detail["batch_size"] == 4
    assert detail["parse_workers"] >= 1
    # Fast-path provenance: which parser produced the headline and what
    # each mechanism contributed (ISSUE 1 tentpole).
    assert detail["parse_fast"] is True
    assert detail["fast_no_cache_images_per_sec"] > 0
    assert detail["specparser_images_per_sec"] > 0
    assert detail["fast_vs_specparser"] > 0
    if detail["decode_cache_mb"] > 0 and detail["decode_cache"] is not None:
        cache = detail["decode_cache"]
        assert cache["hits"] + cache["misses"] > 0
        assert 0.0 <= cache["hit_rate"] <= 1.0
    # ISSUE 2 tentpole provenance: decode-ROI config, the ROI-off cold
    # attribution twin, the content mode + its r06-continuity legs, and
    # the first measured parse_workers sweep.
    assert detail["content"] == "camera"
    assert detail["decode_roi"] in (True, False)
    assert detail["roi"]["crop"] == [472, 472]
    assert detail["roi"]["source"] == [512, 640]
    assert detail["roi"]["mode"] == "random"
    assert detail["cold_noroi_images_per_sec"] > 0
    assert detail["roi_cold_speedup"] > 0
    assert set(detail["worker_sweep"].keys()) == {"1", "2"}
    for legs in detail["worker_sweep"].values():
        assert legs["cold_images_per_sec"] > 0
        assert legs["fast_images_per_sec"] > 0
        assert legs["specparser_images_per_sec"] > 0
    assert detail["noise_content"]["cold_images_per_sec"] > 0
    assert detail["noise_content"]["cold_noroi_images_per_sec"] > 0


@pytest.mark.slow
def test_bench_data_slow_path_still_runs():
    """T2R_PARSE_FAST=0 must keep the bench (and pipeline) functional —
    the oracle path is the fallback story."""
    payload = _run_bench(
        "data",
        env_extra={
            "BENCH_DATA_RECORDS": "8",
            "BENCH_DATA_BATCH": "4",
            "BENCH_DATA_BATCHES": "2",
            "T2R_PARSE_FAST": "0",
        },
    )
    assert payload["value"] > 0
    assert "error" not in payload


@pytest.mark.slow
def test_bench_auc_contract():
    """The bf16-accuracy-budget leg at toy step counts: pins the JSON
    contract and the tie-safe AUC (values must be genuine fractions, not
    the degenerate 0/1 an untie-corrected rank sum produces on constant
    predictors)."""
    payload = _run_bench(
        "auc",
        env_extra={
            "BENCH_AUC_STEPS": "4",
            "BENCH_AUC_BATCH": "8",
            "BENCH_BACKEND_WAIT": "60",
        },
    )
    # On the CPU backend the metric self-describes as a proxy (the real
    # bf16-MXU budget check runs on TPU under the plain name).
    assert payload["metric"] == "qtopt_bf16_eval_auc_delta_cpu_proxy"
    assert payload["proxy"] is True
    assert payload["unit"] == "auc_delta"
    assert 0.0 <= payload["value"] <= 1.0
    assert "error" not in payload
    # Budget-delta metrics name their ratio honestly (VERDICT r5 weak #6):
    # fraction_of_budget == vs_baseline == value / budget, budget explicit.
    assert payload["budget"] == 0.02
    assert payload["fraction_of_budget"] == payload["vs_baseline"]
    assert payload["fraction_of_budget"] == pytest.approx(
        payload["value"] / 0.02, abs=1e-3
    )
    # Proxy payloads point at the newest on-chip artifact of the family
    # (VERDICT r5 next #7) — present even when None.
    assert "last_onchip" in payload
    detail = payload["detail"]
    assert detail["backend"] == "cpu"
    assert detail["f32_leg_precision"] == "true_f32"
    assert 0.0 <= detail["auc_f32"] <= 1.0
    assert 0.0 <= detail["auc_bf16"] <= 1.0
    assert detail["train_steps"] == 4
    assert detail["auc_method"] == "mann_whitney_rank"


@pytest.mark.slow
def test_bench_predict_contract():
    payload = _run_bench(
        "predict",
        env_extra={"BENCH_BACKEND_WAIT": "60", "BENCH_PREDICT_SAMPLES": "8"},
    )
    assert payload["metric"] == "qtopt_cem_predict_hz_cpu_proxy"
    assert payload["unit"] == "predict_calls_per_sec"
    assert payload["value"] > 0
    assert "error" not in payload
    assert payload["detail"]["cem_samples_per_call"] == 8
    assert payload["detail"]["interface"] == "stablehlo_exported_model"
    assert payload["proxy"] is True
    # The jit-native CEM leg really ran (one fused program per selection).
    assert payload["detail"]["jit_cem_action_selects_per_sec"] > 0


@pytest.mark.slow
def test_bench_pipe_contract():
    """The end-to-end host-pipeline->device-step composite on the proxy
    branch: real tfrecord write -> generator -> parse -> prefetch ->
    train step, ratio against the resident-batch rate."""
    payload = _run_bench(
        "pipe",
        env_extra={"BENCH_BACKEND_WAIT": "60", "BENCH_PIPE_RECORDS": "8"},
    )
    assert payload["metric"] == "qtopt_e2e_pipeline_steps_per_sec_cpu_proxy"
    assert payload["unit"] == "steps_per_sec"
    assert payload["value"] > 0
    assert "error" not in payload
    assert payload["proxy"] is True
    detail = payload["detail"]
    assert detail["resident_batch_steps_per_sec"] > 0
    assert 0 < detail["e2e_fraction_of_compute_rate"]
    assert detail["records_in_file"] == 8
    assert detail["parse_workers"] >= 1


def test_bench_cli_lists_legs():
    """bench.py --help must list every leg; serve --help its options
    (the argparse-subcommand contract that replaced the argv chain)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"), "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for leg in (
        "data", "auc", "predict", "bc", "stream", "pipe", "serve", "comms",
        "fleet", "rl", "aot", "plan", "policies", "fabric", "wire",
    ):
        assert leg in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "fabric", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in (
        "--replicas-per-zone", "--trace-secs", "--deadline-ms",
        "--hedge-ms", "--gold-rps", "--crowd-factor", "--out",
    ):
        assert option in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "policies", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in (
        "--variants", "--replicas", "--trace-secs", "--mem-budget-mb",
        "--policy-mem-mb", "--out",
    ):
        assert option in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "rl", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in (
        "--actors", "--replicas", "--steps", "--seal-episodes",
        "--shards", "--chaos-at-s", "--out",
    ):
        assert option in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "serve", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in ("--buckets", "--burst", "--deadline-ms", "--out"):
        assert option in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "wire", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in (
        "--frames", "--trials", "--warmup", "--image-hw", "--state-dim",
        "--speedup-min", "--quant", "--pipeline-requests", "--out",
    ):
        assert option in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "comms", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in ("--block", "--steps", "--repeats", "--out"):
        assert option in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "aot", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in ("--buckets", "--leg-secs", "--swap-rate-hz", "--out"):
        assert option in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "plan", "--help"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for option in ("--steps", "--steps-3d", "--block", "--out"):
        assert option in proc.stdout
    # Unknown legs are an argparse error now, not a silent fallthrough
    # into the headline benchmark.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"), "bogus"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode != 0


def test_bench_wire_contract(tmp_path):
    """The zero-copy wire codec leg at toy scale, tier-1: one JSON
    line + the --out artifact, every acceptance gate green (bitwise
    replies across codecs, quant parity, zero steady-state receive
    allocs, all corruption variants typed-rejected, pipelining
    overlap), and the observability surface present. The reduced image
    gets a reduced speedup floor — the full camera-sized >= 3x gate is
    the round-end `bench.py wire` run."""
    out = tmp_path / "BENCH_WIRE_smoke.json"
    payload = _run_bench(
        "wire", "--frames", "30", "--trials", "3", "--warmup", "8",
        "--image-hw", "224", "--state-dim", "1024",
        "--pipeline-requests", "12", "--speedup-min", "1.2",
        "--out", str(out),
    )
    assert payload["metric"] == "wire_codec_spec_vs_pickle_reqs_per_sec"
    assert "error" not in payload
    assert all(payload["gates"].values()), payload
    assert payload["ok"] is True
    assert payload["value"] >= 1.2
    assert payload["cpu_proxy"] is True
    detail = payload["detail"]
    assert detail["spec_reqs_per_sec"] > detail["pickle_reqs_per_sec"] > 0
    assert detail["quant_leg"]["rel_linf"] <= detail["quant_leg"][
        "parity_gate"
    ]
    audit = detail["pool_audit"]
    assert (
        audit["after_steady_window"]["allocs"]
        == audit["before_steady_window"]["allocs"]
    )
    variants = detail["corruption_variants"]
    assert variants["typed_rejected"] == variants["total"] > 0
    # Per-stage timings + per-segment-class byte counters surfaced.
    stats = detail["wire_stats"]
    for stage in ("serialize_ms", "crc_ms", "send_ms", "recv_ms",
                  "deserialize_ms"):
        assert stage in stats["timings_ms"]
    for counter in ("frames_spec_tx", "frames_pickle_tx", "bytes_raw",
                    "bytes_skeleton", "bytes_quant", "bytes_pickle"):
        assert counter in stats["counters"]
    assert json.loads(out.read_text())["gates"] == payload["gates"]


@pytest.mark.slow
def test_bench_rl_contract(tmp_path):
    """The closed online-RL loop leg at toy scale: one JSON line + the
    --out artifact, all four legs (fault-free + chaos, sharded
    fault-free + sharded chaos) present, the chaos acceptance block
    all-green (equal learner steps, zero torn segments sampled, bounded
    counted loss, real respawn + actor kill; sharded: zero duplicate
    appends, per-shard loss bounded, coverage loss counted), and the
    headline rates positive. Slow slice: it spawns a replay service,
    shard services, actor processes and a policy-server replica; tier-1
    covers the same loops in-process (tests/test_rl_loop.py,
    tests/test_replay_shard.py) and the CLI surface above."""
    out = str(tmp_path / "rl.json")
    payload = _run_bench(
        "rl", "--steps", "6", "--actors", "2", "--replicas", "1",
        "--shards", "3", "--chaos-at-s", "2.0", "--out", out,
        timeout=560,
    )
    assert payload["metric"] == "rl_loop_episodes_per_sec_cpu_proxy"
    assert payload["unit"] == "episodes_per_sec"
    assert payload["value"] > 0
    assert "error" not in payload
    assert payload["proxy"] is True
    detail = payload["detail"]
    for leg in ("fault_free", "chaos", "sharded_fault_free",
                "sharded_chaos"):
        assert detail[leg]["learner_steps"] == 6
        assert detail[leg]["episodes_appended"] > 0
        assert detail[leg]["samples_drawn"] > 0
        assert detail[leg]["torn_segments_sampled"] == []
    acceptance = detail["acceptance"]
    assert acceptance["learner_steps_equal"] is True
    assert acceptance["zero_torn_segments_sampled"] is True
    assert acceptance["loss_bounded_to_unsealed_tail"] is True
    assert acceptance["replay_service_respawned"] is True
    assert acceptance["actor_killed"] is True
    assert acceptance["sharded_learner_steps_equal"] is True
    assert acceptance["sharded_zero_duplicate_appends"] is True
    assert acceptance["sharded_per_shard_loss_bounded"] is True
    assert acceptance["sharded_shard_respawned"] is True
    assert acceptance["sharded_coverage_loss_counted"] is True
    assert detail["chaos"]["chaos"]["replay_pid"] is not None
    assert detail["sharded_chaos"]["chaos"]["shard_pid"] is not None
    assert detail["sharded_chaos"]["uid_audit"]["episodes"] > 0
    assert detail["replay_ratio"] > 0
    with open(out) as f:
        assert json.load(f)["metric"] == payload["metric"]


@pytest.mark.slow
def test_bench_policies_contract(tmp_path):
    """The multi-policy fleet leg at toy scale: one JSON line + the
    --out artifact, the content-addressed store's delta ratio clearing
    the 5x gate, every acceptance gate green (bitwise-vs-twin, zero
    cross-policy coalesce joins, eviction churn actually exercised,
    per-policy rolling swap with zero blip on other policies, zero
    lost). Slow slice: it publishes dozens of policy exports and spawns
    a 4-replica mock fleet; tier-1 covers the store and policy-server
    contracts in-process (tests/test_artifact_store.py,
    tests/test_policy_fleet.py) and the CLI surface above."""
    out = str(tmp_path / "policies.json")
    payload = _run_bench(
        "policies", "--variants", "40", "--trace-secs", "4",
        "--rate", "90", "--mem-budget-mb", "8", "--out", out,
        timeout=560,
    )
    assert payload["metric"] == "multi_policy_fleet_delta_store_cpu_proxy"
    assert payload["unit"] == "dense_over_store_bytes"
    assert payload["value"] >= 5.0
    assert "error" not in payload
    assert payload["cpu_proxy"] is True
    assert payload["all_green"] is True, payload["gates"]
    for gate in (
        "variants_ge_target", "delta_store_ge_5x",
        "per_policy_bitwise_vs_twin", "zero_cross_policy_joins",
        "coalesce_still_effective", "eviction_churn_counted",
        "swap_zero_blip_other_policies", "zero_lost",
    ):
        assert payload["gates"][gate] is True, gate
    detail = payload["detail"]
    assert detail["store"]["n_delta_policies"] == 40
    assert detail["store"]["delta_ratio"] >= 5.0
    assert detail["evictions"] >= 1
    assert detail["cold_loads"] >= 1
    assert detail["coalesced"] > 0
    assert detail["cross_policy_joins"] == 0
    assert detail["bitwise_mismatches"] == 0
    assert detail["lost"] == 0
    assert detail["swap_result"]["failed"] is None
    with open(out) as f:
        assert json.load(f)["metric"] == payload["metric"]


def test_aot_boot_env_scrubs_every_serving_flag(monkeypatch):
    """The aot leg's child boots must see ONLY the flags the twin under
    measurement sets: a leaked ambient bucket ladder / quant regime /
    cache dir would change what the twins boot and fail the acceptance
    gates (or worse, silently measure the wrong tier)."""
    sys.path.insert(0, REPO_ROOT)
    import bench

    for key, value in {
        "T2R_SERVE_AOT": "0",
        "T2R_AOT_REQUIRE": "1",
        "T2R_COMPILE_CACHE_DIR": "/tmp/leak",
        "T2R_SERVE_BUCKETS": "1,2",
        "T2R_SERVE_QUANT": "int8",
    }.items():
        monkeypatch.setenv(key, value)
    env = bench._aot_scrubbed_env(True, platform="cpu")
    for key in (
        "T2R_AOT_REQUIRE", "T2R_COMPILE_CACHE_DIR",
        "T2R_SERVE_BUCKETS", "T2R_SERVE_QUANT",
    ):
        assert key not in env, key
    assert env["T2R_SERVE_AOT"] == "1"
    assert env["JAX_PLATFORMS"] == "cpu"  # pinned to the parent backend
    cached = bench._aot_scrubbed_env(False, cache_dir="/tmp/tier")
    assert cached["T2R_SERVE_AOT"] == "0"
    assert cached["T2R_COMPILE_CACHE_DIR"] == "/tmp/tier"


@pytest.mark.slow
def test_bench_aot_contract(tmp_path):
    """The instant-deploy leg at toy scale: one JSON line + the --out
    artifact, all three boot twins present, the acceptance block
    all-green — in particular zero fresh bucket compiles on the AOT
    boot (prewarm_source all "aot", fresh_trace_calls == 0) and the AOT
    cold start strictly below the fresh-compile twin's. Slow slice: it
    spawns four cold-boot subprocesses; tier-1 covers the restore
    ladder in-process (tests/test_aot.py) and the CLI surface above."""
    out = str(tmp_path / "aot.json")
    payload = _run_bench(
        "aot", "--buckets", "1,2,4", "--leg-secs", "2.0", "--out", out,
        env_extra={"BENCH_BACKEND_WAIT": "60"},
        timeout=560,
    )
    assert payload["metric"] == "serve_cold_start_aot_speedup_cpu_proxy"
    assert payload["unit"] == "x_cold_start_speedup"
    assert payload["value"] > 1.0  # strictly below fresh = speedup > 1
    assert "error" not in payload
    assert payload["proxy"] is True
    detail = payload["detail"]
    for mode in ("fresh", "cache_first", "cache", "aot"):
        assert detail["boots"][mode]["cold_start_s"] > 0
    aot_boot = detail["boots"]["aot"]
    assert aot_boot["fresh_trace_calls"] == 0
    assert aot_boot["aot_misses"] == 0
    assert set(aot_boot["prewarm_source"].values()) == {"aot"}
    assert aot_boot["aot_hits"] == 3
    # The fresh twin really compiled (its sources are the compile tier).
    assert set(detail["boots"]["fresh"]["prewarm_source"].values()) == {
        "compile"
    }
    assert set(detail["boots"]["cache"]["prewarm_source"].values()) == {
        "cache"
    }
    assert detail["boots"]["cache"]["cache_entries_added"] == 0
    assert detail["boots"]["cache_first"]["cache_entries_added"] > 0
    for tier in ("aot", "compile"):
        swap = detail["rolling_swap"][tier]
        assert swap["failed_requests"] == 0
        assert swap["version_after"] > swap["version_before"]
        assert swap["swap_latency_s"] > 0
    acceptance = detail["acceptance"]
    assert all(acceptance.values()), acceptance
    with open(out) as f:
        assert json.load(f)["metric"] == payload["metric"]


@pytest.mark.slow
def test_bench_serve_contract(tmp_path):
    """The fleet-serving leg at toy scale: one JSON line + the --out
    artifact, with the structural fields the round-end driver and
    PERFORMANCE.md rely on."""
    out = str(tmp_path / "serve.json")
    payload = _run_bench(
        "serve",
        "--burst", "128",
        "--baseline-secs", "0.9",
        "--leg-secs", "1.5",
        "--out", out,
        env_extra={"BENCH_BACKEND_WAIT": "60"},
        timeout=420,
    )
    assert payload["metric"] == "policy_serve_throughput_cpu_proxy"
    assert payload["unit"] == "requests_per_sec"
    assert payload["value"] > 0
    assert "error" not in payload
    assert payload["proxy"] is True
    detail = payload["detail"]
    assert detail["sequential_baseline_hz"] > 0
    assert detail["saturated_hz"] > 0
    assert detail["batched_speedup"] > 0
    # The timed bursts run on a dedicated server (no warm-in batches in
    # the snapshot); fill is ~1.0 at saturation but the first dispatch
    # window of a burst can close partially on a loaded host.
    assert detail["saturation_batch_fill"] >= 0.9
    # Served batch sizes are warmup buckets only.
    buckets = set(detail["buckets"])
    assert set(
        int(k) for k in detail["saturation_batches_by_bucket"]
    ) <= buckets
    for leg in detail["open_loop"].values():
        assert leg["offered_hz"] > 0
        assert "deadline_missed" in leg and "p99_ms" in leg
    swap = detail["hot_swap"]
    assert swap["swap_observed"] is True
    assert swap["version_after"] > swap["version_before"]
    # Round-11 quant legs (regime set widened in r16): every regime
    # served, bytes-of-param reduction reported against the bar, req/s
    # attributed honestly.
    quant = detail["quant"]
    assert set(quant["regimes"]) == {
        "none", "fp16", "int8", "fp8_e4m3", "fp8_e5m2"
    }
    for regime, leg in quant["regimes"].items():
        assert leg["saturated_hz"] > 0, (regime, leg)
        assert leg["params_bytes"] > 0
    assert quant["int8_params_bytes_reduction_x"] >= 3.5
    assert quant["regimes"]["fp16"]["params_bytes_reduction_x"] >= 1.8
    for regime in ("fp16", "int8"):
        parity = quant["regimes"][regime]["parity_recorded"]
        assert parity["max_divergence"]["a_predicted"] <= parity["tolerance"]
    assert "req_s_attribution" in quant
    # Round-18 acceptance: the dequant twin shows zero low-precision
    # contractions, the static-calib artifact shows zero activation-
    # quant reduces, and its AOT cold boot serves bitwise with zero
    # fresh compiles.
    assert quant["native_audit_pass"] is True
    assert quant["native_ab"]["audit_delta_proves_lowering"] is True
    assert quant["calib_ab"]["static_zero_reduce_pass"] is True
    assert quant["calib_ab"]["dynamic_reduces_match_native_layers"] is True
    assert quant["static_aot_boot"]["bitwise_vs_fresh"] is True
    assert quant["static_aot_boot"]["zero_fresh_compiles"] is True
    assert quant["r18_all_green"] is True
    import json as json_mod

    with open(out) as f:
        assert json_mod.load(f)["metric"] == payload["metric"]


@pytest.mark.slow
def test_bench_fleet_contract(tmp_path):
    """The replica-fleet routing leg at toy scale: one JSON line + the
    --out artifact, with the acceptance-criteria fields — sweep legs
    carrying p50/p99/p999 + availability, a SIGKILL chaos leg with ZERO
    lost requests and bounded p99 degradation, and a rolling fleet-wide
    hot-swap with zero failed requests."""
    out = str(tmp_path / "fleet.json")
    payload = _run_bench(
        "fleet",
        "--replicas", "3",
        "--capacity-secs", "0.8",
        "--leg-secs", "1.2",
        "--quant-replicas", "2",
        "--quant-secs", "1.0",
        "--out", out,
        timeout=540,
    )
    assert payload["metric"] == "fleet_router_capacity_cpu_proxy"
    assert payload["unit"] == "requests_per_sec"
    assert payload["value"] > 0
    assert "error" not in payload
    detail = payload["detail"]
    assert detail["replicas"] == 3
    assert len(detail["open_loop"]) == 3
    for leg in detail["open_loop"]:
        for key in ("p50_ms", "p99_ms", "p999_ms", "availability"):
            assert key in leg, leg
        # The zero-lost guarantee: every future resolved (ok or typed).
        assert leg["lost"] == 0, leg
    chaos = detail["chaos"]
    assert chaos["sigkill_leg"]["killed_pid"]
    assert chaos["zero_lost"] is True
    assert chaos["sigkill_leg"]["lost"] == 0
    assert chaos["fault_free_leg"]["lost"] == 0
    assert chaos["p99_degradation_x"] <= chaos["p99_degradation_max"]
    # The kill was real AND the fleet recovered from it.
    assert chaos["counters"]["replica_deaths"] >= 1
    assert chaos["counters"]["respawns"] >= 1
    # Round-11 mixed-precision policy-backend leg: real PolicyServer
    # replicas, replica 0 fp32 / replica 1 int8, regimes verified off
    # the router's health snapshots.
    quant = detail["quant"]
    assert quant["mixed_fleet_verified"] is True
    assert quant["replica_serve_quant"] == ["none", "int8"]
    assert quant["closed_loop_capacity_hz"] > 0
    assert quant["int8_params_bytes_reduction_x"] >= 3.5
    swap = detail["rolling_swap"]
    assert swap["failed_requests"] == 0
    assert swap["lost"] == 0
    assert swap["swap_result"]["failed"] is None
    assert all(
        after > before
        for before, after in zip(
            swap["version_before"], swap["version_after"]
        )
    )
    import json as json_mod

    with open(out) as f:
        assert json_mod.load(f)["metric"] == payload["metric"]


# ~13s on 1 cpu: slow slice with the other bench leg contracts;
# BENCH_GATE_r14.json is the committed audit of the same surface.
@pytest.mark.slow
def test_bench_fabric_contract(tmp_path):
    """The cross-host fabric leg at toy scale (one replica per zone,
    short trace): one JSON line + the --out artifact, socket replicas
    in separate process groups, the partition twin holding gold
    availability at the fault-free bar with zero lost requests (all
    shed typed, per-zone ledgers), post-heal re-resolution, the
    ZoneRouter absorbing the partition, typed per-host AOT rows, and
    the local-transport byte-compat pin."""
    out = str(tmp_path / "fabric.json")
    payload = _run_bench(
        "fabric",
        "--replicas-per-zone", "1",
        "--trace-secs", "5",
        "--out", out,
        timeout=540,
    )
    assert payload["metric"] == "fabric_cross_host_partition_slo_cpu_proxy"
    assert payload["unit"] == "gold_availability_under_zone_partition"
    assert "error" not in payload
    assert payload["cpu_proxy"] is True
    assert payload["ok"] is True, payload["gates"]
    assert all(payload["gates"].values()), payload["gates"]
    detail = payload["detail"]
    # The fleet really spanned separate process groups (no replica in
    # the bench's own group, >= 2 distinct groups).
    assert len(detail["process_groups"]) >= 2
    assert os.getpid() not in detail["process_groups"]
    # Zero lost on BOTH twins; the partition twin's gold bar held.
    for leg_name in ("fault_free_leg", "partition_leg"):
        leg = detail[leg_name]
        assert leg["lost"] == 0, leg_name
        assert set(leg["zone_ledgers"]) == {"z0", "z1"}
    assert (
        detail["partition_leg"]["gold_availability"]
        >= detail["fault_free_leg"]["gold_availability"]
    )
    # The healed zone came back with RESPAWNED pids (re-resolved by
    # published address, not by a stale handle).
    assert detail["z1_pids_after_heal"]
    assert not set(detail["z1_pids_after_heal"]) & set(
        detail["zones"]["z1"]["pids"]
    )
    # Cross-zone survival, typed: the zone-router leg lost nothing.
    assert detail["zone_router_leg"]["lost"] == 0
    assert detail["zone_router_leg"]["z0_wins_during_partition"] >= 16
    # Per-host AOT keys: matching host all-aot, transplanted topology
    # typed (never a silent mismatch load).
    het = detail["heterogeneity"]
    assert het["matching_all_aot"] is True
    assert het["transplanted_host"]["topology"] == 2
    assert het["replies_bitwise_identical"] is True
    with open(out) as f:
        assert json.load(f)["metric"] == payload["metric"]


@pytest.mark.slow
def test_bench_gateway_contract(tmp_path):
    """The multi-tenant front-door leg at toy scale: one JSON line + the
    --out artifact, per-tenant accounting with ZERO lost requests on
    every tier, the rogue bronze tenant 100% typed at its quota, the
    coalescing win with bitwise-equal responses, the SIGKILL + rolling
    swap surviving, and the autoscaler scale-up/drain-back cycle. The
    p99-degradation bar is relaxed for CPU-proxy host variance (the
    committed BENCH_GATE artifact runs the strict default)."""
    out = str(tmp_path / "gate.json")
    payload = _run_bench(
        "gateway",
        "--trace-secs", "6",
        "--drain-secs", "4",
        "--rate-scale", "0.6",
        "--max-replicas", "4",
        "--p99-degradation-max", "10",
        "--out", out,
        timeout=540,
    )
    assert payload["metric"] == "gateway_multitenant_slo_cpu_proxy"
    assert payload["unit"] == "requests_per_sec"
    assert payload["value"] > 0
    assert "error" not in payload
    assert payload["cpu_proxy"] is True
    gates = payload["gates"]
    assert payload["all_green"] is True, gates
    detail = payload["detail"]
    for leg_name in ("fault_free", "chaos"):
        leg = detail[leg_name]
        # Per-request accounting: every submission resolved, ok or typed.
        assert leg["lost_total"] == 0, leg_name
        for tenant, stats in leg["per_tenant"].items():
            assert stats["lost"] == 0, (leg_name, tenant)
    chaos_leg = detail["chaos"]
    # Gold held availability 1.0 through kill + swap + crowd.
    assert chaos_leg["per_tenant"]["web-gold"]["availability"] == 1.0
    # The rogue bronze tenant was quota-bound, 100% typed.
    rogue = chaos_leg["per_tenant"]["rogue-bronze"]
    assert rogue["shed_at_admission"].get("TenantThrottled", 0) > 0
    assert rogue["availability"] < 0.5
    # Coalescing measurably cut dispatches, bitwise-equal responses.
    assert chaos_leg["per_tenant"]["app-silver-hot"]["coalesced"] > 0
    assert chaos_leg["gateway_counters"]["coalesced_joins"] > 0
    assert all(
        len(v) == 1 for v in chaos_leg["hot_y_groups"].values()
    )
    # The kill was real, the fleet recovered, the swap published.
    assert chaos_leg["killed_pid"]
    assert chaos_leg["router_counters"]["replica_deaths"] >= 1
    assert chaos_leg["router_counters"]["respawns"] >= 1
    assert chaos_leg["swap_result"]["failed"] is None
    assert max(chaos_leg["versions_observed"]) >= 2
    # The autoscaler reached the ceiling during the crowd and drained
    # back without a single aborted retirement.
    assert chaos_leg["autoscaler"]["peak_replicas_up"] >= 4
    assert chaos_leg["autoscaler"]["counters"].get("scale_down", 0) >= 1
    assert chaos_leg["router_counters"].get("retirement_aborts", 0) == 0
    import json as json_mod

    with open(out) as f:
        assert json_mod.load(f)["metric"] == payload["metric"]


@pytest.mark.slow
def test_bench_plan_contract(tmp_path):
    """The sharding-planner leg at toy step counts: one JSON line + the
    --out artifact, every preset byte-equal with a clean audit, the DP
    family bitwise planner-vs-hand, and the 3D (2x2x2) leg green with
    per-axis wire-byte attribution and the ranked plan table."""
    out = str(tmp_path / "plan.json")
    payload = _run_bench(
        "plan", "--steps", "2", "--steps-3d", "3", "--out", out,
        timeout=700,
    )
    assert payload["metric"] == "plan_preset_byte_equality"
    assert payload["value"] == 1.0
    assert "error" not in payload
    assert all(payload["gates"].values()), payload["gates"]
    audit = payload["detail"]["byte_audit"]
    for preset in (
        "dp", "dp_zero2", "dp_zero2_int8", "dp_zero2_fp8_e4m3",
        "dp_zero2_fp8_e5m2", "dp_sp", "dp_pp", "dp_pp_zero2",
    ):
        assert audit[preset]["layouts_equal"] is True, preset
        assert audit[preset]["audit_mismatches"] == 0, preset
    for preset in ("dp", "dp_zero2", "dp_zero2_int8"):
        assert audit[preset]["params_bitwise_equal"] is True
        assert audit[preset]["loss_abs_diff"] == 0.0
    plan3d = payload["detail"]["plan3d"]
    assert plan3d["preset"]["weight_update_axes"] == ["data", "sequence"]
    assert plan3d["loss_parity_max_abs_diff"] < 1e-3
    axes = {a for e in plan3d["wire_byte_attribution"] for a in e["axes"]}
    assert {"data", "sequence", "pipe"} <= axes
    table = payload["detail"]["ranked_plan_table"]["table"]
    assert len(table) >= 4
    assert any(
        e["plan"]["name"] == "dp2_sp2_pp2" and e["feasible"]
        for e in table
    )
    # Round 19: the widened points pass their parity twins and rank in
    # the widened table.
    widened = payload["detail"]["widened"]
    assert widened["tp"]["loss_parity_max_abs_diff"] < 1e-3
    assert widened["ulysses_in_pipe"]["loss_parity_max_abs_diff"] < 1e-3
    widened_table = widened["ranked_plan_table"]["table"]
    feasible = {
        e["plan"]["name"] for e in widened_table if e["feasible"]
    }
    assert {"dp4_sp1_pp1_tp2", "dp1_sp4_pp2"} <= feasible
    # Round 19: the measured search stores its winner; the warm run
    # replays it byte-for-byte with zero search compiles.
    measured = payload["detail"]["measured_search"]
    assert measured["cold_stats"]["source"] == "measured"
    assert measured["cold_stats"]["probe_compiles"] >= 1
    assert measured["warm_stats"]["source"] == "cache"
    assert measured["warm_stats"]["probe_compiles"] == 0
    assert measured["winner_step_time_ms"] > 0
    assert 0.0 <= measured["analytic_vs_measured_rank_agreement"] <= 1.0
    with open(out) as f:
        assert json.load(f)["metric"] == payload["metric"]


@pytest.mark.slow
def test_bench_comms_contract(tmp_path):
    """The quantized-collective leg at toy step counts: one JSON line +
    the --out artifact, the >=3.5x int8 bytes-reduction bar, loss parity
    within tolerance, and the none-path byte-identity bit."""
    out = str(tmp_path / "comms.json")
    payload = _run_bench(
        "comms", "--steps", "6", "--repeats", "2", "--out", out,
        timeout=560,
    )
    assert payload["metric"] == "zero2_collective_bytes_reduction"
    assert payload["unit"] == "x_fewer_wire_bytes"
    assert payload["value"] >= 3.5
    assert payload["vs_baseline"] >= 1.0
    assert payload["proxy"] is True
    assert payload["parity_ok"] is True
    assert payload["none_byte_identical"] is True
    legs = payload["detail"]["legs"]
    for name in ("none", "fp16", "int8"):
        assert legs[name]["collective/wall_ms"] > 0
        assert legs[name]["collective/bytes_post"] > 0
    assert legs["none"]["collective/compression"] == 1.0
    assert legs["fp16"]["collective/compression"] > 1.9
    parity = payload["detail"]["parity"]
    assert parity["int8_abs_diff"] < parity["tolerance"]
    # The tree really is QT-Opt-critic sized (not a toy vector).
    assert payload["detail"]["n_params"] > 1_000_000
    import json as json_mod

    with open(out) as f:
        assert json_mod.load(f)["value"] == payload["value"]
