"""testing/chaos.py: deterministic fault plans.

The whole value of the chaos harness is determinism — a plan must fire
the same fault at the same occurrence every run, scoped to the right
process, and a malformed plan must fail loudly. These tests pin that
contract; the router/crash-consistency suites then lean on it.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.testing import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


class TestParse:
    def test_empty_and_none(self):
        assert chaos.parse_plan(None) == ()
        assert chaos.parse_plan("") == ()
        assert chaos.parse_plan(" ; ; ") == ()

    def test_full_grammar(self):
        plan = chaos.parse_plan(
            "r0/predict:3:kill; save:2:sigkill ;reply:1:corrupt;"
            "predict:5:delay:250;restore:1:hang:10;loop:2:raise"
        )
        assert [c.describe() for c in plan] == [
            "r0/predict:3:kill",
            "save:2:sigkill",
            "reply:1:corrupt",
            "predict:5:delay:250",
            "restore:1:hang:10",
            "loop:2:raise",
        ]
        assert plan[0].scope == "r0" and plan[1].scope is None
        assert plan[3].arg_ms == 250.0

    @pytest.mark.parametrize(
        "bad",
        [
            "predict:3",  # missing action
            "predict:x:kill",  # bad occurrence
            "predict:0:kill",  # 0: occurrences are 1-based
            "predict:1:explode",  # unknown action
            "predict:1:delay",  # delay needs ms
            "predict:1:delay:abc",  # bad ms
            "predict:1:delay:999999",  # over the stall cap
            "predict:1:kill:5",  # kill takes no arg
            "/predict:1:kill",  # empty scope
            ":1:kill",  # empty site
        ],
    )
    def test_malformed_plans_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            chaos.parse_plan(bad)

    def test_flag_declared(self):
        spec = t2r_flags.get_flag("T2R_CHAOS")
        assert spec.kind == "str" and spec.default is None

    def test_network_action_grammar(self):
        plan = chaos.parse_plan(
            "net_send:1:drop;net_recv:2:slow:150;"
            "net_send:3:partition:s1+s2"
        )
        assert [c.describe() for c in plan] == [
            "net_send:1:drop",
            "net_recv:2:slow:150",
            "net_send:3:partition:s1+s2",
        ]
        assert plan[1].arg_ms == 150.0
        assert plan[2].peers == ("s1", "s2")

    @pytest.mark.parametrize(
        "bad",
        [
            "net_send:1:drop:5",  # drop takes no arg
            "net_send:1:slow",  # slow needs ms
            "net_send:1:partition",  # partition needs peers
            "net_send:1:partition:",  # empty peer list
            "net_send:1:partition:s1++s2",  # empty peer in list
        ],
    )
    def test_malformed_network_plans_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            chaos.parse_plan(bad)


class TestFire:
    def test_inert_without_plan(self):
        assert chaos.maybe_fire("predict") is None
        assert not chaos.active()

    def test_fires_at_exact_occurrence_once(self):
        chaos.configure("predict:3:corrupt")
        hits = [chaos.maybe_fire("predict") for _ in range(6)]
        assert [h.action if h else None for h in hits] == [
            None, None, "corrupt", None, None, None,
        ]
        assert chaos.fired() == ["predict:3:corrupt"]
        assert chaos.counters() == {"predict": 6}

    def test_sites_count_independently(self):
        chaos.configure("a:2:corrupt;b:1:corrupt")
        assert chaos.maybe_fire("a") is None
        assert chaos.maybe_fire("b").site == "b"
        assert chaos.maybe_fire("a").site == "a"

    def test_scope_gating(self):
        chaos.configure("r1/predict:1:corrupt")
        assert chaos.maybe_fire("predict") is None  # no scope declared
        chaos.configure("r1/predict:1:corrupt")
        chaos.set_scope("r0")
        assert chaos.maybe_fire("predict") is None  # wrong scope
        chaos.configure("r1/predict:1:corrupt")
        chaos.set_scope("r1")
        assert chaos.maybe_fire("predict").action == "corrupt"

    def test_drop_fires_once_and_returns_to_caller(self):
        chaos.configure("net_send:2:drop")
        assert chaos.maybe_fire("net_send") is None
        assert chaos.maybe_fire("net_send").action == "drop"
        assert chaos.maybe_fire("net_send") is None  # single-shot

    def test_partition_persists_and_matches_only_named_peers(self):
        chaos.configure("net_send:2:partition:s1+s3")
        assert chaos.maybe_fire("net_send", peer="s1") is None  # occ 1
        assert chaos.maybe_fire("net_send", peer="s1").action == "partition"
        assert chaos.maybe_fire("net_send", peer="s2") is None  # not cut
        assert chaos.maybe_fire("net_send", peer="s3").action == "partition"
        assert chaos.maybe_fire("net_send") is None  # peer-less: not cut
        # Still firing many occurrences later (a partition never
        # self-heals), and the fired log records it exactly once.
        for _ in range(5):
            assert (
                chaos.maybe_fire("net_send", peer="s1").action == "partition"
            )
        assert chaos.fired() == ["net_send:2:partition:s1+s3"]

    def test_receive_side_partition_matches_own_scope(self):
        """The receiver cannot know its caller, so net_recv reports its
        OWN scope as peer (replay/transport.py): a partition naming a
        shard cuts that shard's receive side when installed in its
        process."""
        chaos.configure("net_recv:1:partition:s1")
        chaos.set_scope("s1")
        hit = chaos.maybe_fire("net_recv", peer=chaos.get_scope())
        assert hit is not None and hit.action == "partition"

    def test_delay_sleeps_roughly_arg(self):
        chaos.configure("predict:1:delay:120")
        t0 = time.monotonic()
        hit = chaos.maybe_fire("predict")
        took = time.monotonic() - t0
        assert hit.action == "delay"
        assert took >= 0.1

    def test_raise_action(self):
        chaos.configure("step:2:raise")
        chaos.maybe_fire("step")
        with pytest.raises(chaos.ChaosFault):
            chaos.maybe_fire("step")

    def test_env_flag_route(self, monkeypatch):
        monkeypatch.setenv("T2R_CHAOS", "boot:1:corrupt")
        chaos.reset()  # re-arm env loading
        assert chaos.active()
        assert chaos.maybe_fire("boot").action == "corrupt"

    def test_determinism_across_runs(self):
        """Same plan + same call sequence -> identical fired history."""
        histories = []
        for _ in range(2):
            chaos.configure("a:2:corrupt;b:3:corrupt")
            for site in ("a", "b", "a", "b", "b", "a"):
                try:
                    chaos.maybe_fire(site)
                except chaos.ChaosFault:
                    pass
            histories.append(chaos.fired())
        assert histories[0] == histories[1] == [
            "a:2:corrupt", "b:3:corrupt",
        ]


class TestTenantScopes:
    """The gateway/autoscaler sites (`admit`/`coalesce`/`scale`) and the
    per-tenant call-site scopes `t<i>`: one clause targets ONE tenant
    inside the shared gateway process, counting occurrences per scope."""

    def test_gateway_site_grammar(self):
        plan = chaos.parse_plan(
            "t0/admit:3:raise;t2/coalesce:1:drop;scale:2:drop;"
            "t1/admit:2:delay:50"
        )
        assert [c.describe() for c in plan] == [
            "t0/admit:3:raise",
            "t2/coalesce:1:drop",
            "scale:2:drop",
            "t1/admit:2:delay:50",
        ]
        assert plan[0].scope == "t0"
        assert plan[2].scope is None

    @pytest.mark.parametrize(
        "bad",
        [
            "t0/admit:0:raise",  # occurrences are 1-based
            "t0/admit:1:throttle",  # unknown action
            "/admit:1:raise",  # empty scope
            "t0/admit:1:raise:5",  # raise takes no arg
            "t0/coalesce:1:drop:x",  # drop takes no arg
            "scale:1:delay",  # delay needs ms
        ],
    )
    def test_malformed_gateway_plans_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            chaos.parse_plan(bad)

    def test_call_scope_counts_per_tenant(self):
        """t1/admit:2:corrupt fires at tenant t1's SECOND admit — not at
        the process's second admit overall."""
        chaos.configure("t1/admit:2:corrupt")
        assert chaos.maybe_fire("admit", scope="t0") is None
        assert chaos.maybe_fire("admit", scope="t1") is None  # t1 occ 1
        assert chaos.maybe_fire("admit", scope="t0") is None
        hit = chaos.maybe_fire("admit", scope="t1")  # t1 occ 2
        assert hit is not None and hit.action == "corrupt"
        assert chaos.maybe_fire("admit", scope="t1") is None  # single-shot
        assert chaos.counters() == {
            "admit": 5, "admit@t0": 2, "admit@t1": 3,
        }
        assert chaos.fired() == ["t1/admit:2:corrupt"]

    def test_call_scope_does_not_leak_to_other_tenants(self):
        chaos.configure("t0/coalesce:1:drop")
        for _ in range(3):
            assert chaos.maybe_fire("coalesce", scope="t1") is None
        assert chaos.maybe_fire("coalesce", scope="t0").action == "drop"

    def test_unscoped_clause_counts_process_wide(self):
        """An unscoped clause on a scoped site fires at the Nth visit
        across ALL tenants (the pre-existing process-wide semantics)."""
        chaos.configure("admit:3:corrupt")
        assert chaos.maybe_fire("admit", scope="t0") is None
        assert chaos.maybe_fire("admit", scope="t1") is None
        assert chaos.maybe_fire("admit", scope="t2").action == "corrupt"

    def test_process_scope_still_matches_without_call_scope(self):
        """Call scopes must not break the replica-style process scope:
        the scale site in a process declaring no scope matches unscoped
        clauses; a process-scoped clause still needs set_scope."""
        chaos.configure("scale:1:drop")
        assert chaos.maybe_fire("scale").action == "drop"
        chaos.configure("r1/scale:1:drop")
        assert chaos.maybe_fire("scale") is None
        chaos.configure("r1/scale:1:drop")
        chaos.set_scope("r1")
        assert chaos.maybe_fire("scale").action == "drop"

    def test_scoped_flake_recovers_per_tenant(self):
        """flake:N against a tenant scope fails that tenant's first N
        visits from the start point and then clears — the retry-recovery
        fixture, per tenant."""
        chaos.configure("t0/admit:1:flake:2")
        for _ in range(2):
            with pytest.raises(chaos.ChaosFault):
                chaos.maybe_fire("admit", scope="t0")
            assert chaos.maybe_fire("admit", scope="t1") is None
        assert chaos.maybe_fire("admit", scope="t0") is None  # recovered


class TestKill:
    def test_kill_is_a_real_sigkill(self, tmp_path):
        """The kill action must be an uncatchable SIGKILL — no atexit, no
        finally blocks — because that is the crash the recovery paths
        claim to survive."""
        script = (
            "import sys\n"
            "from tensor2robot_tpu import flags\n"
            "from tensor2robot_tpu.testing import chaos\n"
            "flags.write_env('T2R_CHAOS', 'work:2:kill')\n"
            "try:\n"
            "    for i in range(5):\n"
            "        chaos.maybe_fire('work')\n"
            "        print('tick', i, flush=True)\n"
            "finally:\n"
            "    print('CLEANUP_RAN', flush=True)\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert proc.returncode == -signal.SIGKILL
        assert "tick 0" in proc.stdout
        assert "tick 1" not in proc.stdout  # died inside the 2nd visit
        assert "CLEANUP_RAN" not in proc.stdout
