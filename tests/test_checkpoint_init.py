"""Default warm-start from foreign orbax checkpoints.

Rebuild of the reference warm-start contract: assignment maps, partial
restore, and restorables filtering (models/abstract_model.py:86-126; test at
utils/train_eval_test.py:204).
"""

import os

import jax
import numpy as np
import pytest

from tensor2robot_tpu.models.checkpoint_init import (
    default_init_from_checkpoint_fn,
    flatten_with_paths,
    load_checkpoint_variables,
    path_str,
)
from tensor2robot_tpu.train import train_eval
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BATCH_SIZE = 16


@pytest.fixture(scope="module")
def trained_model_dir(tmp_path_factory):
    model_dir = str(tmp_path_factory.mktemp("donor") / "run")
    train_eval.train_eval_model(
        t2r_model=MockT2RModel(device_type="cpu"),
        input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
        model_dir=model_dir,
        max_train_steps=30,
        save_checkpoints_steps=30,
        log_every_steps=30,
    )
    return model_dir


def _init_variables(model):
    generator = MockInputGenerator(batch_size=BATCH_SIZE)
    train_eval.provide_input_generator_with_model_information(
        generator, model, "train"
    )
    batch = next(iter(generator.create_dataset("train")))
    features, _ = model.preprocessor.preprocess(
        batch["features"], batch["labels"], mode="train",
        rng=jax.random.PRNGKey(0),
    )
    return model.init_variables(jax.random.PRNGKey(1), features), batch


class TestDefaultWarmStart:
    def test_full_restore_matches_checkpoint(self, trained_model_dir):
        model = MockT2RModel(
            device_type="cpu",
            init_from_checkpoint_fn=default_init_from_checkpoint_fn(
                trained_model_dir
            ),
        )
        variables, _ = _init_variables(model)
        warm = model.maybe_init_from_checkpoint(variables)
        source = load_checkpoint_variables(trained_model_dir)
        flat_warm = flatten_with_paths(warm)
        flat_src = flatten_with_paths(source)
        assert set(flat_warm) == set(flat_src)
        for path, leaf in flat_warm.items():
            np.testing.assert_allclose(
                np.asarray(leaf, np.float32),
                np.asarray(flat_src[path], np.float32),
                err_msg=path,
            )

    def test_missing_leaf_raises_without_partial(self, trained_model_dir):
        init_fn = default_init_from_checkpoint_fn(
            trained_model_dir,
            assignment_map={"params/": "params/nonexistent/"},
        )
        model = MockT2RModel(device_type="cpu")
        variables, _ = _init_variables(model)
        with pytest.raises(KeyError, match="missing from checkpoint"):
            init_fn(variables)

    def test_partial_restore_keeps_fresh_init(self, trained_model_dir):
        # A differently-shaped sibling: pretend the donor lacks some leaves
        # by dropping a subtree via assignment_map -> None, plus a bogus
        # mapping tolerated by allow_partial_restore.
        model = MockT2RModel(device_type="cpu")
        variables, _ = _init_variables(model)
        flat_before = flatten_with_paths(variables)
        some_param = sorted(
            p for p in flat_before if p.startswith("params/")
        )[0]
        prefix = some_param.rsplit("/", 1)[0] + "/"
        init_fn = default_init_from_checkpoint_fn(
            trained_model_dir,
            assignment_map={prefix: None},  # keep fresh init for this subtree
            allow_partial_restore=True,
        )
        warm = flatten_with_paths(init_fn(variables))
        source = flatten_with_paths(
            load_checkpoint_variables(trained_model_dir)
        )
        np.testing.assert_array_equal(
            np.asarray(warm[some_param]), np.asarray(flat_before[some_param])
        )
        restored = [
            p for p in warm
            if not p.startswith(prefix) and p.startswith("params/")
        ]
        assert restored
        for path in restored:
            np.testing.assert_allclose(
                np.asarray(warm[path], np.float32),
                np.asarray(source[path], np.float32),
                err_msg=path,
            )

    def test_filter_restorables_fn(self, trained_model_dir):
        model = MockT2RModel(device_type="cpu")
        variables, _ = _init_variables(model)
        flat_before = flatten_with_paths(variables)
        init_fn = default_init_from_checkpoint_fn(
            trained_model_dir,
            filter_restorables_fn=lambda path: "kernel" in path,
        )
        warm = flatten_with_paths(init_fn(variables))
        source = flatten_with_paths(
            load_checkpoint_variables(trained_model_dir)
        )
        kernels = [p for p in warm if "kernel" in p]
        non_kernels = [p for p in warm if "kernel" not in p]
        assert kernels and non_kernels
        for path in kernels:
            np.testing.assert_allclose(
                np.asarray(warm[path]), np.asarray(source[path]), err_msg=path
            )
        for path in non_kernels:
            np.testing.assert_array_equal(
                np.asarray(warm[path]), np.asarray(flat_before[path]),
                err_msg=path,
            )

    def test_shape_mismatch_raises(self, trained_model_dir):
        model = MockT2RModel(device_type="cpu")
        variables, _ = _init_variables(model)
        flat = flatten_with_paths(variables)
        kernel_path = sorted(p for p in flat if "kernel" in p)[0]
        # Grow a leaf so the checkpoint's no longer fits.
        paths, treedef = jax.tree_util.tree_flatten_with_path(variables)
        bad_leaves = []
        for key_path, leaf in paths:
            path = path_str(key_path)
            if path == kernel_path:
                leaf = np.zeros(
                    tuple(d + 1 for d in np.shape(leaf)), np.float32
                )
            bad_leaves.append(leaf)
        bad_variables = jax.tree_util.tree_unflatten(treedef, bad_leaves)
        init_fn = default_init_from_checkpoint_fn(trained_model_dir)
        with pytest.raises(ValueError, match="shape mismatch"):
            init_fn(bad_variables)

    def test_end_to_end_warm_start_through_trainer(
        self, trained_model_dir, tmp_path
    ):
        """Warm-started training resumes from the donor's loss level."""
        model_dir = str(tmp_path / "warm")
        model = MockT2RModel(
            device_type="cpu",
            init_from_checkpoint_fn=default_init_from_checkpoint_fn(
                trained_model_dir
            ),
        )
        train_eval.train_eval_model(
            t2r_model=model,
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=model_dir,
            max_train_steps=10,
            save_checkpoints_steps=10,
            log_every_steps=1,
        )
        from tensor2robot_tpu.train.metrics import read_metrics

        rows = read_metrics(os.path.join(model_dir, "train"))
        fresh_dir = str(tmp_path / "fresh")
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=fresh_dir,
            max_train_steps=10,
            save_checkpoints_steps=10,
            log_every_steps=1,
        )
        fresh_rows = read_metrics(os.path.join(fresh_dir, "train"))
        assert rows[0]["loss"] < fresh_rows[0]["loss"] * 0.8
