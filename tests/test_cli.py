"""The three CLI binaries as real OS processes (reference bin/ parity).

Library-level config execution is covered elsewhere (test_pose_env runs
every shipped gin config through train_eval_model); these tests close the
last gap between "the function works" and "the shipped command works":
each binary runs as `python -m tensor2robot_tpu.bin.<name>` in a fresh
interpreter with real flags, and the test asserts the artifacts the
reference topology relies on (README:44-51: collect writes shards, the
trainer writes checkpoints, continuous-eval writes eval events).

The children force the CPU backend through a tiny runpy shim — this
image's TPU plugin ignores JAX_PLATFORMS, and only jax.config.update
before backend init bypasses it (same trick as tests/conftest.py).
"""

import glob
import os
import subprocess
import sys

import pytest

_SHIM = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import runpy
sys.argv = sys.argv[1:]
runpy.run_module(sys.argv[0], run_name="__main__", alter_sys=True)
"""


def _run_cli(module, args, timeout=420):
    proc = subprocess.run(
        [sys.executable, "-c", _SHIM, module, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, (
        f"{module} failed rc={proc.returncode}\n"
        f"stdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-2000:]}"
    )
    return proc


def _config_dir():
    from tensor2robot_tpu.research import pose_env

    return os.path.join(os.path.dirname(pose_env.__file__), "configs")


@pytest.mark.slow
def test_collect_then_train_then_eval_clis(tmp_path):
    """The full process topology, one CLI at a time: random collect ->
    trainer -> continuous eval, each a separate OS process exchanging
    data only through the filesystem (the reference's message bus)."""
    collect_dir = tmp_path / "collect"
    _run_cli(
        "tensor2robot_tpu.bin.run_collect_eval",
        [
            f"--root_dir={collect_dir}",
            f"--gin_configs={os.path.join(_config_dir(), 'run_random_collect.gin')}",
            "--gin_bindings=collect_eval_loop.num_collect = 12",
        ],
    )
    shards = glob.glob(str(collect_dir / "policy_collect" / "*.tfrecord"))
    if not shards:  # layout fallback: any shard under the root
        shards = glob.glob(str(collect_dir / "**" / "*.tfrecord"), recursive=True)
    assert shards, f"collect CLI wrote no shards under {collect_dir}"

    run_dir = tmp_path / "run"
    _run_cli(
        "tensor2robot_tpu.bin.run_t2r_trainer",
        [
            f"--gin_configs={os.path.join(_config_dir(), 'run_train_reg.gin')}",
            f"--gin_bindings=TRAIN_DATA = {shards!r}",
            f"--gin_bindings=EVAL_DATA = {shards!r}",
            "--gin_bindings=train_eval_model.max_train_steps = 2",
            "--gin_bindings=train_eval_model.eval_steps = 1",
            "--gin_bindings=train_input_generator/DefaultRecordInputGenerator.batch_size = 4",
            "--gin_bindings=eval_input_generator/DefaultRecordInputGenerator.batch_size = 4",
            "--gin_bindings=PoseEnvRegressionModel.device_type = 'cpu'",
            f"--gin_bindings=train_eval_model.model_dir = {str(run_dir)!r}",
        ],
    )
    assert os.path.isdir(run_dir / "checkpoints"), "trainer CLI wrote no checkpoints"
    operative = glob.glob(str(run_dir / "operative_config*"))
    assert operative, "trainer CLI wrote no operative config artifact"

    _run_cli(
        "tensor2robot_tpu.bin.run_continuous_eval",
        [
            f"--gin_configs={os.path.join(_config_dir(), 'run_train_reg.gin')}",
            f"--gin_bindings=EVAL_DATA = {shards!r}",
            "--gin_bindings=eval_input_generator/DefaultRecordInputGenerator.batch_size = 4",
            "--gin_bindings=PoseEnvRegressionModel.device_type = 'cpu'",
            "--gin_bindings=continuous_eval.t2r_model = @PoseEnvRegressionModel()",
            "--gin_bindings=continuous_eval.input_generator_eval = %EVAL_INPUT_GENERATOR",
            f"--gin_bindings=continuous_eval.model_dir = {str(run_dir)!r}",
            "--gin_bindings=continuous_eval.eval_steps = 1",
            "--gin_bindings=continuous_eval.max_train_steps = 2",
            "--gin_bindings=continuous_eval.timeout = 60.0",
        ],
    )
    eval_artifacts = glob.glob(str(run_dir / "eval*")) + glob.glob(
        str(run_dir / "*" / "eval*")
    )
    assert eval_artifacts, f"continuous-eval CLI wrote nothing under {run_dir}"
