"""Parity suite for the quantized gradient collectives.

Covers the registry itself (blockwise quantizers, reduce-scatter /
all-gather vs the exact lax.psum family on the 8-device host mesh) and
the quantized ZeRO-2 trainer integration: error-feedback determinism
across seeds and restarts, `T2R_COLLECTIVE_QUANT=none` exact-equality
with the GSPMD path, and checkpoint round-trip of the residual state.
"""

import numpy as np
import pytest

import jax
import jax.flatten_util
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensor2robot_tpu import flags
from tensor2robot_tpu.parallel import collectives
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.train import train_eval
from tensor2robot_tpu.train.state import ema_as_tree
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

DATA = mesh_lib.DATA_AXIS
N = 8  # the virtual host mesh (conftest forces 8 devices)
BLOCK = 64
L = 4 * BLOCK  # per-peer chunk length


def _mesh():
    return mesh_lib.make_mesh(data=N)


def _rows(seed: int, scale: float = 1.0) -> np.ndarray:
    """[N_dev, N_chunk, L]: device d's local gradient rows are [d]."""
    rng = np.random.RandomState(seed)
    return (rng.randn(N, N, L) * scale).astype(np.float32)


def _run_reduce_scatter(coll, rows_global):
    mesh = _mesh()

    def local(rows):
        reduced, sent = coll.reduce_scatter(rows[0], DATA)
        return reduced[None], sent[None]

    fn = collectives.smap(local, mesh, (P(DATA),), (P(DATA), P(DATA)))
    reduced, sent = fn(jnp.asarray(rows_global))
    return np.asarray(reduced), np.asarray(sent)


def _run_all_gather(coll, shards_global):
    mesh = _mesh()

    def local(shard):
        full, sent = coll.all_gather_shard(shard[0], DATA)
        return full[None], sent

    fn = collectives.smap(local, mesh, (P(DATA),), (P(DATA), P(DATA)))
    full, sent = fn(jnp.asarray(shards_global))
    return np.asarray(full), np.asarray(sent)


#: Per-format quantization step as a fraction of the block max-abs:
#: half of each is the worst-case per-element rounding error. int8 is an
#: ABSOLUTE step (scale/127); the float formats round RELATIVE to the
#: value (<= the block max), with 10 mantissa bits for fp16, 3 for
#: fp8_e4m3, 2 for fp8_e5m2.
STEP_FACTORS = {
    "fp16": 2.0 ** -10,
    "int8": 1 / 127.0,
    "fp8_e4m3": 2.0 ** -3,
    "fp8_e5m2": 2.0 ** -2,
}

QUANT_NAMES = sorted(STEP_FACTORS)
ALL_NAMES = ["none"] + QUANT_NAMES


class TestQuantizers:
    @pytest.mark.parametrize("name", QUANT_NAMES)
    def test_roundtrip_error_bound(self, name):
        coll = collectives.get_collective(name, BLOCK)
        x = jnp.asarray(_rows(0)[0])
        decoded = np.asarray(coll.decode(coll.encode(x)))
        blocks = np.asarray(x).reshape(N, L // BLOCK, BLOCK)
        scale = np.abs(blocks).max(axis=-1, keepdims=True)
        step = scale * STEP_FACTORS[name]
        err = np.abs(decoded.reshape(blocks.shape) - blocks)
        assert (err <= step * 0.5 * (1 + 1e-6) + 1e-12).all()

    @pytest.mark.parametrize("name", ["fp8_e4m3", "fp8_e5m2"])
    def test_fp8_encode_is_finite_and_1_byte(self, name):
        """The clip before the fp8 cast is load-bearing: jax fp8 casts
        don't saturate, so a block max landing ABOVE the format max
        after rounding would decode as NaN and poison the reduced
        shard. Large-magnitude rows + payload dtype/size pinned."""
        coll = collectives.get_collective(name, BLOCK)
        x = jnp.asarray(_rows(5, scale=1e4)[0])
        payload = coll.encode(x)
        assert np.asarray(payload["q"]).dtype.itemsize == 1
        decoded = np.asarray(coll.decode(payload))
        assert np.isfinite(decoded).all()
        assert coll.wire_bytes(1 << 20) == (1 << 20) + 4 * ((1 << 20) // BLOCK)

    @pytest.mark.parametrize("name", ALL_NAMES)
    def test_deterministic(self, name):
        coll = collectives.get_collective(name, BLOCK)
        x = jnp.asarray(_rows(3)[0])
        a = jax.device_get(coll.decode(coll.encode(x)))
        b = jax.device_get(coll.decode(coll.encode(x)))
        np.testing.assert_array_equal(a, b)

    def test_zero_blocks_decode_to_zero(self):
        coll = collectives.get_collective("int8", BLOCK)
        x = jnp.zeros((2, L))
        decoded = np.asarray(coll.decode(coll.encode(x)))
        np.testing.assert_array_equal(decoded, np.zeros((2, L)))

    def test_unknown_collective_rejected(self):
        with pytest.raises(KeyError, match="unknown collective"):
            collectives.get_collective("int4", BLOCK)

    def test_unknown_collective_names_flag_and_available_regimes(self):
        """The resolution error is an operator surface: it must name the
        registered regimes AND the flag that selects one, like the
        flags.py getters do."""
        with pytest.raises(KeyError) as err:
            collectives.get_collective("int4", BLOCK)
        message = str(err.value)
        assert "T2R_COLLECTIVE_QUANT" in message
        for name in collectives.available_collectives():
            assert name in message
        assert "fp8_e4m3" in message  # the registry carries the fp8 regimes

    def test_block_divisibility_enforced(self):
        coll = collectives.get_collective("int8", BLOCK)
        with pytest.raises(ValueError, match="not divisible"):
            coll.encode(jnp.zeros((BLOCK + 1,)))


class TestCollectiveParity:
    """Quantized collectives vs the exact lax.psum family on 8 devices."""

    def test_none_reduce_scatter_matches_psum(self):
        rows = _rows(1)
        coll = collectives.get_collective("none", BLOCK)
        reduced, sent = _run_reduce_scatter(coll, rows)
        expected = rows.sum(axis=0)  # chunk d summed over devices
        np.testing.assert_allclose(reduced, expected, rtol=1e-6, atol=1e-5)
        np.testing.assert_array_equal(sent.reshape(rows.shape), rows)

    @pytest.mark.parametrize("name", QUANT_NAMES)
    def test_quantized_reduce_scatter_within_tolerance(self, name):
        tol_steps = STEP_FACTORS[name]
        rows = _rows(2)
        coll = collectives.get_collective(name, BLOCK)
        reduced, sent = _run_reduce_scatter(coll, rows)
        expected = rows.sum(axis=0)
        # Worst case: every sender contributes half a quantization step
        # of its largest block.
        atol = N * 0.5 * np.abs(rows).max() * tol_steps * 1.01 + 1e-9
        np.testing.assert_allclose(reduced, expected, atol=atol, rtol=0)
        # The error channel is exactly what failed to transmit.
        err = rows - sent.reshape(rows.shape)
        assert np.abs(err).max() <= 0.5 * np.abs(rows).max() * tol_steps * 1.01

    @pytest.mark.parametrize("name", ALL_NAMES)
    def test_all_gather_parity(self, name):
        shards = _rows(4)[:, 0, :]  # [N, L]
        coll = collectives.get_collective(name, BLOCK)
        full, sent = _run_all_gather(coll, shards)
        # Every device reconstructs the same concatenation, equal to the
        # dequantized sends in axis order.
        assert full.shape == (N, N * L)
        for d in range(1, N):
            np.testing.assert_array_equal(full[0], full[d])
        np.testing.assert_array_equal(
            full[0].reshape(N, L), sent.reshape(N, L)
        )
        tol = (
            0
            if name == "none"
            else np.abs(shards).max() * 1.01 * 0.5 * STEP_FACTORS[name]
        )
        np.testing.assert_allclose(
            full[0].reshape(N, L), shards, atol=tol + 1e-12, rtol=0
        )


class TestFlatShardLayout:
    def test_padding_math(self):
        layout = collectives.FlatShardLayout(1000, 8, 64)
        assert layout.shard_len == 128  # ceil(1000/8)=125 -> 128
        assert layout.padded == 1024
        flat = jnp.arange(1000, dtype=jnp.float32)
        padded = layout.pad(flat)
        assert padded.shape == (1024,)
        np.testing.assert_array_equal(np.asarray(padded[1000:]), 0)
        np.testing.assert_array_equal(
            np.asarray(layout.unpad(padded)), np.asarray(flat)
        )
        assert layout.rows(padded).shape == (8, 128)

    def test_bad_inputs_rejected(self):
        with pytest.raises(ValueError):
            collectives.FlatShardLayout(0, 8, 64)
        layout = collectives.FlatShardLayout(100, 4, 8)
        with pytest.raises(ValueError, match="expected"):
            layout.pad(jnp.zeros((101,)))

    def test_wire_summary_ratios(self):
        n = 1 << 20
        pre, post = collectives.wire_summary(
            collectives.get_collective("int8", 512), n
        )
        assert pre / post >= 3.5  # the acceptance bar
        pre16, post16 = collectives.wire_summary(
            collectives.get_collective("fp16", 512), n
        )
        assert 1.9 < pre16 / post16 <= 2.0
        pre0, post0 = collectives.wire_summary(
            collectives.get_collective("none", 512), n
        )
        assert pre0 == post0
        for name in ("fp8_e4m3", "fp8_e5m2"):
            pre8, post8 = collectives.wire_summary(
                collectives.get_collective(name, 512), n
            )
            assert pre8 / post8 >= 3.5  # same byte win as int8


def _setup(batch_size=16, seed=0, **kwargs):
    kwargs.setdefault("use_batch_norm", False)
    model_kwargs = {
        k: kwargs.pop(k)
        for k in ("use_batch_norm", "use_avg_model_params")
        if k in kwargs
    }
    model = MockT2RModel(device_type="cpu", **model_kwargs)
    generator = MockInputGenerator(batch_size=batch_size, seed=seed)
    generator.set_specification_from_model(model, "train")
    batch = next(iter(generator.create_dataset("train")))
    compiled = train_eval.CompiledModel(
        model, donate_state=False, shard_weight_update=True, **kwargs
    )
    state = compiled.init_state(jax.random.PRNGKey(0), batch)
    return compiled, state, batch


def _run_steps(compiled, state, batch, steps, rng_seed=7):
    rng = jax.random.PRNGKey(rng_seed)
    metrics = None
    for _ in range(steps):
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), rng
        )
    return state, metrics


def _flat_params(state):
    return jax.flatten_util.ravel_pytree(jax.device_get(state.params))[0]


class TestQuantizedZero2Step:
    """The trainer integration: explicit quantized collectives vs the
    GSPMD ZeRO-2 step."""

    @pytest.mark.parametrize(
        "quant,loss_tol,param_tol",
        [
            ("fp16", 2e-4, 2e-3),
            ("int8", 2e-3, 2e-2),
            # fp8 wire formats: same 1 byte/element as int8, relative
            # rounding; error feedback keeps the trajectory pinned to
            # the exact path (measured ~3e-4 loss / ~6e-3 param drift
            # over 10 steps — tolerances carry ~5x headroom).
            ("fp8_e4m3", 2e-3, 2e-2),
            ("fp8_e5m2", 5e-3, 5e-2),
        ],
    )
    def test_loss_parity_with_exact(self, quant, loss_tol, param_tol):
        compiled_e, state_e, batch = _setup()
        compiled_q, state_q, _ = _setup(
            collective_quant=quant, collective_block=BLOCK
        )
        assert compiled_q._quant_collective is not None
        state_e, metrics_e = _run_steps(compiled_e, state_e, batch, 10)
        state_q, metrics_q = _run_steps(compiled_q, state_q, batch, 10)
        loss_e = float(jax.device_get(metrics_e["loss"]))
        loss_q = float(jax.device_get(metrics_q["loss"]))
        assert abs(loss_e - loss_q) < loss_tol, (loss_e, loss_q)
        np.testing.assert_allclose(
            _flat_params(state_e), _flat_params(state_q), atol=param_tol
        )

    def test_none_keeps_the_gspmd_path_byte_identical(self):
        """quant='none' must not even engage the manual step — the exact
        GSPMD psum program runs, byte-for-byte."""
        compiled_n, state_n, batch = _setup(collective_quant="none")
        assert compiled_n._quant_collective is None
        assert state_n.collective_residual is None
        compiled_d, state_d, _ = _setup()  # default (flag unset)
        state_n, _ = _run_steps(compiled_n, state_n, batch, 3)
        state_d, _ = _run_steps(compiled_d, state_d, batch, 3)
        np.testing.assert_array_equal(
            _flat_params(state_n), _flat_params(state_d)
        )

    def test_env_flag_selects_collective(self):
        saved_q = flags.read_raw("T2R_COLLECTIVE_QUANT")
        saved_b = flags.read_raw("T2R_COLLECTIVE_BLOCK")
        try:
            flags.write_env("T2R_COLLECTIVE_QUANT", "int8")
            flags.write_env("T2R_COLLECTIVE_BLOCK", 128)
            compiled, state, _ = _setup()
            assert compiled._quant_collective is not None
            assert compiled._quant_collective.name == "int8"
            assert compiled._quant_collective.block == 128
            assert state.collective_residual is not None
        finally:
            flags.restore_env("T2R_COLLECTIVE_QUANT", saved_q)
            flags.restore_env("T2R_COLLECTIVE_BLOCK", saved_b)

    def test_inert_outside_zero2(self):
        """The flag must be safe to export fleet-wide: without
        shard_weight_update (or off the data axis) nothing changes."""
        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        compiled = train_eval.CompiledModel(
            model, donate_state=False, collective_quant="int8"
        )
        assert compiled._quant_collective is None
        mesh = mesh_lib.make_mesh(data=1, devices=jax.devices()[:1])
        compiled_1 = train_eval.CompiledModel(
            model, mesh=mesh, donate_state=False,
            shard_weight_update=True, collective_quant="int8",
        )
        assert compiled_1._quant_collective is None

    def test_error_feedback_determinism_across_runs(self):
        runs = []
        for _ in range(2):
            compiled, state, batch = _setup(
                collective_quant="int8", collective_block=BLOCK
            )
            state, _ = _run_steps(compiled, state, batch, 5)
            runs.append(state)
        np.testing.assert_array_equal(
            _flat_params(runs[0]), _flat_params(runs[1])
        )
        res0 = jax.device_get(runs[0].collective_residual)
        res1 = jax.device_get(runs[1].collective_residual)
        np.testing.assert_array_equal(res0["grad"], res1["grad"])
        np.testing.assert_array_equal(res0["update"], res1["update"])
        # The residual is live (int8 on real gradients cannot be exact).
        assert np.abs(res0["grad"]).max() > 0

    @pytest.mark.parametrize("quant", ["int8", "fp8_e4m3"])
    def test_checkpoint_roundtrip_of_residual(self, tmp_path, quant):
        """Save mid-run, restore into a FRESH trainer, continue: the
        trajectory must match the uninterrupted run exactly — which can
        only hold if the residual state round-trips the checkpoint."""
        kwargs = dict(collective_quant=quant, collective_block=BLOCK)
        compiled, state, batch = _setup(**kwargs)
        state, _ = _run_steps(compiled, state, batch, 3)
        manager = train_eval.create_checkpoint_manager(
            str(tmp_path), save_interval_steps=1
        )
        manager.save(
            3,
            args=train_eval.ocp.args.StandardSave(
                compiled.persistable_state(state)
            ),
            force=True,
        )
        manager.wait_until_finished()

        compiled_r, _, _ = _setup(**kwargs)
        restored = train_eval.restore_or_init_state(
            manager, compiled_r, jax.random.PRNGKey(0), batch
        )
        manager.close()
        assert int(jax.device_get(restored.step)) == 3
        res_saved = jax.device_get(state.collective_residual)
        res_restored = jax.device_get(restored.collective_residual)
        np.testing.assert_array_equal(
            res_saved["grad"], res_restored["grad"]
        )
        # Continue both for 3 more steps: bitwise-identical trajectory.
        state, _ = _run_steps(compiled, state, batch, 3, rng_seed=11)
        restored, _ = _run_steps(compiled_r, restored, batch, 3, rng_seed=11)
        np.testing.assert_array_equal(
            _flat_params(state), _flat_params(restored)
        )

    def test_grad_accum_composes(self):
        compiled, state, batch = _setup(
            collective_quant="int8", collective_block=BLOCK,
            grad_accum_steps=2,
        )
        state, metrics = _run_steps(compiled, state, batch, 2)
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    def test_ema_mirror_and_export(self):
        compiled, state, batch = _setup(
            collective_quant="int8", collective_block=BLOCK,
            use_avg_model_params=True,
        )
        assert state.ema_params is not None
        assert state.ema_params.ndim == 1  # flat padded layout
        state, _ = _run_steps(compiled, state, batch, 3)
        ema_tree = ema_as_tree(
            jax.device_get(state.ema_params), jax.device_get(state.params)
        )
        jax.tree_util.tree_map(
            lambda e, p: np.testing.assert_array_equal(
                np.asarray(e).shape, np.asarray(p).shape
            ),
            ema_tree,
            jax.device_get(state.params),
        )
        # EMA tracked the params (moved off init).
        variables = state.export_variables(use_ema=True)
        moved = jax.flatten_util.ravel_pytree(
            jax.device_get(variables["params"])
        )[0]
        assert np.abs(moved - _flat_params(state)).max() > 0

    def test_batch_norm_stats_averaged(self):
        compiled, state, batch = _setup(
            use_batch_norm=True,
            collective_quant="fp16", collective_block=BLOCK,
        )
        init_stats = jax.device_get(state.variables["batch_stats"])
        state, _ = _run_steps(compiled, state, batch, 2)
        stats = jax.device_get(state.variables["batch_stats"])
        moved = max(
            float(np.abs(np.asarray(a) - np.asarray(b)).max())
            for a, b in zip(
                jax.tree_util.tree_leaves(init_stats),
                jax.tree_util.tree_leaves(stats),
            )
        )
        assert moved > 0

    def test_eval_step_works_on_quant_state(self):
        compiled, state, batch = _setup(
            collective_quant="int8", collective_block=BLOCK
        )
        state, _ = _run_steps(compiled, state, batch, 2)
        metrics = compiled.eval_step(
            state, compiled.shard_batch(batch), False
        )
        assert np.isfinite(float(jax.device_get(metrics["accuracy"])))

    def test_fuse_stats_rejected_with_quant(self):
        model = MockT2RModel(device_type="cpu")
        with pytest.raises(ValueError, match="fuse_batch_stats_update"):
            train_eval.CompiledModel(
                model, shard_weight_update=True,
                collective_quant="int8", fuse_batch_stats_update=True,
            )

    def test_collective_log_record(self):
        compiled, _, _ = _setup(
            collective_quant="int8", collective_block=512
        )
        record = compiled.collective_log_record(measure=False)
        assert record["collective/compression"] >= 3.5
        assert record["collective/bytes_post"] < record["collective/bytes_pre"]
        wall = compiled.measure_collective_ms(repeats=2)
        assert wall > 0
        compiled_e, _, _ = _setup()
        assert compiled_e.collective_log_record() == {}


class TestTrainEvalModelIntegration:
    def test_end_to_end_with_flag(self, tmp_path):
        saved = flags.read_raw("T2R_COLLECTIVE_QUANT")
        try:
            flags.write_env("T2R_COLLECTIVE_QUANT", "int8")
            final = train_eval.train_eval_model(
                t2r_model=MockT2RModel(
                    device_type="cpu", use_batch_norm=False
                ),
                input_generator_train=MockInputGenerator(batch_size=16),
                input_generator_eval=MockInputGenerator(
                    batch_size=16, seed=5
                ),
                model_dir=str(tmp_path / "run"),
                max_train_steps=60,
                eval_steps=4,
                save_checkpoints_steps=30,
                log_every_steps=20,
                shard_weight_update=True,
            )
            assert final["accuracy"] > 0.7
            from tensor2robot_tpu.train.metrics import read_metrics

            stream = read_metrics(str(tmp_path / "run" / "train"))
            assert stream, "no train metrics written"
            last = stream[-1]
            assert last["collective/compression"] > 3.5
            assert last["collective/wall_ms"] > 0
        finally:
            flags.restore_env("T2R_COLLECTIVE_QUANT", saved)
