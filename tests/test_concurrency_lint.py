"""Tests for the lock-discipline static pass (analysis/concurrency.py).

Every rule gets a seeded-violation fixture (the pass must FIND it) and
a negative twin (the pass must stay quiet); alias-resolution cases pin
the lock-identity model; the shipped tree must be clean with zero
unreviewed escape hatches.
"""

import textwrap

from tensor2robot_tpu.analysis import concurrency


def _check(src):
    return concurrency.check_source(textwrap.dedent(src), "fixture.py")


def _rules(diags):
    return [d.rule for d in diags]


# -- guard-contract inference (conc-unguarded-field) --------------------------


class TestUnguardedField:
    def test_majority_guarded_field_flagged_at_bare_access(self):
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def drain(self):
                    with self._lock:
                        out = list(self._items)
                        self._items.clear()
                        return out

                def peek(self):
                    return self._items[-1]
            """
        )
        assert _rules(diags) == [concurrency.RULE_UNGUARDED]
        assert "_items" in diags[0].message
        assert "peek" in diags[0].message

    def test_immutable_config_field_not_flagged(self):
        # Never mutated after __init__: reads race nothing.
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._limit = 8
                    self._pending = []

                def add(self, x):
                    with self._lock:
                        if len(self._pending) < self._limit:
                            self._pending.append(x)

                def drain(self):
                    with self._lock:
                        self._pending.clear()

                def limit(self):
                    return self._limit
            """
        )
        assert diags == []

    def test_minority_guarded_field_not_flagged(self):
        # Guarded once, bare once: no majority contract to enforce.
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def mutate(self):
                    self._items.append(None)
            """
        )
        assert diags == []

    def test_construction_writes_exempt(self):
        # __init__ / start() run before threads exist; bare writes
        # there must not break the contract.
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def bump2(self):
                    with self._lock:
                        self._n += 1
            """
        )
        assert diags == []

    def test_helper_called_only_under_lock_counts_as_guarded(self):
        # Lock-context inference: _flush is reachable only with the
        # lock held, so its bare accesses honor the contract.
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)
                        if len(self._items) > 8:
                            self._flush()

                def drain(self):
                    with self._lock:
                        self._flush()

                def _flush(self):
                    self._items.clear()
            """
        )
        assert diags == []

    def test_helper_also_called_bare_is_not_lock_context(self):
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)
                        self._flush()

                def fast(self):
                    with self._lock:
                        self._items.append(None)

                def racy(self):
                    self._flush()

                def _flush(self):
                    self._items.clear()
            """
        )
        assert _rules(diags) == [concurrency.RULE_UNGUARDED]
        assert "_flush" in diags[0].message


# -- escape hatch + staleness -------------------------------------------------


class TestAnnotations:
    SRC = """
        import threading

        class Hub:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def drain(self):
                with self._lock:
                    self._items.clear()

            def peek(self):
                return self._items[-1]{annot}
    """

    def test_unguarded_ok_suppresses(self):
        diags = _check(
            self.SRC.format(
                annot="  # t2r: unguarded-ok(read is a racy stat)"
            )
        )
        assert diags == []

    def test_empty_reason_is_an_error(self):
        diags = _check(self.SRC.format(annot="  # t2r: unguarded-ok()"))
        assert concurrency.RULE_STALE in _rules(diags)

    def test_unused_annotation_is_stale(self):
        diags = _check(
            """
            import threading

            class Hub:
                def quiet(self):
                    return 1  # t2r: unguarded-ok(nothing to suppress)
            """
        )
        assert _rules(diags) == [concurrency.RULE_STALE]

    def test_comment_line_above_applies_to_next_line(self):
        diags = _check(
            self.SRC.format(annot="").replace(
                "        return self._items[-1]",
                "        # t2r: unguarded-ok(racy stat)\n"
                "                return self._items[-1]",
            )
        )
        assert diags == []


# -- lock-order cycles (conc-lock-order-cycle) --------------------------------


class TestLockOrderCycles:
    def test_two_lock_inversion_reports_both_paths(self):
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """
        )
        assert concurrency.RULE_CYCLE in _rules(diags)
        cycle = next(d for d in diags if d.rule == concurrency.RULE_CYCLE)
        assert "Hub._a" in cycle.message and "Hub._b" in cycle.message
        # Both acquisition sites, in path:line diagnostic format.
        assert cycle.message.count("fixture.py:") >= 2

    def test_consistent_order_is_clean(self):
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
            """
        )
        assert diags == []

    def test_plain_lock_reentry_is_self_deadlock(self):
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        with self._lock:
                            pass
            """
        )
        assert concurrency.RULE_CYCLE in _rules(diags)

    def test_rlock_reentry_is_fine(self):
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        with self._lock:
                            pass
            """
        )
        assert diags == []

    def test_call_mediated_cycle_found(self):
        # outer holds A and CALLS a method that takes B; the reverse
        # path holds B and calls a method that takes A.
        diags = _check(
            """
            import threading

            class Hub:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def fwd(self):
                    with self._a:
                        self._take_b()

                def _take_b(self):
                    with self._b:
                        pass

                def rev(self):
                    with self._b:
                        self._take_a()

                def _take_a(self):
                    with self._a:
                        pass
            """
        )
        assert concurrency.RULE_CYCLE in _rules(diags)


# -- alias resolution / lock identity -----------------------------------------


class TestLockIdentity:
    def test_attr_hop_resolves_to_owning_class(self):
        # self._pool is a _Pool; `with self._pool.cond` must resolve to
        # the SAME LockId as _Pool methods' `with self.cond`.
        diags = _check(
            """
            import threading

            class _Pool:
                def __init__(self):
                    self.cond = threading.Condition()
                    self.items = []

                def put(self, x):
                    with self.cond:
                        self.items.append(x)
                        self.cond.notify()

                def size(self):
                    with self.cond:
                        self.items.clear()
                        return 0

            class Gateway:
                def __init__(self):
                    self._pool = _Pool()

                def flush(self):
                    with self._pool.cond:
                        self._pool.items.clear()
            """
        )
        assert diags == []

    def test_module_level_lock_via_alias_import(self):
        diags = concurrency.check_sources(
            [
                (
                    "pkg/state.py",
                    textwrap.dedent(
                        """
                        import threading

                        GUARD = threading.Lock()
                        """
                    ),
                ),
                (
                    "pkg/worker.py",
                    textwrap.dedent(
                        """
                        import time

                        from pkg import state

                        def spin():
                            with state.GUARD:
                                time.sleep(1.0)
                        """
                    ),
                ),
            ]
        )
        blocking = [
            d for d in diags if d.rule == concurrency.RULE_BLOCKING
        ]
        assert len(blocking) == 1
        assert "state.GUARD" in blocking[0].message

    def test_cross_module_inversion_found(self):
        diags = concurrency.check_sources(
            [
                (
                    "pkg/a.py",
                    textwrap.dedent(
                        """
                        import threading

                        LOCK_A = threading.Lock()
                        LOCK_B = threading.Lock()

                        def fwd():
                            with LOCK_A:
                                with LOCK_B:
                                    pass
                        """
                    ),
                ),
                (
                    "pkg/b.py",
                    textwrap.dedent(
                        """
                        from pkg import a

                        def rev():
                            with a.LOCK_B:
                                with a.LOCK_A:
                                    pass
                        """
                    ),
                ),
            ]
        )
        cycles = [d for d in diags if d.rule == concurrency.RULE_CYCLE]
        assert cycles, _rules(diags)
        assert "pkg/a.py:" in cycles[0].message
        assert "pkg/b.py:" in cycles[0].message

    def test_locksmith_factory_spelling_is_a_lock(self):
        diags = _check(
            """
            from tensor2robot_tpu.testing import locksmith

            class Hub:
                def __init__(self):
                    self._lock = locksmith.make_lock("Hub._lock")
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def drain(self):
                    with self._lock:
                        self._items.clear()

                def peek(self):
                    return self._items[-1]
            """
        )
        assert _rules(diags) == [concurrency.RULE_UNGUARDED]


# -- blocking calls under a lock (conc-blocking-under-lock) -------------------


class TestBlockingUnderLock:
    def _held(self, body, extra=""):
        return _check(
            f"""
            import queue
            import time
            import threading

            class Hub:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()
                    self._worker = None
                {extra}
                def run(self):
                    with self._lock:
                        {body}
            """
        )

    def test_untimed_queue_get(self):
        diags = self._held("return self._q.get()")
        assert _rules(diags) == [concurrency.RULE_BLOCKING]

    def test_queue_get_with_timeout_ok(self):
        assert self._held("return self._q.get(timeout=0.5)") == []

    def test_time_sleep(self):
        diags = self._held("time.sleep(1.0)")
        assert _rules(diags) == [concurrency.RULE_BLOCKING]

    def test_bare_join(self):
        diags = self._held("self._worker.join()")
        assert _rules(diags) == [concurrency.RULE_BLOCKING]

    def test_join_with_timeout_ok(self):
        assert self._held("self._worker.join(timeout=1.0)") == []

    def test_predict_under_lock(self):
        diags = self._held("return self.predictor.predict({})")
        assert _rules(diags) == [concurrency.RULE_BLOCKING]

    def test_blocking_ok_annotation_suppresses(self):
        diags = self._held(
            "time.sleep(0.1)  # t2r: blocking-ok(test pacing only)"
        )
        assert diags == []

    def test_no_lock_no_finding(self):
        diags = _check(
            """
            import time

            def pace():
                time.sleep(1.0)
            """
        )
        assert diags == []


# -- the shipped tree ---------------------------------------------------------


class TestShippedTree:
    def test_threaded_fabric_is_clean(self):
        diags = concurrency.check_paths()
        assert diags == [], "\n".join(d.format() for d in diags)

    def test_syntax_error_is_a_parse_finding(self):
        diags = _check("def broken(:\n")
        assert _rules(diags) == [concurrency.RULE_PARSE]
