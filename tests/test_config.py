"""Config system tests: bindings, scopes, macros, references, includes,
operative config."""

import pytest

from tensor2robot_tpu import config as cfg


@pytest.fixture(autouse=True)
def clean_registry():
    cfg.clear_config()
    yield
    cfg.clear_config()


@cfg.configurable
def make_widget(size=1, color="red", factory=None):
    if factory is not None:
        return factory, size
    return (size, color)


@cfg.configurable("named_thing")
def thing_fn(value=0):
    return value


@cfg.configurable
class Gadget:
    def __init__(self, power=5, name="g"):
        self.power = power
        self.name = name


class TestBindings:
    def test_simple_binding(self):
        cfg.parse_config("make_widget.size = 42")
        assert make_widget() == (42, "red")

    def test_explicit_kwargs_win(self):
        cfg.parse_config("make_widget.size = 42")
        assert make_widget(size=7) == (7, "red")

    def test_named_configurable(self):
        cfg.parse_config("named_thing.value = 3")
        assert thing_fn() == 3

    def test_class_binding_and_isinstance(self):
        cfg.parse_config("Gadget.power = 99")
        g = Gadget()
        assert g.power == 99 and g.name == "g"
        assert isinstance(g, Gadget)

    def test_unknown_param_rejected(self):
        cfg.parse_config("make_widget.nope = 1")
        with pytest.raises(cfg.ConfigError, match="nope"):
            make_widget()

    def test_bind_parameter_runtime(self):
        cfg.bind_parameter("make_widget.color", "blue")
        assert make_widget() == (1, "blue")

    def test_query_parameter(self):
        cfg.bind_parameter("make_widget.size", 5)
        assert cfg.query_parameter("make_widget.size") == 5


class TestValues:
    def test_literals(self):
        cfg.parse_config("""
make_widget.size = -3
make_widget.color = 'green'
""")
        assert make_widget() == (-3, "green")

    def test_containers_multiline(self):
        cfg.parse_config("""
make_widget.size = [1,
                    2,
                    3]
""")
        assert make_widget()[0] == [1, 2, 3]

    def test_macro(self):
        cfg.parse_config("""
SIZE = 11
make_widget.size = %SIZE
""")
        assert make_widget() == (11, "red")

    def test_reference_uncalled(self):
        cfg.parse_config("make_widget.factory = @named_thing")
        factory, _ = make_widget()
        assert factory() == 0

    def test_reference_called(self):
        cfg.parse_config("""
named_thing.value = 9
make_widget.factory = @named_thing()
""")
        factory_result, _ = make_widget()
        assert factory_result == 9


class TestScopes:
    def test_scoped_binding(self):
        cfg.parse_config("""
make_widget.size = 1
train/make_widget.size = 100
""")
        assert make_widget() == (1, "red")
        with cfg.config_scope("train"):
            assert make_widget() == (100, "red")
        assert make_widget() == (1, "red")

    def test_scoped_reference(self):
        cfg.parse_config("""
named_thing.value = 1
s1/named_thing.value = 2
make_widget.factory = @s1/named_thing()
""")
        result, _ = make_widget()
        assert result == 2


class TestFiles:
    def test_include(self, tmp_path):
        base = tmp_path / "base.gin"
        base.write_text("make_widget.size = 5\n")
        main = tmp_path / "main.gin"
        main.write_text(f"include 'base.gin'\nmake_widget.color = 'black'\n")
        cfg.parse_config_file(str(main))
        assert make_widget() == (5, "black")

    def test_parse_config_files_and_bindings(self, tmp_path):
        f = tmp_path / "a.gin"
        f.write_text("make_widget.size = 2\n")
        cfg.parse_config_files_and_bindings(
            [str(f)], ["make_widget.color = 'x'"]
        )
        assert make_widget() == (2, "x")

    def test_comments_ignored(self):
        cfg.parse_config("""
# full line comment
make_widget.size = 4  # trailing comment
""")
        assert make_widget() == (4, "red")


class TestOperativeConfig:
    def test_records_actual_values(self, tmp_path):
        cfg.parse_config("make_widget.size = 8")
        make_widget(color="used")
        text = cfg.operative_config_str()
        assert "make_widget.size = 8" in text
        assert "make_widget.color = 'used'" in text
        path = cfg.save_operative_config(str(tmp_path))
        assert "make_widget.size = 8" in open(path).read()

    def test_external_configurable(self):
        def third_party(a=1):
            return a

        wrapped = cfg.external_configurable(third_party, "tp")
        cfg.parse_config("tp.a = 77")
        assert wrapped() == 77
