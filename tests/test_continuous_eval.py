"""Continuous-eval job + multi-eval wiring.

Rebuild of the reference's continuous-eval topology tests: the trainer and
the eval job are separate processes communicating only through model_dir
(utils/train_eval.py:584-683). Here the trainer runs in a thread while
continuous_eval tails its checkpoints, asserting per-name eval artifacts.
"""

import os
import threading

import numpy as np
import pytest

from tensor2robot_tpu.export.exporters import LatestExporter
from tensor2robot_tpu.train import continuous_eval as ce
from tensor2robot_tpu.train import train_eval
from tensor2robot_tpu.train.metrics import read_metrics
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BATCH_SIZE = 16


class TestMultiEvalInLoop:
    def test_named_eval_streams_and_merged_metrics(self, tmp_path):
        model_dir = str(tmp_path / "run")
        final = train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            input_generator_eval={
                "seen": MockInputGenerator(batch_size=BATCH_SIZE, seed=3),
                "unseen": MockInputGenerator(batch_size=BATCH_SIZE, seed=9),
            },
            model_dir=model_dir,
            max_train_steps=40,
            save_checkpoints_steps=20,
            eval_steps=4,
            log_every_steps=20,
        )
        # Per-name metric streams on disk.
        seen = read_metrics(os.path.join(model_dir, "eval_seen"))
        unseen = read_metrics(os.path.join(model_dir, "eval_unseen"))
        assert [row["step"] for row in seen] == [20, 40]
        assert [row["step"] for row in unseen] == [20, 40]
        # Merged metrics: primary (first) eval unprefixed + per-name copies.
        assert "loss" in final
        assert "seen/loss" in final and "unseen/loss" in final
        assert final["loss"] == final["seen/loss"]


class TestCheckpointBackup:
    def _train(self, model_dir, steps=20):
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=model_dir,
            max_train_steps=steps,
            save_checkpoints_steps=steps,
            log_every_steps=steps,
        )

    def test_backup_survives_source_gc(self, tmp_path):
        model_dir = str(tmp_path / "run")
        self._train(model_dir)
        backup_root = ce.backup_checkpoint_for_eval(model_dir, 20)
        assert backup_root is not None
        # Trainer GC deletes the source; the backup must still restore.
        import shutil

        shutil.rmtree(os.path.join(model_dir, "checkpoints", "20"))
        model = train_eval.maybe_wrap_for_tpu(MockT2RModel(device_type="cpu"))
        compiled = train_eval.CompiledModel(model, donate_state=False)
        generator = MockInputGenerator(batch_size=BATCH_SIZE)
        train_eval.provide_input_generator_with_model_information(
            generator, model, "eval"
        )
        example = next(iter(generator.create_dataset("eval")))
        state = ce.restore_state_from_backup(backup_root, 20, compiled, example)
        assert int(np.asarray(state.step)) == 20

    def test_backup_missing_step_returns_none(self, tmp_path):
        model_dir = str(tmp_path / "run")
        os.makedirs(os.path.join(model_dir, "checkpoints"))
        assert ce.backup_checkpoint_for_eval(model_dir, 999) is None

    def test_wait_timeout_returns_none(self, tmp_path):
        assert (
            ce.wait_for_new_checkpoint(
                str(tmp_path), timeout=0.2, poll_interval=0.05
            )
            is None
        )


class TestContinuousEvalTailsTraining:
    def test_eval_job_follows_trainer(self, tmp_path):
        model_dir = str(tmp_path / "run")
        max_steps = 60

        def train():
            train_eval.train_eval_model(
                t2r_model=MockT2RModel(device_type="cpu"),
                input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
                model_dir=model_dir,
                max_train_steps=max_steps,
                save_checkpoints_steps=20,
                log_every_steps=20,
                keep_checkpoint_max=2,
            )

        trainer = threading.Thread(target=train, daemon=True)
        trainer.start()
        final = ce.continuous_eval(
            t2r_model=MockT2RModel(device_type="cpu"),
            model_dir=model_dir,
            input_generator_eval={
                "a": MockInputGenerator(batch_size=BATCH_SIZE, seed=3),
                "b": MockInputGenerator(batch_size=BATCH_SIZE, seed=9),
            },
            eval_steps=2,
            max_train_steps=max_steps,
            create_exporters_fn=lambda model: [LatestExporter(name="latest")],
            timeout=120.0,
            poll_interval=0.2,
        )
        trainer.join(timeout=300)
        assert not trainer.is_alive()
        # The eval job reached the final checkpoint and wrote per-name streams.
        assert final and "a/loss" in final and "b/loss" in final
        for name in ("a", "b"):
            rows = read_metrics(os.path.join(model_dir, f"eval_{name}"))
            assert rows, f"no metrics for eval_{name}"
            assert rows[-1]["step"] == max_steps
        # Exporter driven by the eval job.
        export_root = os.path.join(model_dir, "export", "latest")
        assert os.path.isdir(export_root) and os.listdir(export_root)
