"""Legacy pkl-asset migration (bin/convert_pkl_assets).

Fabricates a byte-faithful legacy pickle — throwaway classes registered
under the ORIGINAL module paths (`tensor2robot.utils.tensorspec_utils`,
TF framework internals) whose __reduce__ mirrors the reference exactly
(tensorspec_utils.py:275-279) — then runs the converter and checks the
resulting t2r_assets.pbtxt round-trips into this framework's specs."""

import collections
import os
import pickle
import sys
import types

import numpy as np
import pytest

from google.protobuf import text_format

from tensor2robot_tpu.bin import convert_pkl_assets
from tensor2robot_tpu.proto import t2r_pb2
from tensor2robot_tpu.specs.proto_io import struct_from_proto


def _install_legacy_modules(monkeypatch):
    """Registers stand-in legacy modules so pickling records the original
    global names (what a real TF1-era pkl contains)."""

    tshape = types.ModuleType("tensorflow.python.framework.tensor_shape")

    class Dimension:
        def __init__(self, value):
            self.value = value

        def __reduce__(self):
            return Dimension, (self.value,)

    class TensorShape:
        def __init__(self, dims):
            self.dims = [
                d if isinstance(d, Dimension) or d is None else Dimension(d)
                for d in dims
            ]

        def __reduce__(self):
            return TensorShape, (self.dims,)

    tshape.TensorShape = TensorShape
    tshape.Dimension = Dimension
    Dimension.__module__ = tshape.__name__
    Dimension.__qualname__ = "Dimension"
    TensorShape.__module__ = tshape.__name__
    TensorShape.__qualname__ = "TensorShape"

    tdtypes = types.ModuleType("tensorflow.python.framework.dtypes")

    def as_dtype(name):
        return _DType(name)

    class _DType:
        def __init__(self, name):
            self.name = name

        def __reduce__(self):
            return as_dtype, (self.name,)

    tdtypes.as_dtype = as_dtype
    tdtypes.DType = _DType
    as_dtype.__module__ = tdtypes.__name__
    as_dtype.__qualname__ = "as_dtype"
    _DType.__module__ = tdtypes.__name__
    _DType.__qualname__ = "DType"
    tdtypes.DType = _DType

    t2r = types.ModuleType("tensor2robot.utils.tensorspec_utils")

    class ExtendedTensorSpec:
        def __init__(self, shape, dtype, name, is_optional, is_sequence,
                     is_extracted, data_format, dataset_key,
                     varlen_default_value):
            self.args = (shape, dtype, name, is_optional, is_sequence,
                         is_extracted, data_format, dataset_key,
                         varlen_default_value)

        def __reduce__(self):
            return ExtendedTensorSpec, self.args

    class TensorSpecStruct(collections.OrderedDict):
        pass

    t2r.ExtendedTensorSpec = ExtendedTensorSpec
    t2r.TensorSpecStruct = TensorSpecStruct
    ExtendedTensorSpec.__module__ = t2r.__name__
    ExtendedTensorSpec.__qualname__ = "ExtendedTensorSpec"
    TensorSpecStruct.__module__ = t2r.__name__
    TensorSpecStruct.__qualname__ = "TensorSpecStruct"

    for mod in (tshape, tdtypes, t2r):
        monkeypatch.setitem(sys.modules, mod.__name__, mod)
        # pickle verifies globals by __import__ of the dotted path, which
        # walks the PARENT packages — register stubs for those too.
        parts = mod.__name__.split(".")
        for i in range(1, len(parts)):
            parent = ".".join(parts[:i])
            if parent not in sys.modules:
                monkeypatch.setitem(
                    sys.modules, parent, types.ModuleType(parent)
                )
    return t2r, tshape, tdtypes


def test_convert_legacy_assets(tmp_path, monkeypatch):
    t2r, tshape, tdtypes = _install_legacy_modules(monkeypatch)

    def spec(shape, dtype, name, **kw):
        return t2r.ExtendedTensorSpec(
            tshape.TensorShape(shape), tdtypes.as_dtype(dtype), name,
            kw.get("is_optional"), kw.get("is_sequence", False), False,
            kw.get("data_format"), kw.get("dataset_key"), None,
        )

    features = t2r.TensorSpecStruct()
    features["state/image"] = spec(
        (512, 640, 3), "uint8", "image/encoded", data_format="jpeg"
    )
    features["state/pose"] = spec((7,), "float32", "pose", is_optional=True)
    labels = t2r.TensorSpecStruct()
    labels["reward"] = spec((1,), "float32", "grasp_success")

    with open(tmp_path / "input_specs.pkl", "wb") as f:
        pickle.dump(
            {"in_feature_spec": features, "in_label_spec": labels}, f
        )
    with open(tmp_path / "global_step.pkl", "wb") as f:
        pickle.dump({"global_step": 1234}, f)

    out = convert_pkl_assets.convert(str(tmp_path))
    assert os.path.basename(out) == "t2r_assets.pbtxt"

    with open(out) as f:
        assets = text_format.Parse(f.read(), t2r_pb2.T2RAssets())
    assert assets.global_step == 1234
    feature_struct = struct_from_proto(assets.feature_spec)
    image = feature_struct["state/image"]
    assert image.shape == (512, 640, 3)
    assert image.dtype == np.dtype("uint8")
    assert image.name == "image/encoded"
    assert image.data_format == "jpeg"
    pose = feature_struct["state/pose"]
    assert pose.is_optional
    label_struct = struct_from_proto(assets.label_spec)
    assert label_struct["reward"].shape == (1,)


def test_unknown_global_is_refused(tmp_path, monkeypatch):
    """The unpickler must reject globals outside the spec surface —
    a pickle naming os.system must not resolve, let alone run."""

    class Evil:
        def __reduce__(self):
            return os.system, ("true",)

    with open(tmp_path / "input_specs.pkl", "wb") as f:
        pickle.dump({"in_feature_spec": Evil(), "in_label_spec": {}}, f)
    with pytest.raises(pickle.UnpicklingError, match="Refusing"):
        convert_pkl_assets.convert(str(tmp_path))


def test_missing_pkl_raises(tmp_path):
    with pytest.raises(ValueError, match="No file exists"):
        convert_pkl_assets.convert(str(tmp_path))
