"""Crash-consistent checkpoint recovery under seeded T2R_CHAOS kills.

The contract under test (train/durability.py + train_eval wiring):

  1. A SIGKILL mid-orbax-save (injected at the `save` chaos site, no
     cleanup handlers) never corrupts the trainer's recovery: the next
     run quarantines any torn directory, resumes from the last DURABLE
     checkpoint, and — because the host batch stream is realigned to
     the restored step — replays to a trajectory BITWISE identical to a
     run that never crashed, error-feedback residual included (the
     suite trains in the quantized-collective ZeRO-2 regime so
     `TrainState.collective_residual` is live and checkpointed).
  2. A torn/partial *final-named* checkpoint directory (partial copy,
     fsync-less crash — forms orbax's atomic rename cannot rule out) is
     detected by the durability manifest, skipped by every reader, and
     quarantined by the owning trainer. It is never loaded.

Everything is seeded: the fault plan (`T2R_CHAOS=save:2:sigkill`), the
model/data seeds, and the tampering (explicit file surgery). No
wall-clock-dependent assertions.
"""

import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys

import numpy as np
import pytest

from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.train import durability

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One trainer program for every leg: quantized-collective ZeRO-2 regime
# on the forced 8-device host mesh (so the error-feedback residual is
# real, sharded state), save every 5 steps, then restore the final
# durable checkpoint and print a digest over the FULL persistable
# TrainState — params, opt state, EMA, residual, step. Bitwise equality
# of that digest is the "same trajectory" oracle.
_TRAINER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
model_dir, max_steps = sys.argv[1], int(sys.argv[2])
import hashlib
import numpy as np
from tensor2robot_tpu.train import durability
from tensor2robot_tpu.train import train_eval as te
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

print("DURABLE_BEFORE", durability.durable_steps(model_dir), flush=True)

te.train_eval_model(
    MockT2RModel(device_type="cpu", use_batch_norm=False),
    input_generator_train=MockInputGenerator(batch_size=8, seed=7),
    model_dir=model_dir,
    max_train_steps=max_steps,
    eval_steps=None,
    save_checkpoints_steps=5,
    log_every_steps=5,
    seed=31,
    shard_weight_update=True,
)
print("TRAINING_DONE", flush=True)

model = MockT2RModel(device_type="cpu", use_batch_norm=False)
gen = MockInputGenerator(batch_size=8, seed=7)
gen.set_specification_from_model(model, "train")
compiled = te.CompiledModel(
    model, donate_state=False, shard_weight_update=True
)
manager = te.create_checkpoint_manager(model_dir, save_interval_steps=5)
state = te.restore_or_init_state(
    manager, compiled, jax.random.PRNGKey(0),
    next(iter(gen.create_dataset("train"))),
)
digest = hashlib.sha256()
for leaf in jax.tree_util.tree_leaves(
    jax.device_get(compiled.persistable_state(state))
):
    digest.update(np.ascontiguousarray(leaf).tobytes())
print(
    "STATE_SHA256", digest.hexdigest(), "STEP", int(state.step), flush=True
)
manager.close()
"""


def _run_trainer(model_dir, max_steps, chaos_plan=None, check=True):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["T2R_COLLECTIVE_QUANT"] = "int8"
    env.pop("T2R_CHAOS", None)
    if chaos_plan is not None:
        env["T2R_CHAOS"] = chaos_plan
    proc = subprocess.run(
        [sys.executable, "-c", _TRAINER, str(model_dir), str(max_steps)],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=REPO_ROOT,
    )
    if check:
        assert proc.returncode == 0, proc.stdout[-2500:] + proc.stderr[-2500:]
    return proc


def _digest_line(proc):
    lines = [
        l for l in proc.stdout.splitlines() if l.startswith("STATE_SHA256")
    ]
    assert lines, proc.stdout[-2500:] + proc.stderr[-2500:]
    return lines[-1]


def _checkpoint_steps(model_dir):
    root = os.path.join(str(model_dir), "checkpoints")
    if not os.path.isdir(root):
        return []
    return sorted(int(n) for n in os.listdir(root) if n.isdigit())


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory):
    """One uninterrupted 15-step run: the trajectory oracle every chaos
    leg must reproduce bitwise."""
    model_dir = tmp_path_factory.mktemp("crash") / "reference"
    proc = _run_trainer(model_dir, 15)
    return {"model_dir": str(model_dir), "digest": _digest_line(proc)}


class TestKillMidSave:
    def test_sigkill_mid_save_then_resume_bitwise(
        self, tmp_path, reference_run
    ):
        model_dir = str(tmp_path / "victim")

        # Leg 1: the seeded fault plan SIGKILLs the trainer at its 2nd
        # save (step 10), with the async orbax write for step 10 in
        # flight — the mid-save crash, no cleanup handlers.
        crashed = _run_trainer(
            model_dir, 15, chaos_plan="save:2:sigkill", check=False
        )
        assert crashed.returncode == -signal.SIGKILL, (
            crashed.returncode,
            crashed.stdout[-2000:],
        )
        assert "TRAINING_DONE" not in crashed.stdout

        # The durable set can only be {5} (write didn't finish: torn
        # tmp or absent) or {5, 10} (rename won the race) — never empty,
        # never a torn dir presenting as durable.
        survivors = durability.durable_steps(model_dir)
        assert survivors in ([5], [5, 10]), survivors

        # Leg 2: restart. Must quarantine/skip any wreckage, resume
        # from the last durable step, and land on the SAME final state
        # as the run that never crashed — bitwise, residual included.
        resumed = _run_trainer(model_dir, 15)
        assert "TRAINING_DONE" in resumed.stdout
        before = [
            l for l in resumed.stdout.splitlines()
            if l.startswith("DURABLE_BEFORE")
        ][0]
        assert before.endswith(str(survivors)), (before, survivors)
        assert _digest_line(resumed) == reference_run["digest"]
        # Every checkpoint on disk after recovery is durable.
        assert durability.durable_steps(model_dir) == _checkpoint_steps(
            model_dir
        )

    @pytest.mark.slow
    def test_torn_final_named_dir_quarantined_never_loaded(
        self, tmp_path, reference_run
    ):
        """A checkpoint directory that LOOKS committed (bare step name)
        but is internally torn — the failure orbax's atomic rename
        cannot express — must be detected via the durability manifest,
        quarantined by the resuming trainer, and never restored.

        Slow slice: this is the end-to-end (subprocess, bitwise-replay)
        twin of coverage the tier-1 slice already has in-process —
        TestDurabilityModule's surgery/quarantine tests and
        TestRestoreChaosSites.test_restore_skips_torn_latest."""
        model_dir = str(tmp_path / "torn")
        shutil.copytree(reference_run["model_dir"], model_dir)
        step_dir = os.path.join(model_dir, "checkpoints", "15")
        manifest = json.load(
            open(os.path.join(step_dir, durability.MANIFEST_NAME))
        )
        # Seeded surgery: truncate the largest manifest-listed file.
        victim = max(manifest["files"], key=lambda e: e["size"])
        victim_path = os.path.join(step_dir, victim["path"])
        with open(victim_path, "r+b") as f:
            f.truncate(max(victim["size"] // 2, 1))
        assert durability.validate_step_dir(step_dir) is not None
        assert durability.durable_steps(model_dir) == [5, 10]

        resumed = _run_trainer(model_dir, 15)
        assert "Quarantined torn checkpoint '15'" in resumed.stdout
        # Resumed from 10 (the last durable), replayed 10->15, and the
        # replayed trajectory is bitwise the reference one.
        assert "DURABLE_BEFORE [5, 10]" in resumed.stdout
        assert _digest_line(resumed) == reference_run["digest"]
        # The wreckage moved to quarantine (forensics, not deletion) and
        # a fresh durable 15 exists.
        quarantine = os.path.join(
            model_dir, durability.QUARANTINE_DIRNAME
        )
        assert os.path.isdir(quarantine)
        assert any(
            entry.startswith("15.") for entry in os.listdir(quarantine)
        )
        assert 15 in durability.durable_steps(model_dir)


class TestDurabilityModule:
    """Pure-filesystem unit tests: no jax, no subprocesses."""

    def _fake_checkpoint(self, root, step, payload=b"x" * 64):
        step_dir = os.path.join(str(root), "checkpoints", str(step))
        item = os.path.join(step_dir, "default")
        os.makedirs(item)
        with open(os.path.join(step_dir, "_CHECKPOINT_METADATA"), "wb") as f:
            f.write(b"{}")
        with open(os.path.join(item, "_METADATA"), "wb") as f:
            f.write(b"{}")
        with open(os.path.join(item, "data.bin"), "wb") as f:
            f.write(payload)
        return step_dir

    def test_manifest_roundtrip_validates(self, tmp_path):
        step_dir = self._fake_checkpoint(tmp_path, 5)
        durability.write_manifest(step_dir)
        assert durability.validate_step_dir(step_dir) is None
        manifest = json.load(
            open(os.path.join(step_dir, durability.MANIFEST_NAME))
        )
        assert {e["path"] for e in manifest["files"]} == {
            "_CHECKPOINT_METADATA",
            os.path.join("default", "_METADATA"),
            os.path.join("default", "data.bin"),
        }

    def test_truncated_file_fails_manifest(self, tmp_path):
        step_dir = self._fake_checkpoint(tmp_path, 5)
        durability.write_manifest(step_dir)
        with open(os.path.join(step_dir, "default", "data.bin"), "r+b") as f:
            f.truncate(10)
        assert "size mismatch" in durability.validate_step_dir(step_dir)

    def test_missing_file_fails_manifest(self, tmp_path):
        step_dir = self._fake_checkpoint(tmp_path, 5)
        durability.write_manifest(step_dir)
        os.unlink(os.path.join(step_dir, "default", "data.bin"))
        assert "missing" in durability.validate_step_dir(step_dir)

    def test_orbax_tmp_name_is_torn(self, tmp_path):
        path = str(tmp_path / "7.orbax-checkpoint-tmp-123")
        os.makedirs(path)
        assert "tmp" in durability.validate_step_dir(path)

    def test_structural_fallback_without_manifest(self, tmp_path):
        # Committed-by-orbax but not yet blessed (the window between the
        # rename and the manifest write): structurally sound -> durable.
        step_dir = self._fake_checkpoint(tmp_path, 5)
        assert durability.validate_step_dir(step_dir) is None
        # An empty final-named dir (the orbax latest_step() trap) is torn.
        empty = os.path.join(str(tmp_path), "checkpoints", "10")
        os.makedirs(empty)
        assert durability.validate_step_dir(empty) is not None
        assert durability.durable_steps(str(tmp_path)) == [5]

    def test_sweep_quarantines_and_preserves(self, tmp_path):
        good = self._fake_checkpoint(tmp_path, 5)
        durability.write_manifest(good)
        bad = self._fake_checkpoint(tmp_path, 10)
        durability.write_manifest(bad)
        os.unlink(os.path.join(bad, "default", "data.bin"))
        tmp_dir = os.path.join(
            str(tmp_path), "checkpoints", "15.orbax-checkpoint-tmp-9"
        )
        os.makedirs(tmp_dir)
        report = durability.sweep_torn_checkpoints(str(tmp_path))
        assert sorted(name for name, _ in report) == [
            "10",
            "15.orbax-checkpoint-tmp-9",
        ]
        assert durability.durable_steps(str(tmp_path)) == [5]
        quarantine = durability.quarantine_root(str(tmp_path))
        moved = sorted(os.listdir(quarantine))
        assert len(moved) == 2
        # Quarantine preserves the wreckage byte-for-byte (forensics).
        ten = [m for m in moved if m.startswith("10.")][0]
        assert os.path.isfile(
            os.path.join(quarantine, ten, "_CHECKPOINT_METADATA")
        )

    def test_sweep_second_run_is_noop(self, tmp_path):
        bad = self._fake_checkpoint(tmp_path, 10)
        durability.write_manifest(bad)
        os.unlink(os.path.join(bad, "default", "data.bin"))
        assert durability.sweep_torn_checkpoints(str(tmp_path))
        assert durability.sweep_torn_checkpoints(str(tmp_path)) == []

    def test_publish_durable_refuses_torn(self, tmp_path):
        step_dir = self._fake_checkpoint(tmp_path, 5)
        os.unlink(os.path.join(step_dir, "_CHECKPOINT_METADATA"))
        assert not durability.publish_durable(str(tmp_path), 5)
        assert not os.path.exists(
            os.path.join(step_dir, durability.MANIFEST_NAME)
        )

    def test_publish_durable_idempotent(self, tmp_path):
        self._fake_checkpoint(tmp_path, 5)
        assert durability.publish_durable(str(tmp_path), 5)
        assert durability.publish_durable(str(tmp_path), 5)
        assert durability.publish_durable(str(tmp_path), 99) is False


class TestRestoreChaosSites:
    """In-process chaos at the restore site, over one small real run."""

    @pytest.fixture()
    def trained_dir(self, tmp_path):
        import jax

        from tensor2robot_tpu.train import train_eval as te
        from tensor2robot_tpu.utils.mocks import (
            MockInputGenerator,
            MockT2RModel,
        )

        model_dir = str(tmp_path / "run")
        te.train_eval_model(
            MockT2RModel(device_type="cpu", use_batch_norm=False),
            input_generator_train=MockInputGenerator(batch_size=8, seed=7),
            model_dir=model_dir,
            max_train_steps=4,
            eval_steps=None,
            save_checkpoints_steps=4,
            log_every_steps=4,
            seed=31,
        )
        return model_dir

    def _restore(self, model_dir):
        import jax

        from tensor2robot_tpu.train import train_eval as te
        from tensor2robot_tpu.utils.mocks import (
            MockInputGenerator,
            MockT2RModel,
        )

        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        gen = MockInputGenerator(batch_size=8, seed=7)
        gen.set_specification_from_model(model, "train")
        compiled = te.CompiledModel(model, donate_state=False)
        manager = te.create_checkpoint_manager(
            model_dir, save_interval_steps=4
        )
        try:
            return te.restore_or_init_state(
                manager,
                compiled,
                jax.random.PRNGKey(0),
                next(iter(gen.create_dataset("train"))),
            )
        finally:
            manager.close()

    def test_slow_restore_injection_fires_site(self, trained_dir):
        chaos.reset()
        try:
            chaos.configure("restore:1:delay:50")
            state = self._restore(trained_dir)
            assert int(state.step) == 4
            assert chaos.fired() == ["restore:1:delay:50"]
        finally:
            chaos.reset()

    def test_restore_exception_injection_propagates(self, trained_dir):
        chaos.reset()
        try:
            chaos.configure("restore:1:raise")
            with pytest.raises(chaos.ChaosFault):
                self._restore(trained_dir)
        finally:
            chaos.reset()

    def test_restore_skips_torn_latest(self, trained_dir):
        """restore_or_init_state walks PAST a torn newer dir — the
        orbax latest_step() trap — to the durable one (read-only: the
        torn dir stays in place for the owner to quarantine)."""
        torn = os.path.join(trained_dir, "checkpoints", "8")
        os.makedirs(torn)
        state = self._restore(trained_dir)
        assert int(state.step) == 4
        assert os.path.isdir(torn)  # reader never quarantines

    def test_predict_from_model_refuses_torn_only_dir(self, tmp_path):
        from tensor2robot_tpu.train import train_eval as te
        from tensor2robot_tpu.utils.mocks import (
            MockInputGenerator,
            MockT2RModel,
        )

        model_dir = str(tmp_path / "torn_only")
        os.makedirs(os.path.join(model_dir, "checkpoints", "5"))
        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        gen = MockInputGenerator(batch_size=8, seed=7)
        with pytest.raises(FileNotFoundError, match="durable"):
            next(
                te.predict_from_model(model, gen, model_dir)
            )
