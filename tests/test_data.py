"""Data pipeline tests: TFRecord IO, spec-driven parsing, dataset assembly.

Mirrors the coverage strategy of the reference's utils/tfdata_test.py
(generated records incl. sequences, varlen, images) against the JAX-native
pipeline.
"""

import os

import numpy as np
import pytest

from tensor2robot_tpu.data import tfrecord
from tensor2robot_tpu.data.dataset import RecordDataset
from tensor2robot_tpu.data.encoder import encode_example, encode_examples_by_dataset
from tensor2robot_tpu.data.input_generators import (
    DefaultConstantInputGenerator,
    DefaultRandomInputGenerator,
    DefaultRecordInputGenerator,
    GeneratorInputGenerator,
)
from tensor2robot_tpu.data.parser import SpecParser, decode_image
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct
from tensor2robot_tpu.specs import proto_io


class TestTFRecordIO:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "records.tfrecord")
        records = [b"hello", b"", b"x" * 10000]
        tfrecord.write_tfrecords(path, records)
        assert list(tfrecord.read_tfrecords(path)) == records
        assert tfrecord.count_tfrecords(path) == 3

    def test_corruption_detected(self, tmp_path):
        path = str(tmp_path / "bad.tfrecord")
        tfrecord.write_tfrecords(path, [b"payload"])
        data = bytearray(open(path, "rb").read())
        data[14] ^= 0xFF  # flip a payload byte
        with pytest.raises(tfrecord.TFRecordCorruptionError):
            list(tfrecord.read_tfrecords(bytes_path(tmp_path, data)))

    def test_tf_compatibility(self, tmp_path):
        """Our framing must be readable by TensorFlow and vice versa."""
        tf = pytest.importorskip("tensorflow")
        path = str(tmp_path / "ours.tfrecord")
        tfrecord.write_tfrecords(path, [b"abc", b"defg"])
        got = [r.numpy() for r in tf.data.TFRecordDataset(path)]
        assert got == [b"abc", b"defg"]
        theirs = str(tmp_path / "theirs.tfrecord")
        with tf.io.TFRecordWriter(theirs) as w:
            w.write(b"zzz")
        assert list(tfrecord.read_tfrecords(theirs)) == [b"zzz"]

    def test_buffered_reader_matches_streaming(self, tmp_path):
        """The block-buffered native-indexed reader and the per-record
        framing fallback must yield identical record streams, including
        when records straddle block boundaries (tiny buffer_bytes)."""
        from tensor2robot_tpu.data.tfrecord import _read_tfrecords_streaming

        path = str(tmp_path / "blocks.tfrecord")
        rng = np.random.RandomState(0)
        records = [bytes(rng.randint(0, 256, n, np.uint8).tobytes())
                   for n in (0, 1, 100, 5000, 17, 64 << 10)]
        tfrecord.write_tfrecords(path, records)
        assert list(tfrecord.read_tfrecords(path)) == records
        assert list(tfrecord.read_tfrecords(path, buffer_bytes=64)) == records
        assert list(_read_tfrecords_streaming(path, True)) == records
        assert list(tfrecord.read_tfrecords(path, verify_crc=False)) == records

    def test_list_files(self, tmp_path):
        for name in ["a-0.rec", "a-1.rec", "b-0.rec"]:
            tfrecord.write_tfrecords(str(tmp_path / name), [b"r"])
        files = tfrecord.list_files(str(tmp_path / "a-*.rec"))
        assert [os.path.basename(f) for f in files] == ["a-0.rec", "a-1.rec"]
        both = tfrecord.list_files(f"{tmp_path}/a-*.rec,{tmp_path}/b-*.rec")
        assert len(both) == 3
        with pytest.raises(FileNotFoundError):
            tfrecord.list_files(str(tmp_path / "nope-*.rec"))


def bytes_path(tmp_path, data: bytes) -> str:
    path = str(tmp_path / "mutated.tfrecord")
    with open(path, "wb") as f:
        f.write(data)
    return path


def image_bytes(shape=(6, 8, 3), fmt="PNG", value=128):
    import io

    from PIL import Image

    arr = np.full(shape, value, np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format=fmt)
    return buf.getvalue()


class TestParser:
    def spec(self):
        s = TensorSpecStruct()
        s["state"] = ExtendedTensorSpec(shape=(3,), dtype=np.float32, name="s")
        s["action"] = ExtendedTensorSpec(shape=(2,), dtype=np.int64, name="a")
        return s

    def test_roundtrip_fixed(self):
        spec = self.spec()
        values = {"state": np.array([1.0, 2.0, 3.0], np.float32),
                  "action": np.array([4, 5], np.int64)}
        serialized = encode_example(spec, values)
        parsed = SpecParser(spec).parse_single(serialized)
        np.testing.assert_array_equal(parsed["state"], values["state"])
        np.testing.assert_array_equal(parsed["action"], values["action"])

    def test_batch_parse(self):
        spec = self.spec()
        records = [
            encode_example(spec, {"state": np.full((3,), i, np.float32),
                                  "action": np.array([i, i], np.int64)})
            for i in range(4)
        ]
        batch = SpecParser(spec).parse_batch(records)
        assert batch["state"].shape == (4, 3)
        np.testing.assert_array_equal(batch["state"][2], [2.0, 2.0, 2.0])

    def test_missing_required_raises(self):
        spec = self.spec()
        serialized = encode_example(
            {"state": spec["state"]}, {"state": np.zeros(3, np.float32)}
        )
        with pytest.raises(KeyError):
            SpecParser(spec).parse_single(serialized)

    def test_optional_absent_ok(self):
        spec = self.spec()
        spec["extra"] = ExtendedTensorSpec(
            shape=(1,), dtype=np.float32, is_optional=True
        )
        serialized = encode_example(
            self.spec(), {"state": np.zeros(3, np.float32),
                          "action": np.zeros(2, np.int64)}
        )
        parsed = SpecParser(spec).parse_single(serialized)
        assert "extra" not in parsed

    def test_bfloat16_roundtrip(self):
        import jax.numpy as jnp

        spec = {"x": ExtendedTensorSpec(shape=(2,), dtype="bfloat16", name="x")}
        serialized = encode_example(spec, {"x": np.array([1.5, 2.5], np.float32)})
        batch = SpecParser(spec).parse_batch([serialized])
        assert batch["x"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(batch["x"].astype(np.float32), [[1.5, 2.5]])

    def test_varlen_pad_and_clip(self):
        spec = {"v": ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="v",
                                        varlen_default_value=-1.0)}
        short = encode_example(
            {"v": ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="v")},
            {"v": np.array([1.0, 2.0], np.float32)},
        )
        parsed = SpecParser(spec).parse_single(short)
        np.testing.assert_array_equal(parsed["v"], [1.0, 2.0, -1.0, -1.0])
        long = encode_example(
            {"v": ExtendedTensorSpec(shape=(6,), dtype=np.float32, name="v")},
            {"v": np.arange(6, dtype=np.float32)},
        )
        parsed = SpecParser(spec).parse_single(long)
        np.testing.assert_array_equal(parsed["v"], [0.0, 1.0, 2.0, 3.0])

    def test_image_decode_png_roundtrip(self):
        spec = {"img": ExtendedTensorSpec(shape=(6, 8, 3), dtype=np.uint8,
                                          name="img", data_format="png")}
        values = {"img": np.random.RandomState(0).randint(0, 255, (6, 8, 3), np.uint8)}
        serialized = encode_example(spec, values)
        parsed = SpecParser(spec).parse_single(serialized)
        np.testing.assert_array_equal(parsed["img"], values["img"])

    def test_empty_image_zero_fallback(self):
        spec = ExtendedTensorSpec(shape=(4, 4, 3), dtype=np.uint8, data_format="jpeg")
        out = decode_image(b"", spec)
        np.testing.assert_array_equal(out, np.zeros((4, 4, 3), np.uint8))

    def test_native_jpeg_decode_matches_pil(self):
        """The one-shot libjpeg path (native/jpeg_decode.cc) must be
        BIT-IDENTICAL to the PIL fallback — both sit on libjpeg-turbo, so
        any divergence means the wiring (colorspace, stride, channel
        request) is wrong, not the codec."""
        import io as iomod

        from PIL import Image

        from tensor2robot_tpu.data import parser as parser_mod
        from tensor2robot_tpu.data.encoder import encode_image

        if parser_mod._load_jpeg_native() is None:
            pytest.skip("no C++ toolchain / libjpeg dev files on this host")
        img = np.random.RandomState(3).randint(
            0, 256, (96, 128, 3), np.uint8
        )
        data = encode_image(img, "jpeg")
        native = parser_mod._decode_jpeg_native(data, (96, 128, 3))
        assert native is not None
        pil = np.asarray(Image.open(iomod.BytesIO(data)).convert("RGB"))
        np.testing.assert_array_equal(native, pil)

    def test_native_jpeg_decode_rejects_garbage(self):
        """Corrupt buffers must return None (PIL fallback handles the
        error reporting), never crash the process — libjpeg's default
        handler would exit()."""
        from tensor2robot_tpu.data import parser as parser_mod

        assert (
            parser_mod._decode_jpeg_native(
                b"\xff\xd8" + b"not a jpeg" * 10, (8, 8, 3)
            )
            is None
        )
        # Shape mismatch (spec says 4x4, file is bigger) -> None, fallback.
        from tensor2robot_tpu.data.encoder import encode_image

        img = np.zeros((16, 16, 3), np.uint8)
        assert (
            parser_mod._decode_jpeg_native(
                encode_image(img, "jpeg"), (4, 4, 3)
            )
            is None
        )

    def test_sequence_roundtrip_and_lengths(self):
        spec = TensorSpecStruct()
        spec["obs"] = ExtendedTensorSpec(
            shape=(2,), dtype=np.float32, name="obs", is_sequence=True
        )
        spec["goal"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="goal")
        r1 = encode_example(spec, {"obs": np.ones((5, 2), np.float32),
                                   "goal": np.zeros((1,), np.float32)})
        r2 = encode_example(spec, {"obs": np.ones((3, 2), np.float32),
                                   "goal": np.ones((1,), np.float32)})
        batch = SpecParser(spec).parse_batch([r1, r2])
        assert batch["obs"].shape == (2, 5, 2)  # padded to batch max
        np.testing.assert_array_equal(batch["obs_length"], [5, 3])
        np.testing.assert_array_equal(batch["obs"][1, 3:], np.zeros((2, 2)))

    def test_multi_dataset_routing(self):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="a",
                                       dataset_key="d1")
        spec["b"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="b",
                                       dataset_key="d2")
        values = {"a": np.array([1.0], np.float32), "b": np.array([2.0], np.float32)}
        by_key = encode_examples_by_dataset(spec, values)
        assert set(by_key.keys()) == {"d1", "d2"}
        parsed = SpecParser(spec).parse_single(by_key)
        np.testing.assert_array_equal(parsed["a"], [1.0])
        np.testing.assert_array_equal(parsed["b"], [2.0])


class TestRecordDataset:
    def make_records(self, tmp_path, n=16, shards=2):
        spec = TensorSpecStruct()
        spec["x"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="x")
        spec["y"] = ExtendedTensorSpec(shape=(), dtype=np.int64, name="y")
        idx = 0
        for shard in range(shards):
            records = []
            for _ in range(n // shards):
                records.append(
                    encode_example(spec, {"x": np.full((2,), idx, np.float32),
                                          "y": np.asarray(idx, np.int64)})
                )
                idx += 1
            tfrecord.write_tfrecords(str(tmp_path / f"data-{shard}.tfrecord"), records)
        return spec

    def test_single_epoch_eval(self, tmp_path):
        spec = self.make_records(tmp_path)
        dataset = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "data-*.tfrecord"),
            batch_size=4,
            mode="eval",
        )
        batches = list(dataset)
        assert len(batches) == 4
        all_y = np.concatenate([b["y"] for b in batches])
        assert sorted(all_y.tolist()) == list(range(16))

    def test_process_parse_backend_matches_thread(self, tmp_path):
        """The process-pool decode path must yield the same batches as the
        thread pool (order is deterministic in eval mode)."""
        spec = self.make_records(tmp_path)

        thread_ds = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "data-*.tfrecord"),
            batch_size=4,
            mode="eval",
            num_parse_workers=2,
            parse_backend="thread",
        )
        process_ds = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "data-*.tfrecord"),
            batch_size=4,
            mode="eval",
            num_parse_workers=2,
            parse_backend="process",
        )
        thread_batches = list(thread_ds)
        process_batches = list(process_ds)
        assert len(thread_batches) == len(process_batches) == 4
        for a, b in zip(thread_batches, process_batches):
            assert sorted(a.keys()) == sorted(b.keys())
            for key in a.keys():
                np.testing.assert_array_equal(
                    np.asarray(a[key]), np.asarray(b[key])
                )
        # The spawn pool is cached on the dataset: a second epoch reuses it
        # (no re-spawn) and still yields the same data.
        pool_first = process_ds._process_pool
        assert pool_first is not None
        second_epoch = list(process_ds)
        assert process_ds._process_pool is pool_first
        np.testing.assert_array_equal(
            np.asarray(second_epoch[0]["y"]),
            np.asarray(process_batches[0]["y"]),
        )
        process_ds.close()
        assert process_ds._process_pool is None

    def test_bad_parse_backend_rejected(self, tmp_path):
        spec = self.make_records(tmp_path)
        with pytest.raises(ValueError, match="parse_backend"):
            RecordDataset(
                specs=spec,
                file_patterns=str(tmp_path / "data-*.tfrecord"),
                batch_size=4,
                parse_backend="greenlet",
            )

    def test_train_repeats_and_shuffles(self, tmp_path):
        spec = self.make_records(tmp_path)
        dataset = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "data-*.tfrecord"),
            batch_size=4,
            mode="train",
            seed=42,
            shuffle_buffer_size=16,
        )
        it = iter(dataset)
        seen = [next(it)["y"] for _ in range(8)]  # 2 epochs worth
        flat = np.concatenate(seen).tolist()
        assert len(flat) == 32
        assert sorted(set(flat)) == list(range(16))
        assert flat[:16] != list(range(16))  # shuffled


class TestInputGenerators:
    def spec_pair(self):
        features = TensorSpecStruct()
        features["x"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="x")
        labels = TensorSpecStruct()
        labels["y"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="y")
        return features, labels

    def test_record_generator(self, tmp_path):
        features, labels = self.spec_pair()
        combined = TensorSpecStruct()
        combined.features = features.copy()
        combined.labels = labels.copy()
        records = [
            encode_example(combined, {"features/x": np.full((2,), i, np.float32),
                                      "labels/y": np.array([i], np.float32)})
            for i in range(8)
        ]
        tfrecord.write_tfrecords(str(tmp_path / "r.tfrecord"), records)
        gen = DefaultRecordInputGenerator(
            file_patterns=str(tmp_path / "r.tfrecord"), batch_size=4
        )
        gen.set_specification(features, labels)
        batch = next(iter(gen.create_dataset("eval")))
        assert batch.features.x.shape == (4, 2)
        assert batch.labels.y.shape == (4, 1)

    def test_random_and_constant_generators(self):
        features, labels = self.spec_pair()
        for gen in [DefaultRandomInputGenerator(batch_size=3),
                    DefaultConstantInputGenerator(constant_value=1.0, batch_size=3)]:
            gen.set_specification(features, labels)
            batch = next(iter(gen.create_dataset("train")))
            assert batch.features.x.shape == (3, 2)

    def test_generator_input_generator(self):
        features, labels = self.spec_pair()

        def source():
            while True:
                yield {"features/x": np.zeros(2, np.float32),
                       "labels/y": np.ones(1, np.float32)}

        gen = GeneratorInputGenerator(source, batch_size=2)
        gen.set_specification(features, labels)
        batch = next(iter(gen.create_dataset("train")))
        np.testing.assert_array_equal(batch.labels.y, np.ones((2, 1)))


class TestProtoIO:
    def test_spec_roundtrip(self):
        spec = ExtendedTensorSpec(
            shape=(4, None, 3), dtype="bfloat16", name="n", is_optional=True,
            is_sequence=True, data_format="jpeg", dataset_key="d",
        )
        back = proto_io.spec_from_proto(proto_io.spec_to_proto(spec))
        assert back.shape == (4, None, 3)
        assert back.name == "n"
        assert back.is_optional and back.is_sequence
        assert back.data_format == "jpeg"
        assert back.dataset_key == "d"
        import jax.numpy as jnp
        assert back.dtype == jnp.bfloat16

    def test_varlen_zero_roundtrip(self):
        spec = ExtendedTensorSpec(shape=(4,), dtype=np.float32, varlen_default_value=0.0)
        back = proto_io.spec_from_proto(proto_io.spec_to_proto(spec))
        assert back.varlen_default_value == 0.0

    def test_assets_roundtrip(self, tmp_path):
        features = TensorSpecStruct()
        features["img"] = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.uint8, name="i")
        labels = TensorSpecStruct()
        labels["y"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="y")
        path = proto_io.write_t2r_assets(str(tmp_path), features, labels, global_step=7)
        assert path.endswith("t2r_assets.pbtxt")
        f, l, step = proto_io.read_t2r_assets(str(tmp_path))
        assert list(f.keys()) == ["img"]
        assert l is not None and list(l.keys()) == ["y"]
        assert step == 7


class TestMultiDatasetZip:
    def test_misalignment_raises(self, tmp_path):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="a",
                                       dataset_key="d1")
        spec["b"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="b",
                                       dataset_key="d2")
        recs_a = [encode_example({"a": spec["a"]}, {"a": np.array([float(i)], np.float32)})
                  for i in range(4)]
        recs_b = [encode_example({"b": spec["b"]}, {"b": np.array([float(i)], np.float32)})
                  for i in range(3)]  # one short
        tfrecord.write_tfrecords(str(tmp_path / "a.tfrecord"), recs_a)
        tfrecord.write_tfrecords(str(tmp_path / "b.tfrecord"), recs_b)
        dataset = RecordDataset(
            specs=spec,
            file_patterns={"d1": str(tmp_path / "a.tfrecord"),
                           "d2": str(tmp_path / "b.tfrecord")},
            batch_size=1, mode="eval", prefetch_depth=0,
        )
        with pytest.raises(ValueError, match="misalignment"):
            list(dataset)

    def test_aligned_zip(self, tmp_path):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="a",
                                       dataset_key="d1")
        spec["b"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="b",
                                       dataset_key="d2")
        recs_a = [encode_example({"a": spec["a"]}, {"a": np.array([float(i)], np.float32)})
                  for i in range(4)]
        recs_b = [encode_example({"b": spec["b"]}, {"b": np.array([float(10 + i)], np.float32)})
                  for i in range(4)]
        tfrecord.write_tfrecords(str(tmp_path / "a.tfrecord"), recs_a)
        tfrecord.write_tfrecords(str(tmp_path / "b.tfrecord"), recs_b)
        dataset = RecordDataset(
            specs=spec,
            file_patterns={"d1": str(tmp_path / "a.tfrecord"),
                           "d2": str(tmp_path / "b.tfrecord")},
            batch_size=2, mode="eval", prefetch_depth=0,
        )
        batches = list(dataset)
        assert len(batches) == 2
        np.testing.assert_array_equal(
            batches[0]["b"] - batches[0]["a"], np.full((2, 1), 10.0)
        )


class TestHardening:
    def test_huge_length_field_reports_corruption(self, tmp_path):
        """A crafted length of ~2^64 must raise, not crash (overflow guard)."""
        import struct as structlib

        from tensor2robot_tpu.data.tfrecord import (
            index_tfrecord_buffer, masked_crc32c,
        )
        header = structlib.pack("<Q", (1 << 64) - 16)
        buf = header + structlib.pack("<I", masked_crc32c(header)) + b"x" * 32
        with pytest.raises(tfrecord.TFRecordCorruptionError):
            index_tfrecord_buffer(buf)
        with pytest.raises(tfrecord.TFRecordCorruptionError):
            list(tfrecord.read_tfrecords(bytes_path(tmp_path, buf)))

    def test_image_stack_roundtrip(self):
        spec = {"imgs": ExtendedTensorSpec(shape=(2, 4, 4, 3), dtype=np.uint8,
                                           name="imgs", data_format="png")}
        values = {"imgs": np.random.RandomState(0).randint(
            0, 255, (2, 4, 4, 3), np.uint8)}
        parsed = SpecParser(spec).parse_single(encode_example(spec, values))
        np.testing.assert_array_equal(parsed["imgs"], values["imgs"])

    def test_image_count_mismatch_raises(self):
        one_spec = {"imgs": ExtendedTensorSpec(shape=(4, 4, 3), dtype=np.uint8,
                                               name="imgs", data_format="png")}
        two = {"imgs": ExtendedTensorSpec(shape=(2, 4, 4, 3), dtype=np.uint8,
                                          name="imgs", data_format="png")}
        serialized = encode_example(
            two, {"imgs": np.zeros((2, 4, 4, 3), np.uint8)}
        )
        with pytest.raises(ValueError, match="images"):
            SpecParser(one_spec).parse_single(serialized)


class TestParseOnError:
    """T2R_PARSE_ON_ERROR: graceful degradation on a genuinely corrupt
    record mid-stream. Default (`raise`) keeps the canonical kill-the-
    consumer error; `skip` drops-and-counts the bad record(s) — the
    quarantine counter surfaced in RecordDataset.stats() — and yields
    the surviving (short) batch instead of dying."""

    def _corrupt_fixture(self, tmp_path, n=8, bad=(3,)):
        spec = TensorSpecStruct()
        spec["features/x"] = ExtendedTensorSpec(
            shape=(3,), dtype=np.float32, name="x"
        )
        records = [
            encode_example(spec, {"features/x": np.full(3, i, np.float32)})
            for i in range(n)
        ]
        for index in bad:
            # Forge a LEN frame that overruns the record: both the fast
            # parser (strict framing) and protobuf reject it.
            records[index] = records[index][:4] + b"\xff\xff\xff\xff"
        path = str(tmp_path / "mixed.tfrecord")
        tfrecord.write_tfrecords(path, records)
        return spec, path

    def _dataset(self, spec, path, workers=0, backend="thread"):
        return RecordDataset(
            spec, path, batch_size=4, mode="eval", repeat=False,
            num_parse_workers=workers, parse_backend=backend,
            prefetch_depth=0, drop_remainder=False,
        )

    def test_default_raise_kills_consumer(self, tmp_path, monkeypatch):
        monkeypatch.delenv("T2R_PARSE_ON_ERROR", raising=False)
        spec, path = self._corrupt_fixture(tmp_path)
        dataset = self._dataset(spec, path)
        with pytest.raises(Exception):
            list(dataset)
        dataset.close()

    @pytest.mark.parametrize("workers", [0, 2])
    def test_skip_counts_and_survives(self, tmp_path, monkeypatch, workers):
        monkeypatch.setenv("T2R_PARSE_ON_ERROR", "skip")
        spec, path = self._corrupt_fixture(tmp_path)
        dataset = self._dataset(spec, path, workers=workers)
        batches = list(dataset)
        # Record 3 dropped: its batch comes back short, the stream lives,
        # and the surviving values are exactly the good records in order.
        sizes = [batch["features/x"].shape[0] for batch in batches]
        assert sizes == [3, 4]
        got = np.concatenate([np.asarray(b["features/x"])[:, 0]
                              for b in batches])
        np.testing.assert_array_equal(got, [0, 1, 2, 4, 5, 6, 7])
        stats = dataset.stats()
        assert stats["records_skipped"] == 1
        assert stats["batches_degraded"] == 1
        assert stats["batches_dropped"] == 0
        dataset.close()

    def test_skip_whole_bad_batch_dropped(self, tmp_path, monkeypatch):
        monkeypatch.setenv("T2R_PARSE_ON_ERROR", "skip")
        spec, path = self._corrupt_fixture(
            tmp_path, n=8, bad=(0, 1, 2, 3)
        )
        dataset = self._dataset(spec, path)
        batches = list(dataset)
        assert [b["features/x"].shape[0] for b in batches] == [4]
        stats = dataset.stats()
        assert stats["records_skipped"] == 4
        assert stats["batches_dropped"] == 1
        dataset.close()

    def test_skip_mode_reraises_batch_level_failures(self, monkeypatch):
        """Skip mode is licensed to swallow RECORD corruption only: a
        failure where every record parses individually (stacking/ROI/
        parser bug at batch level) must re-raise the original error
        uncounted, not log 'dropped 0 records' and die on the retry."""
        from tensor2robot_tpu.data.dataset import (
            ParseStats, _parse_chunk_impl,
        )

        monkeypatch.setenv("T2R_PARSE_ON_ERROR", "skip")

        class BatchLevelBroken:
            def parse_single(self, record):
                return {"x": np.zeros(3, np.float32)}

            def parse_batch(self, chunk, roi=None):
                raise RuntimeError("batch-level stacking failure")

        stats = ParseStats()
        with pytest.raises(RuntimeError, match="batch-level"):
            _parse_chunk_impl(None, BatchLevelBroken(), [b"a", b"b"], stats)
        assert stats.snapshot()["records_skipped"] == 0
        assert stats.snapshot()["batches_degraded"] == 0

    def test_skip_counts_worker_fallbacks_in_stats(
        self, tmp_path, monkeypatch
    ):
        """Process backend: worker-side fast-parser fallbacks must fold
        into the parent's stats() (they ride the payload delta)."""
        monkeypatch.setenv("T2R_PARSE_ON_ERROR", "skip")
        spec, path = self._corrupt_fixture(tmp_path)
        dataset = self._dataset(spec, path, workers=2, backend="process")
        batches = list(dataset)
        assert [b["features/x"].shape[0] for b in batches] == [3, 4]
        stats = dataset.stats()
        assert stats["records_skipped"] == 1
        # The corrupt batch forced one worker fast-parse fallback, and
        # it must be visible HERE, not trapped in the worker process.
        assert stats["fast_fallbacks"] >= 1
        dataset.close()

    def test_skip_mode_clean_stream_untouched(self, tmp_path, monkeypatch):
        """With no corruption, skip mode changes nothing: same batches,
        zero counters (the flag is a failure-path policy, not a parser
        variant)."""
        monkeypatch.setenv("T2R_PARSE_ON_ERROR", "skip")
        spec, path = self._corrupt_fixture(tmp_path, bad=())
        dataset = self._dataset(spec, path)
        batches = list(dataset)
        assert [b["features/x"].shape[0] for b in batches] == [4, 4]
        assert dataset.stats()["records_skipped"] == 0
        dataset.close()


class TestParallelParse:
    """The thread-pool parse path must match the synchronous path exactly
    (same batches, same order) — parallelism is an implementation detail."""

    def make_records(self, tmp_path, n=24):
        spec = TensorSpecStruct()
        spec["img"] = ExtendedTensorSpec(
            shape=(8, 10, 3), dtype=np.uint8, name="img", data_format="jpeg"
        )
        spec["y"] = ExtendedTensorSpec(shape=(), dtype=np.int64, name="y")
        records = []
        for i in range(n):
            img = np.full((8, 10, 3), i % 250, np.uint8)
            records.append(
                encode_example(spec, {"img": img, "y": np.asarray(i, np.int64)})
            )
        tfrecord.write_tfrecords(str(tmp_path / "imgs.tfrecord"), records)
        return spec

    def _batches(self, tmp_path, spec, workers):
        dataset = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "imgs.tfrecord"),
            batch_size=4,
            mode="eval",
            num_parse_workers=workers,
        )
        return list(dataset)

    def test_parallel_matches_synchronous(self, tmp_path):
        spec = self.make_records(tmp_path)
        sync = self._batches(tmp_path, spec, workers=0)
        par = self._batches(tmp_path, spec, workers=4)
        assert len(sync) == len(par) == 6
        for a, b in zip(sync, par):
            np.testing.assert_array_equal(a["y"], b["y"])
            np.testing.assert_array_equal(a["img"], b["img"])

    def test_parallel_train_stream(self, tmp_path):
        spec = self.make_records(tmp_path)
        dataset = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "imgs.tfrecord"),
            batch_size=4,
            mode="train",
            seed=1,
            num_parse_workers=2,
        )
        it = iter(dataset)
        batches = [next(it) for _ in range(10)]  # > one epoch; repeats fine
        assert all(b["img"].shape == (4, 8, 10, 3) for b in batches)

    @pytest.mark.slow
    def test_process_backend_shm_ring_roundtrip(self, tmp_path):
        """Batches big enough for the shared-memory return path (>= 1 MB
        of decoded image) must round-trip bit-exact through ring slots,
        across epochs (slot reuse), and slots must recycle rather than
        leak (bounded ring)."""
        spec = TensorSpecStruct()
        spec["img"] = ExtendedTensorSpec(
            shape=(320, 320, 3), dtype=np.uint8, name="img", data_format="png"
        )
        spec["y"] = ExtendedTensorSpec(shape=(), dtype=np.int64, name="y")
        records = []
        for i in range(8):
            img = np.full((320, 320, 3), i * 7 % 250, np.uint8)
            records.append(
                encode_example(spec, {"img": img, "y": np.asarray(i, np.int64)})
            )
        tfrecord.write_tfrecords(str(tmp_path / "shm.tfrecord"), records)
        kwargs = dict(
            specs=spec,
            file_patterns=str(tmp_path / "shm.tfrecord"),
            batch_size=4,
            mode="eval",
            num_parse_workers=2,
        )
        ref = list(RecordDataset(parse_backend="thread", **kwargs))
        ds = RecordDataset(parse_backend="process", **kwargs)
        from tensor2robot_tpu.data.dataset import _ShmArray

        shm_batches = 0
        # Enough epochs that total shm cycles exceed the ring size
        # (max_in_flight + 2 slots): recycling, not just first use.
        num_epochs = 8
        for epoch in range(num_epochs):
            batches = list(ds)
            assert len(batches) == len(ref) == 2
            for a, b in zip(batches, ref):
                if isinstance(a["img"], _ShmArray):
                    shm_batches += 1
                np.testing.assert_array_equal(
                    np.asarray(a["img"]), np.asarray(b["img"])
                )
                np.testing.assert_array_equal(
                    np.asarray(a["y"]), np.asarray(b["y"])
                )
            del a, b, batches  # release views so slots return to the ring
        assert ds._shm_ring is not None
        ring_size = len(ds._shm_ring.slots)
        assert ring_size > 0
        # First batches return inline (they size the ring); after that the
        # shm path must carry the image batches, INCLUDING after every
        # slot has been used once — i.e. released slots really recycle.
        assert shm_batches > ring_size, (shm_batches, ring_size)
        # Early abandonment must not leak ring slots: drop an iterator
        # mid-epoch, then a fresh full epoch must still ride the shm path
        # (completed-but-unconsumed futures return their slots on discard).
        for _ in range(3):
            it = iter(ds)
            next(it)
            del it
        batches = list(ds)
        assert any(isinstance(b["img"], _ShmArray) for b in batches)
        del batches
        ds.close()
        assert ds._shm_ring is None

    def test_parse_error_propagates(self, tmp_path):
        spec = TensorSpecStruct()
        spec["x"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="x")
        # Write records missing the required feature.
        bad_spec = TensorSpecStruct()
        bad_spec["z"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="z")
        records = [
            encode_example(bad_spec, {"z": np.zeros((2,), np.float32)})
            for _ in range(4)
        ]
        tfrecord.write_tfrecords(str(tmp_path / "bad.tfrecord"), records)
        dataset = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "bad.tfrecord"),
            batch_size=4,
            mode="eval",
            num_parse_workers=2,
        )
        with pytest.raises(KeyError):
            list(dataset)


class TestCompression:
    def test_compress_decompress_roundtrip_png(self):
        from tensor2robot_tpu.data.compression import (
            create_compress_fn,
            create_decompress_fn,
        )

        spec = TensorSpecStruct()
        spec["img"] = ExtendedTensorSpec(
            shape=(6, 7, 3), dtype=np.uint8, name="img", data_format="png"
        )
        spec["action"] = ExtendedTensorSpec(
            shape=(2,), dtype=np.float32, name="action"
        )
        batch = TensorSpecStruct()
        rng = np.random.RandomState(0)
        batch["img"] = rng.randint(0, 255, (3, 6, 7, 3), np.uint8)
        batch["action"] = rng.randn(3, 2).astype(np.float32)

        compressed = create_compress_fn(spec)(batch)
        assert isinstance(compressed["img"][0], bytes)
        np.testing.assert_array_equal(compressed["action"], batch["action"])
        restored = create_decompress_fn(spec)(compressed)
        # PNG is lossless: exact roundtrip.
        np.testing.assert_array_equal(restored["img"], batch["img"])

    def test_jpeg_compress_is_lossy_but_close(self):
        from tensor2robot_tpu.data.compression import (
            create_compress_fn,
            create_decompress_fn,
        )

        spec = TensorSpecStruct()
        spec["img"] = ExtendedTensorSpec(
            shape=(16, 16, 3), dtype=np.uint8, name="img", data_format="jpeg"
        )
        batch = TensorSpecStruct()
        batch["img"] = np.full((2, 16, 16, 3), 128, np.uint8)
        restored = create_decompress_fn(spec)(create_compress_fn(spec)(batch))
        assert restored["img"].shape == (2, 16, 16, 3)
        assert np.abs(restored["img"].astype(int) - 128).max() <= 4

    def test_image_stack_roundtrip(self):
        from tensor2robot_tpu.data.compression import (
            create_compress_fn,
            create_decompress_fn,
        )

        spec = TensorSpecStruct()
        spec["frames"] = ExtendedTensorSpec(
            shape=(4, 6, 6, 3), dtype=np.uint8, name="frames", data_format="png"
        )
        batch = TensorSpecStruct()
        batch["frames"] = np.random.RandomState(1).randint(
            0, 255, (2, 4, 6, 6, 3), np.uint8
        )
        compressed = create_compress_fn(spec)(batch)
        assert len(compressed["frames"]) == 2
        assert len(compressed["frames"][0]) == 4
        restored = create_decompress_fn(spec)(compressed)
        np.testing.assert_array_equal(restored["frames"], batch["frames"])


class TestHostSharding:
    def _write_shards(self, tmp_path, n=4):
        spec = TensorSpecStruct()
        spec["y"] = ExtendedTensorSpec(shape=(), dtype=np.int64, name="y")
        for shard in range(n):
            tfrecord.write_tfrecords(
                str(tmp_path / f"s-{shard}.tfrecord"),
                [encode_example(spec, {"y": np.asarray(shard, np.int64)})],
            )
        return spec

    def test_hosts_get_disjoint_complete_slices(self, tmp_path, monkeypatch):
        import jax

        spec = self._write_shards(tmp_path)
        seen = []
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        for host in range(2):
            monkeypatch.setattr(jax, "process_index", lambda h=host: h)
            dataset = RecordDataset(
                specs=spec,
                file_patterns=str(tmp_path / "s-*.tfrecord"),
                batch_size=1,
                mode="eval",
                drop_remainder=False,
                shard_by_host=True,
            )
            seen.append(
                sorted(int(b["y"][0]) for b in dataset)
            )
        # Round-robin over the sorted file list: disjoint and complete.
        assert seen[0] == [0, 2] and seen[1] == [1, 3]

    def test_host_without_files_raises(self, tmp_path, monkeypatch):
        import jax

        spec = self._write_shards(tmp_path, n=1)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        with pytest.raises(ValueError, match="no files"):
            RecordDataset(
                specs=spec,
                file_patterns=str(tmp_path / "s-*.tfrecord"),
                batch_size=1,
                mode="eval",
                shard_by_host=True,
            )

    def test_single_process_unaffected(self, tmp_path):
        spec = TensorSpecStruct()
        spec["y"] = ExtendedTensorSpec(shape=(), dtype=np.int64, name="y")
        for shard in range(4):
            tfrecord.write_tfrecords(
                str(tmp_path / f"s-{shard}.tfrecord"),
                [encode_example(spec, {"y": np.asarray(shard, np.int64)})],
            )
        dataset = RecordDataset(
            specs=spec,
            file_patterns=str(tmp_path / "s-*.tfrecord"),
            batch_size=2,
            mode="eval",
            shard_by_host=True,  # process_count()==1 -> no-op
        )
        ys = np.concatenate([b["y"] for b in dataset])
        assert sorted(ys.tolist()) == [0, 1, 2, 3]
