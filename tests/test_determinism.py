"""Cross-process training determinism: same seed, bit-identical weights.

Stronger than the golden-value gates (decimal=5 tolerance, one process):
two INDEPENDENT OS processes train the same model/seed and must produce
byte-identical final parameters. Catches hidden nondeterminism —
unseeded rngs, iteration-order dependence, time-based branching — that
tolerance-based checks absorb. (Same-platform only by design: the
fixture regeneration caveat for cross-platform drift is documented on
the golden tools.)
"""

import hashlib
import os
import subprocess
import sys

import pytest

_TRAINER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
model_dir = sys.argv[1]
import hashlib
import numpy as np
from tensor2robot_tpu.train.train_eval import train_eval_model
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel
from tensor2robot_tpu.train import train_eval as te

train_eval_model(
    MockT2RModel(device_type="cpu"),
    input_generator_train=MockInputGenerator(batch_size=4, seed=11),
    model_dir=model_dir,
    max_train_steps=25,
    eval_steps=None,
    save_checkpoints_steps=25,
    seed=123,
)
# Hash the final checkpoint's param bytes deterministically.
from tensor2robot_tpu.train.train_eval import CompiledModel

model = MockT2RModel(device_type="cpu")
gen = MockInputGenerator(batch_size=4, seed=11)
gen.set_specification_from_model(model, "train")
compiled = CompiledModel(model, donate_state=False)
manager = te.create_checkpoint_manager(model_dir, save_interval_steps=25)
restored = te.restore_or_init_state(manager, compiled, jax.random.PRNGKey(0),
                                    next(iter(gen.create_dataset("train"))))
digest = hashlib.sha256()
for leaf in jax.tree_util.tree_leaves(jax.device_get(restored.params)):
    digest.update(np.ascontiguousarray(leaf).tobytes())
print("PARAM_SHA256", digest.hexdigest(), "STEP", int(restored.step), flush=True)
"""


@pytest.mark.slow
def test_same_seed_trains_bit_identically_across_processes(tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    digests = []
    for run in range(2):
        proc = subprocess.run(
            [sys.executable, "-c", _TRAINER, str(tmp_path / f"run{run}")],
            capture_output=True,
            text=True,
            timeout=420,
            env=env,
            cwd=cwd,
        )
        assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-1500:]
        line = [
            l for l in proc.stdout.splitlines() if l.startswith("PARAM_SHA256")
        ]
        assert line, proc.stdout[-1500:]
        digests.append(line[0])
    assert digests[0] == digests[1], digests
    assert "STEP 25" in digests[0]
