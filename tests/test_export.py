"""Export layer tests: artifact roundtrip, StableHLO serving, exporters + GC.

Mirrors the export coverage of the reference's train_eval_test.py (export
dirs appear, exported model loads, numpy vs tf.Example interfaces agree)
and checkpoint_hooks_test.py (version GC).
"""

import os

import jax
import numpy as np
import pytest

from tensor2robot_tpu.export import (
    BestExporter,
    DefaultExportGenerator,
    DirectoryVersionGC,
    ExportedModel,
    LatestExporter,
    create_default_exporters,
    create_valid_result_larger,
    create_valid_result_smaller,
    latest_export_dir,
    list_export_dirs,
    save_exported_model,
)
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel


@pytest.fixture(scope="module")
def trained():
    """A briefly-trained compiled mock model + its state."""
    model = MockT2RModel(device_type="cpu")
    generator = MockInputGenerator(batch_size=8)
    generator.set_specification_from_model(model, "train")
    batches = iter(generator.create_dataset("train"))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
    for _ in range(3):
        batch = compiled.shard_batch(next(batches))
        state, _ = compiled.train_step(state, batch, jax.random.PRNGKey(1))
    return compiled, state


def _export_once(trained, root, **kwargs):
    compiled, state = trained
    generator = DefaultExportGenerator()
    generator.set_specification_from_model(compiled.model)
    variables = state.export_variables()
    serving_fn = generator.create_serving_fn(compiled, variables)
    return save_exported_model(
        root,
        variables=variables,
        feature_spec=generator.serving_input_spec(),
        label_spec=generator.label_spec,
        global_step=int(jax.device_get(state.step)),
        predict_fn=serving_fn,
        example_features=generator.create_example_features(),
        **kwargs,
    )


class TestSavedModelArtifact:
    def test_export_creates_valid_timestamped_dir(self, trained, tmp_path):
        root = str(tmp_path / "export")
        path = _export_once(trained, root)
        assert os.path.basename(path).isdigit()
        assert latest_export_dir(root) == path
        assert os.path.exists(os.path.join(path, "variables.msgpack"))
        assert os.path.exists(
            os.path.join(path, "assets.extra", "t2r_assets.pbtxt")
        )

    def test_stablehlo_predict_matches_model(self, trained, tmp_path):
        compiled, state = trained
        path = _export_once(trained, str(tmp_path / "export"))
        exported = ExportedModel(path)
        assert exported.has_stablehlo, exported.metadata.get("stablehlo_error")
        x = np.random.RandomState(0).uniform(-1, 1, (4, 3)).astype(np.float32)
        out = exported.predict({"x": x})
        assert out["a_predicted"].shape == (4, 1)
        # Must match the in-process model bit-for-bit structure-wise.
        variables = state.export_variables()
        direct = compiled.predict_step(variables, {"x": x})
        np.testing.assert_allclose(
            out["a_predicted"], np.asarray(direct["a_predicted"]), rtol=1e-5
        )

    def test_stablehlo_is_batch_polymorphic(self, trained, tmp_path):
        path = _export_once(trained, str(tmp_path / "export"))
        exported = ExportedModel(path)
        for batch in (1, 7):
            x = np.zeros((batch, 3), np.float32)
            assert exported.predict({"x": x})["a_predicted"].shape == (batch, 1)

    def test_assets_spec_roundtrip(self, trained, tmp_path):
        path = _export_once(trained, str(tmp_path / "export"))
        exported = ExportedModel(path)
        assert "x" in exported.feature_spec
        assert exported.feature_spec["x"].shape == (3,)
        assert exported.global_step >= 3

    def test_variables_roundtrip(self, trained, tmp_path):
        compiled, state = trained
        path = _export_once(trained, str(tmp_path / "export"))
        exported = ExportedModel(path)
        variables = exported.load_variables(target=state.export_variables())
        leaves_a = jax.tree_util.tree_leaves(variables)
        leaves_b = jax.tree_util.tree_leaves(state.export_variables())
        assert len(leaves_a) == len(leaves_b)
        for a, b in zip(leaves_a, leaves_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_temp_dirs_invisible_to_pollers(self, trained, tmp_path):
        root = str(tmp_path / "export")
        path = _export_once(trained, root)
        os.makedirs(os.path.join(root, "temp-99999999999"))
        os.makedirs(os.path.join(root, "99999999998"))  # no metadata: partial
        assert latest_export_dir(root) == path


class TestTfExampleInterface:
    def test_parse_fn_matches_numpy_interface(self, trained, tmp_path):
        from tensor2robot_tpu.data.encoder import encode_example

        compiled, state = trained
        generator = DefaultExportGenerator()
        generator.set_specification_from_model(compiled.model)
        spec = generator.serving_input_spec()
        parse_fn = generator.create_tf_example_parse_fn()
        x = np.random.RandomState(1).uniform(-1, 1, (2, 3)).astype(np.float32)
        serialized = [encode_example(spec, {"x": x[i]}) for i in range(2)]
        parsed = parse_fn(serialized)
        np.testing.assert_allclose(parsed["x"], x, rtol=1e-6)

    def test_warmup_requests_written_and_parseable(self, trained, tmp_path):
        from tensor2robot_tpu.data.tfrecord import read_tfrecords

        compiled, _ = trained
        generator = DefaultExportGenerator()
        generator.set_specification_from_model(compiled.model)
        path = generator.create_warmup_requests_numpy(
            batch_sizes=(1, 2), export_dir=str(tmp_path)
        )
        records = list(read_tfrecords(path))
        assert len(records) == 3
        parse_fn = generator.create_tf_example_parse_fn()
        parsed = parse_fn(records)
        assert parsed["x"].shape == (3, 3)


class TestExporters:
    def test_latest_exporter_exports_every_eval(self, trained, tmp_path):
        compiled, state = trained
        exporter = LatestExporter(name="latest", exports_to_keep=2,
                                  serialize_stablehlo=False)
        model_dir = str(tmp_path)
        for step in (1, 2, 3):
            exporter.maybe_export(
                step=step, state=state, eval_metrics={"loss": 1.0},
                compiled=compiled, model_dir=model_dir,
            )
        root = exporter.export_root(model_dir)
        dirs = list_export_dirs(root)
        assert len(dirs) == 2  # GC kept the newest two

    def test_best_exporter_gates_on_metric(self, trained, tmp_path):
        compiled, state = trained
        exporter = BestExporter(
            name="best", compare_fn=create_valid_result_smaller("loss"),
            serialize_stablehlo=False,
        )
        model_dir = str(tmp_path)
        p1 = exporter.maybe_export(step=1, state=state,
                                   eval_metrics={"loss": 1.0},
                                   compiled=compiled, model_dir=model_dir)
        p2 = exporter.maybe_export(step=2, state=state,
                                   eval_metrics={"loss": 2.0},
                                   compiled=compiled, model_dir=model_dir)
        p3 = exporter.maybe_export(step=3, state=state,
                                   eval_metrics={"loss": 0.5},
                                   compiled=compiled, model_dir=model_dir)
        assert p1 is not None and p2 is None and p3 is not None

    def test_best_exporter_persists_gate_across_instances(self, trained, tmp_path):
        compiled, state = trained
        model_dir = str(tmp_path)
        make = lambda: BestExporter(  # noqa: E731
            name="best", compare_fn=create_valid_result_smaller("loss"),
            serialize_stablehlo=False,
        )
        assert make().maybe_export(step=1, state=state,
                                   eval_metrics={"loss": 1.0},
                                   compiled=compiled, model_dir=model_dir)
        # Fresh instance (resume): worse metric must still be rejected.
        assert make().maybe_export(step=2, state=state,
                                   eval_metrics={"loss": 1.5},
                                   compiled=compiled, model_dir=model_dir) is None

    def test_compare_fns(self):
        smaller = create_valid_result_smaller("m")
        larger = create_valid_result_larger("m")
        assert smaller(None, {"m": 1.0})
        assert smaller({"m": 1.0}, {"m": 0.5})
        assert not smaller({"m": 1.0}, {"m": 1.0})
        assert larger({"m": 1.0}, {"m": 2.0})
        assert not larger({"m": 1.0}, {"m": 0.5})
        assert not smaller({"m": 1.0}, {})

    def test_create_default_exporters(self, trained):
        compiled, _ = trained
        exporters = create_default_exporters(compiled.model)
        names = [e.name for e in exporters]
        assert names == ["latest", "best"]

    def test_version_gc(self, tmp_path):
        import json

        root = str(tmp_path)
        for ts in (100, 200, 300, 400):
            d = os.path.join(root, str(ts))
            os.makedirs(d)
            with open(os.path.join(d, "t2r_metadata.json"), "w") as f:
                json.dump({}, f)
            open(os.path.join(d, "variables.msgpack"), "wb").close()
        removed = DirectoryVersionGC(keep=2).collect(root)
        assert [os.path.basename(r) for r in removed] == ["100", "200"]
        assert [os.path.basename(d) for d in list_export_dirs(root)] == ["300", "400"]
