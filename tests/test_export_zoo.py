"""StableHLO export is a HARD guarantee for the research model zoo.

Every research model must export a loadable StableHLO artifact whose
outputs numerically match the in-process predict path — a regression that
silently falls back to the model-code path fails here loudly (VERDICT r1
weak #6; reference serving-receiver coverage in utils/train_eval_test.py
compared numpy vs tf_example interfaces the same way).
"""

import jax
import numpy as np
import pytest

from tensor2robot_tpu.export import (
    DefaultExportGenerator,
    ExportedModel,
    save_exported_model,
)
from tensor2robot_tpu.specs import make_random_numpy
from tensor2robot_tpu.train.train_eval import CompiledModel, maybe_wrap_for_tpu
from tensor2robot_tpu.utils.mocks import MockT2RModel


def _mock():
    return MockT2RModel(device_type="cpu")


def _qtopt():
    from tensor2robot_tpu.research.qtopt.t2r_models import (
        Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    )

    return Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
        device_type="cpu", image_size=(96, 96), num_convs=(2, 2, 1)
    )


def _qtopt_tpu_bf16():
    from tensor2robot_tpu.research.qtopt.t2r_models import (
        Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    )

    return Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
        device_type="tpu", image_size=(96, 96), num_convs=(2, 2, 1)
    )


def _grasp2vec():
    from tensor2robot_tpu.research.grasp2vec import grasp2vec_model

    return grasp2vec_model.Grasp2VecModel(
        scene_size=(32, 32), goal_size=(32, 32), resnet_size=18,
        device_type="cpu",
    )


def _vrgripper():
    from tensor2robot_tpu.research import vrgripper

    return vrgripper.VRGripperRegressionModel(
        episode_length=4, image_size=(32, 32), device_type="cpu"
    )


def _pose_env_regression():
    from tensor2robot_tpu.research import pose_env

    return pose_env.PoseEnvRegressionModel(device_type="cpu")


def _pose_env_mc():
    from tensor2robot_tpu.research import pose_env

    return pose_env.PoseEnvContinuousMCModel(device_type="cpu")


def _transformer_bc():
    from tensor2robot_tpu.models.transformer_models import TransformerBCModel

    return TransformerBCModel(
        action_size=3, episode_length=4, image_size=(16, 16),
        use_flash=False, device_type="cpu",
    )


MODEL_FACTORIES = {
    "mock": _mock,
    "qtopt": _qtopt,
    "qtopt_tpu_bf16": _qtopt_tpu_bf16,
    "grasp2vec": _grasp2vec,
    "vrgripper_regression": _vrgripper,
    "pose_env_regression": _pose_env_regression,
    "pose_env_mc": _pose_env_mc,
    "transformer_bc": _transformer_bc,
}


def _trained_export_parts(name):
    """(compiled, generator, variables) for one zoo model — the shared
    setup of the export-guarantee tests."""
    model = maybe_wrap_for_tpu(MODEL_FACTORIES[name]())
    compiled = CompiledModel(model, donate_state=False)
    train_features = make_random_numpy(
        model.preprocessor.get_in_feature_specification("train"),
        batch_size=2,
        seed=0,
    )
    train_labels = make_random_numpy(
        model.preprocessor.get_in_label_specification("train"),
        batch_size=2,
        seed=1,
    )
    state = compiled.init_state(
        jax.random.PRNGKey(0),
        {"features": train_features, "labels": train_labels},
    )
    generator = DefaultExportGenerator()
    generator.set_specification_from_model(model)
    return compiled, generator, state.export_variables()


# grasp2vec is the costliest zoo entry (~19s of conv-tower compiles on
# 1 cpu) and fp32 qtopt (~11s) duplicates the tower its bf16 twin
# compiles anyway: both ride the slow slice; the remaining six entries
# keep the hard guarantee fast for every distinct architecture.
_SLOW_ZOO = ("grasp2vec", "qtopt")
@pytest.mark.parametrize(
    "name",
    [
        pytest.param(n, marks=pytest.mark.slow) if n in _SLOW_ZOO else n
        for n in sorted(MODEL_FACTORIES)
    ],
)
def test_zoo_stablehlo_export_is_hard_guarantee(name, tmp_path):
    compiled, generator, variables = _trained_export_parts(name)
    serving_fn = generator.create_serving_fn(compiled, variables)
    example_features = generator.create_example_features()

    path = save_exported_model(
        str(tmp_path / "export"),
        variables=variables,
        feature_spec=generator.serving_input_spec(),
        label_spec=generator.label_spec,
        global_step=0,
        predict_fn=serving_fn,
        example_features=example_features,
        serialize_stablehlo=True,
    )
    exported = ExportedModel(path)
    # THE guarantee: no silent fallback to the model-code path.
    assert exported.metadata["stablehlo"] is True, exported.metadata.get(
        "stablehlo_error"
    )
    assert exported.has_stablehlo

    # Reload + numeric match vs the in-process predict path.
    request = dict(
        make_random_numpy(
            generator.serving_input_spec(), batch_size=2, seed=7
        ).items()
    )
    served = exported.predict(request)
    direct = {
        key: np.asarray(value)
        for key, value in serving_fn(request).items()
    }
    assert sorted(served) == sorted(direct)
    for key in direct:
        np.testing.assert_allclose(
            np.asarray(served[key], np.float32),
            np.asarray(direct[key], np.float32),
            rtol=1e-4,
            atol=1e-5,
            err_msg=f"{name}:{key}",
        )


def test_flagship_quantized_export_same_guarantee(tmp_path):
    """The int8 weights-as-args format holds the zoo guarantee on the
    flagship too: StableHLO present, serve within weight-rounding error
    of the f32 path."""
    compiled, generator, variables = _trained_export_parts("qtopt")
    serving_fn_f32 = generator.create_serving_fn(compiled, variables)
    serving_fn_q = generator.create_serving_fn(
        compiled, variables, quantize_weights=True
    )
    path = save_exported_model(
        str(tmp_path / "export_q"),
        variables=variables,
        feature_spec=generator.serving_input_spec(),
        global_step=0,
        predict_fn=serving_fn_q,
        example_features=generator.create_example_features(),
        quantize_weights=True,
    )
    exported = ExportedModel(path)
    assert exported.metadata["stablehlo"] is True, exported.metadata.get(
        "stablehlo_error"
    )
    assert exported.metadata["stablehlo_weights_in_args"] is True
    request = dict(
        make_random_numpy(
            generator.serving_input_spec(), batch_size=2, seed=7
        ).items()
    )
    served = exported.predict(request)
    direct = {
        key: np.asarray(value)
        for key, value in serving_fn_f32(request).items()
    }
    assert sorted(served) == sorted(direct)
    for key in direct:
        np.testing.assert_allclose(
            np.asarray(served[key], np.float32),
            np.asarray(direct[key], np.float32),
            rtol=0.05,
            atol=0.05,
            err_msg=key,
        )
