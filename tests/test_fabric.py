"""serving/fabric.py + serving/pool.py: the cross-host serving fabric.

Pins the round-21 fabric contract at every layer it claims: replicas
as separate process groups speaking the CRC-framed socket wire
(net/frames.py — the SAME frame contract the replay transport ships),
published-address discovery with incarnation-stamped re-resolution
after respawn, zone-aware dispatch with cross-zone hedging/failover
(every counter typed, every future resolves), the content-addressed
store served over the wire with re-hash-on-receipt, and per-host AOT
key resolution that records a typed row instead of silently loading a
transplanted executable. The corpus corruption family drives the
serving wire exactly as it drives replay's — a corrupt frame tears the
connection whole, never a partial decode.

Multi-process legs spawn the jax-free mock backend (process spawns,
not XLA compiles); zone-dispatch logic is ALSO pinned in-process
against stub zones so tier-1 covers the routing brain without a single
fork. Long partition/heal soaks ride @slow.
"""

import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from tensor2robot_tpu.analysis import corpus
from tensor2robot_tpu.export import aot as aot_lib
from tensor2robot_tpu.export.artifact_store import (
    ArtifactCorrupt,
    ArtifactStore,
)
from tensor2robot_tpu.net import frames
from tensor2robot_tpu.serving import (
    FleetRouter,
    ReplicaSpec,
    RequestAbandoned,
    StoreServer,
    ZoneRouter,
    mirror_policy,
    host_aot_report,
    mock_server_factory,
)
from tensor2robot_tpu.serving.pool import ReplicaLink, replica_scope
from tensor2robot_tpu.serving.router import FleetError, RouterFuture
from tensor2robot_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _lock_sanitizer_armed(locksmith_sanitizer):
    """Every run of this chaos suite doubles as a deadlock hunt: the
    lock sanitizer (testing/locksmith.py) is armed for each test and
    teardown fails on any observed lock-order cycle or hold-budget
    violation (fixture: tests/conftest.py)."""
    yield


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Router-side chaos plans (net_send partitions) are configured
    in-process here; never leak one into the next test."""
    chaos.configure(None)
    yield
    chaos.configure(None)


def _features(n=4, value=1.0):
    return {"x": np.full((n,), value, np.float32)}


def _spec(service_ms=1.0, version=1, scope=None):
    return ReplicaSpec(
        factory=mock_server_factory,
        factory_kwargs={"service_ms": service_ms, "version": version},
        scope=scope,
    )


def _wait(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _socket_router(fabric_root, num=2, zone=None, **kwargs):
    kwargs.setdefault("probe_interval_ms", 50.0)
    kwargs.setdefault("backoff_ms", 5.0)
    router = FleetRouter(
        _spec(), num,
        transport_mode="socket", fabric_root=str(fabric_root),
        zone=zone, **kwargs,
    )
    return router.start(timeout_s=90.0)


def _wait_all_up(router):
    assert _wait(
        lambda: all(s == "up" for s in router.replica_states())
    ), f"fleet never fully up: {router.replica_states()}"


# -- discovery: published addresses + incarnations ----------------------------


class TestDiscovery:
    def test_unpublished_root_reads_as_absent(self, tmp_path):
        assert frames.read_address_info(str(tmp_path)) is None
        assert frames.read_address(str(tmp_path)) is None

    def test_publish_roundtrip_with_incarnation(self, tmp_path):
        frames.publish_address(str(tmp_path), 12345, incarnation=3)
        info = frames.read_address_info(str(tmp_path))
        assert info["port"] == 12345
        assert info["incarnation"] == 3
        assert info["pid"] == os.getpid()
        host, port = frames.read_address(str(tmp_path))
        assert port == 12345

    def test_stale_incarnation_is_refused(self, tmp_path):
        """A link armed for incarnation N never connects to the N-1
        address file — the respawned replica's publish is the ONLY
        thing that can satisfy it (no split-brain reconnect to a
        half-dead predecessor)."""
        frames.publish_address(str(tmp_path), 12345, incarnation=1)
        link = ReplicaLink(
            str(tmp_path), "r0", lambda m: None, min_incarnation=2,
            connect_timeout_s=0.2,
        )
        try:
            with pytest.raises(frames.TransportError, match="incarnation"):
                link.put(("hello",))
        finally:
            link.close()

    def test_scope_naming_is_chaos_grammar_safe(self):
        scope = replica_scope(3, _spec(), zone="1")
        assert scope == "z1.r3"
        assert not any(ch in scope for ch in ":+;/")
        assert replica_scope(0, _spec(scope="custom"), zone="1") == "custom"


# -- the serving wire: every corpus corruption is typed, never partial --------


@pytest.fixture
def store_server(tmp_path):
    store = ArtifactStore(str(tmp_path / "store"))
    server = StoreServer(store, root=str(tmp_path / "serve")).start()
    yield server
    server.stop()


def _raw_conn(server):
    sock = socket.create_connection(("127.0.0.1", server.port), timeout=5.0)
    sock.settimeout(5.0)
    return sock


def _request(server, message):
    sock = _raw_conn(server)
    try:
        frames.write_frame(sock, message)
        return frames.read_frame(sock, deadline=time.monotonic() + 5)
    finally:
        sock.close()


class TestServingWireTyped:
    def test_good_request_roundtrip(self, store_server):
        assert _request(store_server, ("list", 1)) == (1, "ok", [])

    @pytest.mark.parametrize("name", sorted(
        corpus.corrupt_frame_variants(
            frames.encode_frame(("manifest", 7, "some-policy-id" * 8))
        )
    ))
    def test_corpus_variant_tears_connection_never_partial(
        self, store_server, name
    ):
        """Every corruption family from the PR 3 generator, fired at
        the SERVING wire: the server tears the connection down whole
        (no reply bytes, no partial decode reaching the handler as a
        garbled request) and keeps serving the next clean connection."""
        frame = frames.encode_frame(("manifest", 7, "some-policy-id" * 8))
        variant = corpus.corrupt_frame_variants(frame)[name]
        sock = _raw_conn(store_server)
        try:
            try:
                sock.sendall(variant)
                sock.shutdown(socket.SHUT_WR)  # EOF: no resync possible
            except OSError:
                pass  # server already tore the connection down — good
            leaked = b""
            try:
                while True:
                    chunk = sock.recv(4096)
                    if not chunk:
                        break
                    leaked += chunk
            except socket.timeout:
                pytest.fail("server neither replied nor closed")
            except OSError:
                pass  # reset mid-read: the tear, observed harder
            if leaked:
                # The only legal bytes back are ONE whole, valid error
                # reply to a still-parseable frame (a payload flip the
                # CRC happens to pass — impossible by construction) —
                # never a partial frame.
                a, b = socket.socketpair()
                try:
                    a.sendall(leaked)
                    a.close()
                    reply = frames.read_frame(
                        b, deadline=time.monotonic() + 2
                    )
                    assert reply[1] == "error"
                finally:
                    b.close()
        finally:
            sock.close()
        # The server survives the torn connection: clean requests work.
        assert _request(store_server, ("list", 2)) == (2, "ok", [])

    def test_unknown_op_is_typed_error_reply(self, store_server):
        reply = _request(store_server, ("launch", 9, "nukes"))
        assert reply[0] == 9 and reply[1] == "error"

    def test_missing_policy_is_typed_error_reply(self, store_server):
        reply = _request(store_server, ("manifest", 3, "absent"))
        assert reply[1] == "error"
        assert "PolicyNotFound" in reply[2]


# -- zone dispatch brain, in-process (tier-1 twin of the fleet legs) ----------


class _StubZone:
    """Duck-type of FleetRouter's submit/load/snapshot/swap surface:
    resolves futures per a scripted behavior, so the zone-dispatch
    logic is pinned without one fork."""

    def __init__(self, name, latency_s=0.0, util=0.0, up=1,
                 submit_error=None, result_error=None, swap_fail=False):
        self.name = name
        self.latency_s = latency_s
        self.util = util
        self.up = up
        self.submit_error = submit_error
        self.result_error = result_error
        self.swap_fail = swap_fail
        self.submits = 0
        self.swapped = 0
        self.stopped = False

    def submit(self, features, deadline_ms=None, policy_id=None):
        self.submits += 1
        if self.submit_error is not None:
            raise self.submit_error
        future = RouterFuture(self.submits)

        def _resolve():
            if self.result_error is not None:
                future._set(None, self.result_error)
            else:
                future._set({"zone": self.name, "policy": policy_id}, None)

        if self.latency_s > 0:
            timer = threading.Timer(self.latency_s, _resolve)
            timer.daemon = True
            timer.start()
        else:
            _resolve()  # already-resolved before add_done_callback
        return future

    def load(self):
        return {
            "replicas_up": self.up, "replicas_pending": 0,
            "replicas_draining": 0, "inflight": 0, "capacity": 8,
            "utilization": self.util, "shed_saturated": 0,
        }

    def snapshot(self):
        return {"replicas": [
            {"index": 0, "state": "up" if self.up else "dead"}
        ]}

    def rolling_swap(self, swap_timeout_s=60.0, policy_id=None):
        self.swapped += 1
        return {"failed": "0" if self.swap_fail else None}

    def stop(self, timeout_s=10.0):
        self.stopped = True


class TestZoneDispatch:
    def test_least_loaded_zone_wins(self):
        z0 = _StubZone("z0", util=0.9)
        z1 = _StubZone("z1", util=0.1)
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0) as zr:
            for _ in range(6):
                out = zr.call(_features(), deadline_ms=5000)
                assert out["zone"] == "z1"
            counters = zr.snapshot()["counters"]
            assert counters["zone_dispatch_z1"] == 6
            assert counters.get("zone_dispatch_z0", 0) == 0

    def test_sync_refusal_fails_over_typed(self):
        z0 = _StubZone("z0", submit_error=FleetError("zone z0 is down"))
        z1 = _StubZone("z1", util=0.9)
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0) as zr:
            out = zr.call(_features(), deadline_ms=5000)
            assert out["zone"] == "z1"
            counters = zr.snapshot()["counters"]
            assert counters["zone_attempt_failed_z0"] >= 1
            assert counters["zone_win_z1"] == 1

    def test_async_failure_retries_onto_different_zone(self):
        z0 = _StubZone(
            "z0", latency_s=0.05,
            result_error=RequestAbandoned("replica died", reason="crash"),
        )
        z1 = _StubZone("z1", util=0.9)
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0) as zr:
            out = zr.call(_features(), deadline_ms=5000)
            assert out["zone"] == "z1"
            counters = zr.snapshot()["counters"]
            assert counters["zone_retries"] == 1
            assert counters["zone_attempt_failed_z0"] == 1
            assert counters["completed"] == 1

    def test_hedge_lands_in_different_zone_and_first_wins(self):
        z0 = _StubZone("z0", latency_s=1.5, util=0.0)
        z1 = _StubZone("z1", latency_s=0.01, util=0.4)
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=50) as zr:
            out = zr.call(_features(), deadline_ms=10000)
            assert out["zone"] == "z1"  # the cross-zone hedge won
            counters = zr.snapshot()["counters"]
            assert counters["zone_hedges"] == 1
            assert counters["zone_hedge_wins"] == 1
            assert counters["zone_dispatch_z0"] == 1
            assert counters["zone_dispatch_z1"] == 1

    def test_every_zone_refusing_is_typed(self):
        z0 = _StubZone("z0", submit_error=FleetError("down"))
        z1 = _StubZone("z1", submit_error=FleetError("also down"))
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0) as zr:
            with pytest.raises(FleetError):
                zr.submit(_features(), deadline_ms=1000)

    def test_exhausted_retries_resolve_with_last_typed_error(self):
        crash = RequestAbandoned("replica died", reason="crash")
        z0 = _StubZone("z0", latency_s=0.02, result_error=crash)
        z1 = _StubZone("z1", latency_s=0.02, result_error=crash)
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0,
                        zone_retries=1) as zr:
            future = zr.submit(_features(), deadline_ms=5000)
            with pytest.raises(RequestAbandoned, match="replica died"):
                future.result(10)
            counters = zr.snapshot()["counters"]
            assert counters["failed"] == 1

    def test_load_aggregates_and_details_zones(self):
        z0 = _StubZone("z0", up=2, util=0.5)
        z1 = _StubZone("z1", up=1, util=0.25)
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0) as zr:
            load = zr.load()
            assert load["replicas_up"] == 3
            assert load["capacity"] == 16
            assert set(load["zones"]) == {"z0", "z1"}

    def test_snapshot_flattens_replicas_with_zone_labels(self):
        with ZoneRouter(
            {"z0": _StubZone("z0"), "z1": _StubZone("z1")}, hedge_ms=0
        ) as zr:
            snap = zr.snapshot()
            assert set(snap["zones"]) == {"z0", "z1"}
            assert [r["zone"] for r in snap["replicas"]] == ["z0", "z1"]
            assert snap["policy"]["zones"] == ["z0", "z1"]

    def test_rolling_swap_aborts_roll_on_zone_failure(self):
        z0 = _StubZone("z0", swap_fail=True)
        z1 = _StubZone("z1")
        with ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0) as zr:
            result = zr.rolling_swap()
            assert result["failed"] == "z0:0"
            assert z0.swapped == 1
            assert z1.swapped == 0  # remaining zones keep old version

    def test_stop_stops_every_zone_and_refuses_submits(self):
        z0, z1 = _StubZone("z0"), _StubZone("z1")
        zr = ZoneRouter({"z0": z0, "z1": z1}, hedge_ms=0)
        zr.stop()
        assert z0.stopped and z1.stopped
        from tensor2robot_tpu.serving.router import RouterClosed

        with pytest.raises(RouterClosed):
            zr.submit(_features())


# -- socket fabric: real replica processes over the frame wire ----------------


class TestSocketFabric:
    def test_round_trip_across_separate_process_groups(self, tmp_path):
        with _socket_router(tmp_path, num=2) as router:
            _wait_all_up(router)
            for value in (1.0, 2.0):
                response = router.call(
                    _features(value=value), deadline_ms=20000
                )
                assert response.outputs["y"] == pytest.approx(4 * value)
            snap = router.snapshot()
            assert snap["transport"] == "socket"
            pids = {r["host"]["pid"] for r in snap["replicas"]}
            assert len(pids) == 2 and os.getpid() not in pids
            # Separate process GROUPS: each replica leads its own
            # session, so a signal to the router's group never fans
            # out to the fleet (and vice versa).
            own_pgid = os.getpgid(0)
            for pid in pids:
                assert os.getpgid(pid) != own_pgid
            assert len({os.getpgid(p) for p in pids}) == 2

    def test_respawn_reresolves_published_address(self, tmp_path):
        """SIGKILL a replica: the monitor respawns it, the respawn
        publishes a NEW incarnation-stamped address, and the link
        re-resolves it — requests flow again with the new pid."""
        with _socket_router(tmp_path, num=2) as router:
            _wait_all_up(router)
            old_pid = router.snapshot()["replicas"][0]["host"]["pid"]
            os.kill(old_pid, signal.SIGKILL)
            assert _wait(
                lambda: router.snapshot()["counters"].get("respawns", 0)
                >= 1,
                timeout=60,
            ), "replica never respawned"
            _wait_all_up(router)

            def _new_pid():
                host = router.snapshot()["replicas"][0].get("host")
                return host and host["pid"] != old_pid

            assert _wait(_new_pid, timeout=60), "pid never re-resolved"
            response = router.call(_features(), deadline_ms=20000)
            assert response.outputs["y"] == pytest.approx(4.0)

    def test_lost_hello_still_admits_replica(self, tmp_path):
        """The ("hello",)->("started",...) handshake rides the same
        lossy wire as everything else. Drop the FIRST router->replica
        frame (the fresh link's hello): the replica never hears it, so
        it never posts "started" — but it answers the health probes
        that follow, and every answer refreshes last_health_time, so
        the boot-timeout backstop cannot fire either. The router must
        admit on the health reply (it carries the same evidence:
        addresses are only published after the factory succeeded)
        instead of wedging the replica in `starting` forever."""
        chaos.configure("net_send:1:drop")
        with _socket_router(tmp_path, num=1) as router:
            _wait_all_up(router)
            response = router.call(_features(), deadline_ms=20000)
            assert response.outputs["y"] == pytest.approx(4.0)

    def test_local_transport_is_byte_compatible(self, tmp_path, monkeypatch):
        """T2R_FLEET_TRANSPORT=local is the tier-1 default and rides
        the pre-fabric mp path unchanged — and the socket path returns
        BITWISE the same outputs for the same request."""
        monkeypatch.setenv("T2R_FLEET_TRANSPORT", "local")
        router = FleetRouter(
            _spec(), 1, probe_interval_ms=50.0, backoff_ms=5.0
        ).start(timeout_s=90.0)
        try:
            assert router._pool is None  # mp transport, not a socket pool
            assert router.snapshot()["transport"] == "local"
            local_out = router.call(
                _features(value=3.0), deadline_ms=20000
            ).outputs["y"]
        finally:
            router.stop()
        monkeypatch.delenv("T2R_FLEET_TRANSPORT")
        with _socket_router(tmp_path, num=1) as router:
            _wait_all_up(router)
            socket_out = router.call(
                _features(value=3.0), deadline_ms=20000
            ).outputs["y"]
        assert (
            np.asarray(local_out).tobytes()
            == np.asarray(socket_out).tobytes()
        )


# -- partition -> cross-zone hedge -> heal ------------------------------------


def _two_zone_fleet(tmp_path, hedge_ms=100):
    routers = {}
    for zone in ("0", "1"):
        routers[f"z{zone}"] = _socket_router(
            tmp_path / f"z{zone}", num=1, zone=zone,
        )
    for router in routers.values():
        _wait_all_up(router)
    return ZoneRouter(routers, hedge_ms=hedge_ms)


@pytest.mark.slow
class TestPartitionHedgeHeal:
    def test_partition_hedges_cross_zone_then_heals(self, tmp_path):
        with _two_zone_fleet(tmp_path) as zr:
            # Sanity: both zones serve.
            assert _wait(
                lambda: (
                    zr.call(_features(), deadline_ms=20000) and
                    zr.snapshot()["counters"].get("zone_win_z0", 0) > 0
                    and zr.snapshot()["counters"].get("zone_win_z1", 0)
                    > 0
                ),
                timeout=60,
            ), zr.snapshot()["counters"]
            before = zr.snapshot()["counters"]
            # Partition z1's only replica: every router->z1 frame dies
            # on the wire from occurrence 1, symmetric, until healed.
            chaos.configure("net_send:1:partition:z1.r0")
            lost = 0
            for _ in range(8):
                try:
                    out = zr.call(_features(), deadline_ms=4000)
                    assert out.outputs["y"] == pytest.approx(4.0)
                except Exception:
                    lost += 1
            counters = zr.snapshot()["counters"]
            # Zero lost: z0 absorbs everything the partition costs z1,
            # via hedge or retry — and each absorbed request is typed
            # in the zone counters, never silent.
            assert lost == 0, f"{lost} requests lost: {counters}"
            z0_wins = counters.get("zone_win_z0", 0) - before.get(
                "zone_win_z0", 0
            )
            assert z0_wins == 8
            assert (
                counters.get("zone_hedge_wins", 0)
                + counters.get("zone_retries", 0)
                + counters.get("zone_attempt_failed_z1", 0)
            ) >= 1
            # Heal: the plan clears; z1's replica (respawned or merely
            # re-linked) re-resolves by published address and serves.
            chaos.configure(None)

            def _z1_serves():
                base = zr.snapshot()["counters"].get("zone_win_z1", 0)
                for _ in range(4):
                    try:
                        zr.call(_features(), deadline_ms=4000)
                    except Exception:
                        return False
                return (
                    zr.snapshot()["counters"].get("zone_win_z1", 0)
                    > base
                )

            assert _wait(_z1_serves, timeout=90), (
                f"z1 never healed: {zr.snapshot()['counters']}"
            )


# -- per-host AOT key resolution ----------------------------------------------


def _forge_aot(export_root, name, header, payload=b"never-unpickled"):
    aot_dir = os.path.join(export_root, aot_lib.AOT_DIR)
    os.makedirs(aot_dir, exist_ok=True)
    with open(os.path.join(aot_dir, name), "wb") as f:
        f.write(aot_lib._pack(header, payload))


_HOST_TOPOLOGY = {"platform": "cpu", "device_kind": "cpu", "device_count": 1}


def _header(**overrides):
    import jax

    header = {
        "format_version": aot_lib.AOT_FORMAT_VERSION,
        "jax": jax.__version__,
        "topology": dict(_HOST_TOPOLOGY),
        "fingerprint": "fp-1",
        "regime": "serve",
        "bucket": 8,
    }
    header.update(overrides)
    return header


class TestHostAOTKeys:
    def test_statuses_and_counts_per_host_key(self, tmp_path):
        root = str(tmp_path)
        _forge_aot(root, "exec_serve_b8.bin", _header())
        _forge_aot(
            root, "exec_serve_b16.bin",
            _header(topology={"platform": "tpu", "device_kind": "v4",
                              "device_count": 8}),
        )
        _forge_aot(root, "exec_serve_b32.bin", _header(jax="0.0.0-else"))
        _forge_aot(root, "exec_serve_b64.bin", _header(format_version=99))
        aot_dir = os.path.join(root, aot_lib.AOT_DIR)
        with open(os.path.join(aot_dir, "exec_serve_b4.bin"), "wb") as f:
            f.write(b"garbage, not an envelope")
        report = host_aot_report(root, topology=_HOST_TOPOLOGY)
        statuses = {
            name: row["status"] for name, row in report["files"].items()
        }
        assert statuses == {
            "exec_serve_b8.bin": "aot",
            "exec_serve_b16.bin": "topology",
            "exec_serve_b32.bin": "jax_version",
            "exec_serve_b64.bin": "key",
            "exec_serve_b4.bin": "corrupt",
        }
        assert report["counts"] == {
            "aot": 1, "topology": 1, "jax_version": 1, "key": 1,
            "corrupt": 1,
        }
        # One mismatched executable anywhere -> the host is NOT all-aot:
        # a transplanted topology is a typed fallback row, never a
        # silent load (the payload is junk and was never unpickled).
        assert report["all_aot"] is False

    def test_matching_host_is_all_aot(self, tmp_path):
        root = str(tmp_path)
        _forge_aot(root, "exec_serve_b8.bin", _header())
        _forge_aot(root, "exec_serve_b16.bin", _header())
        report = host_aot_report(root, topology=_HOST_TOPOLOGY)
        assert report["all_aot"] is True
        assert report["counts"]["aot"] == 2

    def test_missing_aot_dir_is_empty_not_an_error(self, tmp_path):
        report = host_aot_report(str(tmp_path), topology=_HOST_TOPOLOGY)
        assert report["all_aot"] is False
        assert report["files"] == {}
        assert sum(report["counts"].values()) == 0


# -- cross-host artifact mirroring --------------------------------------------


def _dense_publish(store, tmp_path, policy_id, weights=b"w" * 256):
    export_dir = tmp_path / f"export-{policy_id}"
    os.makedirs(export_dir / "stablehlo", exist_ok=True)
    (export_dir / "stablehlo" / "forward.mlir").write_bytes(
        b"stablehlo-program " * 64
    )
    (export_dir / "t2r_metadata.json").write_text("{}")
    (export_dir / "variables.msgpack").write_bytes(weights)
    return store.put(str(export_dir), policy_id)


class TestStoreMirror:
    def test_mirror_is_bitwise_and_idempotent(self, tmp_path):
        src = ArtifactStore(str(tmp_path / "src"))
        _dense_publish(src, tmp_path, "pi", weights=b"weights-pi" * 40)
        server = StoreServer(src, root=str(tmp_path / "serve")).start()
        try:
            dest = ArtifactStore(str(tmp_path / "dest"))
            stats = mirror_policy(server.root, "pi", dest)
            assert stats["policies"] == ["pi"]
            assert stats["blobs_fetched"] > 0
            assert dest.load_weights("pi") == src.load_weights("pi")
            again = mirror_policy(server.root, "pi", dest)
            # Content-addressed dedup: the re-mirror moves zero bytes.
            assert again["blobs_fetched"] == 0
            assert again["bytes_fetched"] == 0
            assert again["blobs_reused"] >= stats["blobs_fetched"]
        finally:
            server.stop()

    def test_corrupt_blob_is_refused_nothing_lands(self, tmp_path):
        src = ArtifactStore(str(tmp_path / "src"))
        manifest = _dense_publish(src, tmp_path, "pi")
        sha = manifest["payload"]["blob"]
        blob_path = os.path.join(src.root, "blobs", f"sha256-{sha}")
        with open(blob_path, "wb") as f:
            f.write(b"rotted on the source disk")
        server = StoreServer(src, root=str(tmp_path / "serve")).start()
        try:
            dest = ArtifactStore(str(tmp_path / "dest"))
            with pytest.raises(ArtifactCorrupt):
                mirror_policy(server.root, "pi", dest)
            # Manifests land LAST: the refused mirror left no policy.
            assert not dest.has("pi")
        finally:
            server.stop()

    def test_delta_chain_mirrors_bases_first(self, tmp_path):
        flax = pytest.importorskip("flax")
        from flax import serialization

        src = ArtifactStore(str(tmp_path / "src"))
        rng = np.random.RandomState(0)
        params = {"w": rng.standard_normal((8, 8)).astype(np.float32)}

        def _publish(policy_id, p, base=None):
            export_dir = tmp_path / f"export-{policy_id}"
            os.makedirs(export_dir / "stablehlo", exist_ok=True)
            (export_dir / "stablehlo" / "forward.mlir").write_bytes(
                b"prog " * 64
            )
            (export_dir / "t2r_metadata.json").write_text("{}")
            (export_dir / "variables.msgpack").write_bytes(
                serialization.to_bytes(p)
            )
            src.put(str(export_dir), policy_id, base_policy=base)

        _publish("base", params)
        sibling = {"w": params["w"] + 1e-4}
        _publish("sib", sibling, base="base")
        server = StoreServer(src, root=str(tmp_path / "serve")).start()
        try:
            dest = ArtifactStore(str(tmp_path / "dest"))
            stats = mirror_policy(server.root, "sib", dest)
            # Bases land before dependents; the mirrored sibling
            # reconstructs bitwise-identically on the far host.
            assert stats["policies"] == ["base", "sib"]
            assert dest.load_weights("sib") == src.load_weights("sib")
        finally:
            server.stop()
