"""Parity suite: the wire-format fast parser vs. the SpecParser oracle.

The fast path (data/wire.py) re-implements the generated parser at batch
granularity (spans + decode-into + vectorized varints); `SpecParser` stays
the semantics oracle. Every test here round-trips spec-conforming values
through `encode_example` and asserts the two parsers produce BYTE-IDENTICAL
outputs — same keys, same dtypes, same shapes, same bits — across the spec
families the framework ships (QT-Opt, transformer-BC, meta-learning) and
the corner-case features the oracle documents (varlen pad/clip, jpeg/png
decode + zero-image fallback, dataset_key zip, sequence `_length`
sidecars, bfloat16 egress, optional features).
"""

import os

import numpy as np
import pytest

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.data.encoder import encode_example, encode_examples_by_dataset
from tensor2robot_tpu.data.parser import SpecParser
from tensor2robot_tpu.data.wire import (
    DecodeCache,
    FastSpecParser,
    decode_packed_varints,
    reset_decode_cache,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    make_random_numpy,
)


def _records_for(specs, batch, seed=0):
    values = make_random_numpy(specs, batch_size=batch, seed=seed)
    rows = [
        {key: np.asarray(value[i]) for key, value in values.items()}
        for i in range(batch)
    ]
    return [encode_example(specs, row) for row in rows], rows


def assert_parity(specs, records, cache=None):
    """Both parsers on the same batch -> byte-identical structs."""
    slow = SpecParser(specs).parse_batch(records)
    fast_parser = FastSpecParser(specs)
    assert fast_parser.supported, fast_parser.unsupported_reason
    fast = fast_parser.parse_batch(records, cache=cache)
    assert set(slow.keys()) == set(fast.keys())
    for key in slow.keys():
        want = np.asarray(slow[key])
        got = np.asarray(fast[key])
        assert want.dtype == got.dtype, (key, want.dtype, got.dtype)
        assert want.shape == got.shape, (key, want.shape, got.shape)
        np.testing.assert_array_equal(
            want.view(np.uint8) if want.dtype.itemsize else want,
            got.view(np.uint8) if got.dtype.itemsize else got,
            err_msg=key,
        )
    return fast


class TestModelSpecParity:
    @pytest.mark.slow
    def test_qtopt_spec(self):
        from tensor2robot_tpu.research.qtopt.t2r_models import (
            Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
        )

        model = Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
            device_type="cpu"
        )
        specs = {
            "features": model.preprocessor.get_in_feature_specification("train"),
            "labels": model.preprocessor.get_in_label_specification("train"),
        }
        records, _ = _records_for(specs, batch=4)
        assert_parity(specs, records)

    def test_transformer_bc_spec(self):
        from tensor2robot_tpu.models.transformer_models import TransformerBCModel

        model = TransformerBCModel(
            action_size=2,
            pose_size=4,
            episode_length=4,
            image_size=(16, 16),
            use_flash=False,
            device_type="cpu",
        )
        feature_spec = model.preprocessor.get_in_feature_specification("train")
        label_spec = model.preprocessor.get_in_label_specification("train")
        specs = {"features": feature_spec, "labels": label_spec}
        values = make_random_numpy(specs, batch_size=3, seed=1)
        records = []
        for i in range(3):
            row = {k: np.asarray(v[i]) for k, v in values.items()}
            for key, value in row.items():
                spec = dict(specs["features"]).get(key.split("/", 1)[-1])
                if getattr(spec, "data_format", None):
                    row[key] = (np.clip(value, 0, 1) * 255).astype(np.uint8)
            records.append(encode_example(specs, row))
        assert_parity(specs, records)

    def test_meta_learning_metaexample_spec(self):
        from tensor2robot_tpu.meta_learning.preprocessors import (
            create_metaexample_spec,
        )
        from tensor2robot_tpu.utils.mocks import MockT2RModel

        model = MockT2RModel()
        specs = create_metaexample_spec(
            model.get_feature_specification("train"), 3, "condition"
        )
        records, _ = _records_for(specs, batch=5)
        assert_parity(specs, records)


class TestFeatureParity:
    def test_scalar_and_ranked_numerics(self):
        specs = TensorSpecStruct()
        specs["s"] = ExtendedTensorSpec(shape=(), dtype=np.float32, name="s")
        specs["v"] = ExtendedTensorSpec(shape=(7,), dtype=np.float64, name="v")
        specs["m"] = ExtendedTensorSpec(shape=(3, 4), dtype=np.int32, name="m")
        specs["b"] = ExtendedTensorSpec(shape=(2,), dtype=bool, name="b")
        specs["big"] = ExtendedTensorSpec(shape=(5,), dtype=np.int64, name="big")
        records, _ = _records_for(specs, batch=6, seed=3)
        assert_parity(specs, records)

    def test_negative_and_large_int64(self):
        """Multi-byte and 10-byte (negative) varints through the vectorized
        decoder, against the protobuf-serialized truth."""
        specs = TensorSpecStruct()
        specs["x"] = ExtendedTensorSpec(shape=(6,), dtype=np.int64, name="x")
        rows = [
            {"x": np.array([0, -1, 1, -(2**62), 2**62, 127], np.int64)},
            {"x": np.array([128, 300, -300, 2**40, -(2**40), 1], np.int64)},
        ]
        records = [encode_example(specs, row) for row in rows]
        fast = assert_parity(specs, records)
        np.testing.assert_array_equal(np.asarray(fast["x"])[0], rows[0]["x"])

    def test_bfloat16_egress_cast(self):
        import jax.numpy as jnp

        specs = TensorSpecStruct()
        specs["h"] = ExtendedTensorSpec(shape=(4,), dtype=jnp.bfloat16, name="h")
        records, _ = _records_for(specs, batch=3, seed=5)
        fast = assert_parity(specs, records)
        assert np.asarray(fast["h"]).dtype == jnp.bfloat16

    def test_varlen_pad_and_clip(self):
        specs = TensorSpecStruct()
        specs["v"] = ExtendedTensorSpec(
            shape=(5,), dtype=np.float32, name="v", varlen_default_value=-1.0
        )
        specs["n"] = ExtendedTensorSpec(
            shape=(3,), dtype=np.int64, name="n", varlen_default_value=7.0
        )
        rows = [
            {"v": np.arange(2, dtype=np.float32), "n": np.arange(9)},  # pad/clip
            {"v": np.arange(8, dtype=np.float32), "n": np.arange(1)},  # clip/pad
            {"v": np.arange(5, dtype=np.float32), "n": np.arange(3)},  # exact
        ]
        records = [encode_example(specs, row) for row in rows]
        fast = assert_parity(specs, records)
        np.testing.assert_array_equal(
            np.asarray(fast["v"])[0], [0.0, 1.0, -1.0, -1.0, -1.0]
        )
        np.testing.assert_array_equal(np.asarray(fast["n"])[1], [0, 7, 7])

    def test_sequence_lengths_and_padding(self):
        specs = TensorSpecStruct()
        specs["seq"] = ExtendedTensorSpec(
            shape=(3,), dtype=np.float32, name="seq", is_sequence=True
        )
        specs["ctx"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="ctx")
        rng = np.random.RandomState(0)
        rows = [
            {"seq": rng.randn(t, 3).astype(np.float32),
             "ctx": rng.randn(2).astype(np.float32)}
            for t in (1, 4, 2)
        ]
        records = [encode_example(specs, row) for row in rows]
        fast = assert_parity(specs, records)
        np.testing.assert_array_equal(np.asarray(fast["seq_length"]), [1, 4, 2])
        assert np.asarray(fast["seq"]).shape == (3, 4, 3)

    def test_dataset_key_zip(self):
        specs = TensorSpecStruct()
        specs["a"] = ExtendedTensorSpec(
            shape=(2,), dtype=np.float32, name="a", dataset_key="d1"
        )
        specs["b"] = ExtendedTensorSpec(
            shape=(3,), dtype=np.int64, name="b", dataset_key="d2"
        )
        values = make_random_numpy(specs, batch_size=4, seed=2)
        serialized = {"d1": [], "d2": []}
        for i in range(4):
            row = {k: np.asarray(v[i]) for k, v in values.items()}
            by_key = encode_examples_by_dataset(specs, row)
            for key, record in by_key.items():
                serialized[key].append(record)
        slow = SpecParser(specs).parse_batch(serialized)
        fast_parser = FastSpecParser(specs)
        assert fast_parser.supported
        fast = fast_parser.parse_batch(serialized)
        for key in slow.keys():
            np.testing.assert_array_equal(
                np.asarray(slow[key]), np.asarray(fast[key]), err_msg=key
            )

    def test_optional_all_absent_and_partial(self):
        specs = TensorSpecStruct()
        specs["req"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="req")
        specs["opt"] = ExtendedTensorSpec(
            shape=(2,), dtype=np.float32, name="opt", is_optional=True
        )
        with_opt = encode_example(
            specs, {"req": np.zeros(2, np.float32), "opt": np.ones(2, np.float32)}
        )
        without_opt = encode_example(specs, {"req": np.zeros(2, np.float32)})
        fast = assert_parity(specs, [without_opt, without_opt])
        assert "opt" not in fast
        assert_parity(specs, [with_opt, with_opt])
        with pytest.raises(ValueError, match="only some"):
            FastSpecParser(specs).parse_batch([with_opt, without_opt])


class TestImageParity:
    def _image_specs(self, data_format="jpeg", channels=3, dtype=np.uint8):
        specs = TensorSpecStruct()
        specs["img"] = ExtendedTensorSpec(
            shape=(24, 20, channels), dtype=dtype, name="img",
            data_format=data_format,
        )
        return specs

    def _pixel_rows(self, specs, batch, seed=0):
        rng = np.random.RandomState(seed)
        shape = tuple(specs["img"].shape)
        return [
            {"img": rng.randint(0, 256, shape, dtype=np.uint8)}
            for _ in range(batch)
        ]

    def test_jpeg_rgb(self):
        specs = self._image_specs("jpeg")
        rows = self._pixel_rows(specs, 3)
        records = [encode_example(specs, r) for r in rows]
        assert_parity(specs, records)

    def test_png_rgb_and_grayscale(self):
        """PNG (and 1-channel) decode rides the PIL path in both parsers."""
        for channels in (3, 1):
            specs = self._image_specs("png", channels=channels)
            rows = self._pixel_rows(specs, 2, seed=channels)
            records = [encode_example(specs, r) for r in rows]
            assert_parity(specs, records)

    def test_float_image_spec(self):
        """Specs may declare the DECODED dtype (e.g. f32); parity includes
        the post-decode cast."""
        specs = self._image_specs("jpeg", dtype=np.float32)
        rows = self._pixel_rows(specs, 2, seed=9)
        records = [encode_example(specs, r) for r in rows]
        assert_parity(specs, records)

    def test_empty_string_zero_image_fallback(self):
        specs = self._image_specs("jpeg")
        record = encode_example(specs, {"img": b""})
        fast = assert_parity(specs, [record])
        assert not np.asarray(fast["img"]).any()

    def test_image_stack(self):
        specs = TensorSpecStruct()
        specs["stack"] = ExtendedTensorSpec(
            shape=(3, 12, 10, 3), dtype=np.uint8, name="stack",
            data_format="png",
        )
        rng = np.random.RandomState(4)
        rows = [
            {"stack": rng.randint(0, 256, (3, 12, 10, 3), dtype=np.uint8)}
            for _ in range(2)
        ]
        records = [encode_example(specs, r) for r in rows]
        assert_parity(specs, records)

    def test_varlen_image_stack_pads_with_zero_images(self):
        specs = TensorSpecStruct()
        specs["stack"] = ExtendedTensorSpec(
            shape=(4, 12, 10, 3), dtype=np.uint8, name="stack",
            data_format="png", varlen_default_value=0.0,
        )
        rng = np.random.RandomState(5)
        rows = [
            {"stack": rng.randint(0, 256, (2, 12, 10, 3), dtype=np.uint8)},
            {"stack": rng.randint(0, 256, (6, 12, 10, 3), dtype=np.uint8)},
        ]
        records = [encode_example(specs, r) for r in rows]
        fast = assert_parity(specs, records)
        assert not np.asarray(fast["stack"])[0, 2:].any()  # zero-padded

    def test_sequence_images_with_lengths(self):
        specs = TensorSpecStruct()
        specs["cam"] = ExtendedTensorSpec(
            shape=(8, 6, 3), dtype=np.uint8, name="cam",
            data_format="png", is_sequence=True,
        )
        rng = np.random.RandomState(6)
        rows = [
            {"cam": rng.randint(0, 256, (t, 8, 6, 3), dtype=np.uint8)}
            for t in (2, 3)
        ]
        records = [encode_example(specs, r) for r in rows]
        fast = assert_parity(specs, records)
        np.testing.assert_array_equal(np.asarray(fast["cam_length"]), [2, 3])


class TestDecodeCache:
    def test_cache_hit_is_bit_identical(self):
        specs = TensorSpecStruct()
        specs["img"] = ExtendedTensorSpec(
            shape=(24, 20, 3), dtype=np.uint8, name="img", data_format="jpeg"
        )
        rng = np.random.RandomState(7)
        record = encode_example(
            specs, {"img": rng.randint(0, 256, (24, 20, 3), dtype=np.uint8)}
        )
        cache = DecodeCache(64 << 20)
        parser = FastSpecParser(specs)
        cold = parser.parse_batch([record], cache=cache)
        assert cache.misses >= 1 and cache.hits == 0
        warm = parser.parse_batch([record], cache=cache)
        assert cache.hits >= 1
        np.testing.assert_array_equal(
            np.asarray(cold["img"]), np.asarray(warm["img"])
        )
        # ... and identical to the oracle.
        slow = SpecParser(specs).parse_batch([record])
        np.testing.assert_array_equal(
            np.asarray(slow["img"]), np.asarray(warm["img"])
        )

    def test_cache_budget_evicts(self):
        cache = DecodeCache(4096)
        for i in range(8):
            cache.put("sig", bytes([i]), np.full((32, 32), i, np.uint8))
        assert cache.stats()["bytes"] <= 4096
        assert cache.stats()["entries"] <= 4

    def test_fingerprint_collision_degrades_to_miss_not_wrong_pixels(self):
        """Two encoded payloads crafted to share a fingerprint (same
        length, head, middle, tail) must never serve each other's pixels:
        the exact-verify memcmp turns the collision into a miss."""
        cache = DecodeCache(64 << 20)
        base = bytearray(np.random.RandomState(0).bytes(4096))
        other = bytearray(base)
        other[100] ^= 0xFF  # differs outside every sampled window
        a, b = bytes(base), bytes(other)
        assert DecodeCache.fingerprint("sig", a) == DecodeCache.fingerprint(
            "sig", b
        )
        img_a = np.full((4, 4), 1, np.uint8)
        cache.put("sig", a, img_a)
        assert cache.get("sig", b) is None  # collision -> miss
        np.testing.assert_array_equal(cache.get("sig", a), img_a)

    def test_cache_env_zero_disables(self, monkeypatch):
        from tensor2robot_tpu.data import wire

        monkeypatch.setenv("T2R_DECODE_CACHE_MB", "0")
        reset_decode_cache()
        assert wire.get_decode_cache() is None
        monkeypatch.setenv("T2R_DECODE_CACHE_MB", "8")
        reset_decode_cache()
        assert wire.get_decode_cache() is not None
        monkeypatch.delenv("T2R_DECODE_CACHE_MB")
        reset_decode_cache()


class TestVarintDecoder:
    def test_single_byte_fast_path(self):
        raw = np.array([0, 1, 127], np.uint8)
        np.testing.assert_array_equal(
            decode_packed_varints(raw), [0, 1, 127]
        )

    def test_multibyte_and_negative(self):
        from tensor2robot_tpu.proto import example_pb2

        values = [0, 1, 127, 128, 300, 2**32, 2**62, -1, -300, -(2**62)]
        feature = example_pb2.Feature()
        feature.int64_list.value.extend(values)
        wire_bytes = feature.int64_list.SerializeToString()
        # Strip the field-1 LEN frame (tag byte + length varint(s)).
        pos = 1
        while wire_bytes[pos] & 0x80:
            pos += 1
        raw = np.frombuffer(wire_bytes, np.uint8, offset=pos + 1)
        np.testing.assert_array_equal(decode_packed_varints(raw), values)

    def test_truncated_run_raises(self):
        from tensor2robot_tpu.data.wire import FastParseError

        with pytest.raises(FastParseError):
            decode_packed_varints(np.array([0x80], np.uint8))

    def test_empty(self):
        assert decode_packed_varints(np.empty(0, np.uint8)).size == 0


class TestFallback:
    def test_unsupported_specs_flagged_at_compile(self):
        specs = TensorSpecStruct()
        specs["raw"] = ExtendedTensorSpec(shape=(1,), dtype=np.str_, name="raw")
        parser = FastSpecParser(specs)
        assert not parser.supported
        with pytest.raises(Exception):
            parser.parse_batch([b""])

    def test_dataset_falls_back_on_garbage_record(self):
        """A record the fast path cannot scan re-parses via SpecParser,
        which raises the canonical error."""
        from tensor2robot_tpu.data.dataset import _FastParseState, _parse_chunk_impl
        from tensor2robot_tpu.data.parser import SpecParser as Oracle

        specs = TensorSpecStruct()
        specs["x"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="x")
        state = _FastParseState(specs, enabled=True)
        oracle = Oracle(specs)
        good, _ = _records_for(specs, batch=2)
        out = _parse_chunk_impl(state, oracle, good)
        assert np.asarray(out["x"]).shape == (2, 2)
        with pytest.raises(Exception):
            _parse_chunk_impl(state, oracle, [b"\xff\xff\xff"])
        assert state.parser is None or state.parser.fallbacks >= 1

    def test_fast_state_disables_after_repeated_fallbacks(self):
        from tensor2robot_tpu.data.dataset import _FastParseState, _parse_chunk_impl
        from tensor2robot_tpu.data.parser import SpecParser as Oracle

        specs = TensorSpecStruct()
        specs["x"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="x")
        state = _FastParseState(specs, enabled=True)
        oracle = Oracle(specs)
        for _ in range(_FastParseState.max_fallbacks):
            with pytest.raises(Exception):
                _parse_chunk_impl(state, oracle, [b"\x00garbage"])
        assert state.parser is None


@pytest.mark.skipif(
    t2r_flags.get_bool("T2R_SKIP_HYPOTHESIS"), reason="explicitly skipped"
)
class TestFuzzParity:
    """Hypothesis fuzz mirroring test_parser_properties, but asserting the
    two parsers against EACH OTHER (bit-exact, including bf16)."""

    def test_random_spec_structures(self):
        st = pytest.importorskip("hypothesis.strategies")
        hypothesis = pytest.importorskip("hypothesis")
        import string

        name = st.text(string.ascii_lowercase, min_size=1, max_size=5)

        @st.composite
        def leaf_specs(draw, key):
            dtype = draw(st.sampled_from([np.int64, np.float32, "bfloat16"]))
            rank = draw(st.integers(0, 3))
            shape = tuple(draw(st.integers(1, 4)) for _ in range(rank))
            return ExtendedTensorSpec(shape=shape, dtype=dtype, name=key)

        @st.composite
        def spec_structs(draw):
            keys = draw(st.lists(name, min_size=1, max_size=5, unique=True))
            struct = TensorSpecStruct()
            for key in keys:
                struct[key] = draw(leaf_specs(key))
            return struct

        @hypothesis.settings(max_examples=25, deadline=None)
        @hypothesis.given(spec_structs(), st.integers(0, 2**31 - 1))
        def run(specs, seed):
            records, _ = _records_for(specs, batch=3, seed=seed % (2**31 - 1))
            assert_parity(specs, records)

        run()
