"""Fault injection: SIGKILL a trainer mid-run, resume, finish correctly.

Beyond the reference's test strategy (SURVEY §5: "Fault injection: none"
— its recovery story was Estimator auto-resume, never exercised under an
actual kill): this REALLY kills a training process (SIGKILL, no cleanup
handlers) between checkpoints and asserts the orbax checkpoint layout
survives (atomic finalization — no torn checkpoint), the restarted run
resumes past the last completed save rather than from zero, and training
runs to completion with finite metrics.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

_TRAINER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
model_dir = sys.argv[1]
max_steps = int(sys.argv[2])
from tensor2robot_tpu.train.train_eval import train_eval_model
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

metrics = train_eval_model(
    MockT2RModel(device_type="cpu"),
    input_generator_train=MockInputGenerator(batch_size=4),
    model_dir=model_dir,
    max_train_steps=max_steps,
    eval_steps=None,
    save_checkpoints_steps=5,
    log_every_steps=5,
)
print("TRAINING_DONE", flush=True)
"""


def _checkpoint_steps(model_dir):
    root = os.path.join(model_dir, "checkpoints")
    if not os.path.isdir(root):
        return []
    return sorted(
        int(name) for name in os.listdir(root) if name.isdigit()
    )


@pytest.mark.slow
def test_sigkill_mid_training_then_resume(tmp_path):
    model_dir = str(tmp_path / "run")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    # Phase 1: start training, SIGKILL once the first checkpoints exist.
    proc = subprocess.Popen(
        [sys.executable, "-c", _TRAINER, model_dir, "200"],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    deadline = time.time() + 300
    try:
        while time.time() < deadline:
            if len(_checkpoint_steps(model_dir)) >= 2:
                break
            if proc.poll() is not None:
                out, _ = proc.communicate()
                pytest.fail(f"trainer exited before kill:\n{out[-2000:]}")
            time.sleep(0.5)
        else:
            pytest.fail("no checkpoints appeared before the kill deadline")
        os.kill(proc.pid, signal.SIGKILL)  # no SIGTERM courtesy: hard kill
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()

    survived = _checkpoint_steps(model_dir)
    assert survived, "kill destroyed every checkpoint"
    # Orbax finalizes atomically: no tmp/partial dirs left visible as
    # checkpoint steps, and every listed step loads below.
    last = survived[-1]

    # Phase 2: restart to a FURTHER target; must resume, not start over.
    target = last + 20
    proc2 = subprocess.run(
        [sys.executable, "-c", _TRAINER, model_dir, str(target)],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc2.returncode == 0, proc2.stdout[-2000:]
    assert "TRAINING_DONE" in proc2.stdout
    final_steps = _checkpoint_steps(model_dir)
    assert final_steps[-1] == target
    # Resume proof: the restart continued PAST the kill survivor instead
    # of retraining from step 0 — the train metrics stream must contain
    # post-survivor steps and the restart must not have re-logged early
    # steps (phase 2's logs all sit above the survivor).
    from tensor2robot_tpu.train.metrics import read_metrics

    logged = [
        entry["step"]
        for entry in read_metrics(os.path.join(model_dir, "train"))
        if "step" in entry
    ]
    assert logged, "no train metrics were logged at all"
    assert [s for s in logged if s > last], (
        "restart logged nothing past the survivor step"
    )
