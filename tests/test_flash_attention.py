"""Pallas flash-attention kernel: numerics vs the materialized reference.

Runs the kernel in Pallas interpreter mode on CPU (the TPU-emulation test
strategy, SURVEY §4); the same code path compiles natively on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_tile,
    reference_attention,
)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    shape = (2, 64, 4, 16)  # [B, S, H, D]
    return tuple(
        jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, qkv, causal):
        q, k, v = qkv
        ref = reference_attention(q, k, v, causal=causal)
        out = flash_attention(
            q, k, v, causal=causal, interpret=True, block_q=16, block_k=16
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_single_block(self, qkv):
        q, k, v = qkv
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(
            q, k, v, causal=True, interpret=True, block_q=64, block_k=64
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_global_offsets_tile_semantics(self, qkv):
        q, k, v = qkv
        q_shard = q[:, 32:, :, :]
        ref = reference_attention(q_shard, k, v, causal=True, q_offset=32)
        out = flash_attention(
            q_shard, k, v, causal=True, q_offset=32,
            interpret=True, block_q=16, block_k=16,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("window", [1, 7, 16, 33, 64, 200])
    def test_sliding_window_matches_reference(self, qkv, window):
        """Causal sliding window (q-W < k <= q) for every alignment class:
        sub-block, block-aligned, block-straddling, and wider-than-S (==
        plain causal). Exercises the k-block loop-bound tightening, not
        just the mask."""
        q, k, v = qkv
        ref = reference_attention(q, k, v, causal=True, window=window)
        out = flash_attention(
            q, k, v, causal=True, window=window,
            interpret=True, block_q=16, block_k=16,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )
        if window >= q.shape[1]:
            full = reference_attention(q, k, v, causal=True)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(full), rtol=2e-5, atol=2e-5
            )

    @pytest.mark.parametrize("window", [7, 32])
    def test_sliding_window_gradients(self, qkv, window):
        q, k, v = qkv
        dout = jnp.asarray(
            np.random.RandomState(7).randn(*q.shape).astype(np.float32)
        )

        def loss(fn):
            def f(q, k, v):
                return jnp.sum(fn(q, k, v) * dout)

            return f

        flash_fn = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, window=window,
            interpret=True, block_q=16, block_k=16,
        )
        ref_fn = lambda q, k, v: reference_attention(  # noqa: E731
            q, k, v, causal=True, window=window
        )
        grads = jax.grad(loss(flash_fn), argnums=(0, 1, 2))(q, k, v)
        grads_ref = jax.grad(loss(ref_fn), argnums=(0, 1, 2))(q, k, v)
        for g, gr in zip(grads, grads_ref):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(gr), rtol=3e-5, atol=3e-5
            )

    def test_sliding_window_with_offsets(self, qkv):
        """Windowed attention composes with the global-position tile
        semantics (a ring hop whose k shard is partly outside the window)."""
        q, k, v = qkv
        q_shard = q[:, 32:, :, :]
        window = 24
        ref = reference_attention(
            q_shard, k, v, causal=True, q_offset=32, window=window
        )
        out = flash_attention(
            q_shard, k, v, causal=True, q_offset=32, window=window,
            interpret=True, block_q=16, block_k=16,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_k_block_bounds_exact_over_small_grid(self):
        """Exhaustive check of the kernel's visibility bounds: for every
        (q-block, window, block size, offsets) combination on a small
        grid, [j_lo, j_hi) contains EXACTLY the k blocks holding at least
        one visible (q, k) pair — too-narrow breaks numerics, too-wide is
        silent wasted compute; the docstring claims exact."""
        from tensor2robot_tpu.ops.flash_attention import _k_block_bounds

        for block_q in (2, 3, 8):
            for block_k in (2, 4):
                for num_kb in (1, 3):
                    s_k = block_k * num_kb
                    for q_off in (0, 5, -3):
                        for k_off in (0, 7):
                            for qi in range(3):
                                q0 = q_off + qi * block_q
                                for window in (None, 1, 2, 5, 100):
                                    j_lo, j_hi = _k_block_bounds(
                                        q0, block_q, block_k, num_kb,
                                        k_off, True, window,
                                    )
                                    visible_blocks = set()
                                    for dq in range(block_q):
                                        for kk in range(s_k):
                                            q_pos = q0 + dq
                                            k_pos = k_off + kk
                                            vis = q_pos >= k_pos
                                            if window is not None:
                                                vis &= (
                                                    q_pos - k_pos < window
                                                )
                                            if vis:
                                                visible_blocks.add(
                                                    kk // block_k
                                                )
                                    expected = (
                                        set(range(int(j_lo), int(j_hi)))
                                        if visible_blocks
                                        else set()
                                    )
                                    # Exactness when anything is visible;
                                    # an empty visible set allows any
                                    # (possibly empty) range whose blocks
                                    # are all masked.
                                    if visible_blocks:
                                        assert expected == visible_blocks, (
                                            block_q, block_k, num_kb,
                                            q0, k_off, window,
                                            sorted(expected),
                                            sorted(visible_blocks),
                                        )

    def test_window_requires_causal(self, qkv):
        q, k, v = qkv
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=8, interpret=True)

    def test_gradients_match_reference(self, qkv):
        q, k, v = qkv

        def loss_flash(q, k, v):
            return flash_attention(
                q, k, v, causal=True, interpret=True, block_q=16, block_k=16
            ).sum()

        def loss_ref(q, k, v):
            return reference_attention(q, k, v, causal=True).sum()

        grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(grads_flash, grads_ref):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), rtol=2e-5, atol=2e-5
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_rectangular_with_offsets(self, qkv, causal):
        """The flash backward on a (q-shard x k-shard) tile: s_q != s_k,
        nonzero global offsets — the exact shape a ring hop differentiates."""
        q, k, v = qkv
        q_shard = q[:, 16:48, :, :]

        def loss_flash(q, k, v):
            return (
                flash_attention(
                    q, k, v, causal=causal, q_offset=16,
                    interpret=True, block_q=16, block_k=16,
                )
                ** 2
            ).sum()

        def loss_ref(q, k, v):
            return (
                reference_attention(q, k, v, causal=causal, q_offset=16) ** 2
            ).sum()

        grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q_shard, k, v)
        grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q_shard, k, v)
        for gf, gr in zip(grads_flash, grads_ref):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), rtol=2e-4, atol=2e-4
            )

    def test_gradients_bf16_inputs(self, qkv):
        """bf16 q/k/v (the TPU wrapper's forward dtype): grads keep the
        input dtype and track the reference within bf16 tolerance."""
        q, k, v = (t.astype(jnp.bfloat16) for t in qkv)

        def loss_flash(q, k, v):
            return flash_attention(
                q, k, v, causal=True, interpret=True, block_q=16, block_k=16
            ).astype(jnp.float32).sum()

        def loss_ref(q, k, v):
            return reference_attention(q, k, v, causal=True).astype(
                jnp.float32
            ).sum()

        grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(grads_flash, grads_ref):
            assert gf.dtype == jnp.bfloat16
            np.testing.assert_allclose(
                np.asarray(gf, np.float32),
                np.asarray(gr, np.float32),
                rtol=0.1,
                atol=0.1,
            )

    def test_cpu_fallback_is_reference(self, qkv):
        q, k, v = qkv
        out = flash_attention(q, k, v, causal=True)  # cpu backend -> fallback
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    def test_tile_residuals_merge_to_full_attention(self, qkv):
        """Two k-shard tiles merged with the online-softmax rule must equal
        full attention — the exact contract a ring hop relies on."""
        q, k, v = qkv
        k1, k2 = k[:, :32], k[:, 32:]
        v1, v2 = v[:, :32], v[:, 32:]
        o1, l1, m1 = flash_attention_tile(
            q, k1, v1, causal=True, k_offset=0, interpret=True,
            block_q=16, block_k=16,
        )
        o2, l2, m2 = flash_attention_tile(
            q, k2, v2, causal=True, k_offset=32, interpret=True,
            block_q=16, block_k=16,
        )
        m = jnp.maximum(m1, m2)
        a1, a2 = jnp.exp(m1 - m), jnp.exp(m2 - m)
        l = l1 * a1 + l2 * a2
        t = lambda x: jnp.transpose(x, (0, 2, 1))[..., None]
        o = o1 * t(a1) + o2 * t(a2)
        out = o / t(jnp.maximum(l, 1e-30))
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out.astype(q.dtype)), np.asarray(ref),
            rtol=2e-5, atol=2e-5,
        )


class TestRingWithFlashTiles:
    # ~12s: pallas-interpret forward over the 4-way ring; the flash tile
    # forward stays fast in TestFlashAttention::test_matches_reference
    # and the plain ring-vs-full parity stays fast in
    # test_ring_attention's 4-shard column — this composition joins its
    # gradients twin on the slow slice.
    @pytest.mark.slow
    def test_ring_flash_matches_reference(self):
        from tensor2robot_tpu.parallel import mesh as mesh_lib
        from tensor2robot_tpu.parallel.ring_attention import ring_attention

        n = min(4, len(jax.devices()))
        mesh = mesh_lib.make_mesh(
            data=1, sequence=n, devices=jax.devices()[:n]
        )
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(2, 16 * n, 2, 8).astype(np.float32))
        ref = reference_attention(q, q, q, causal=True)
        out = ring_attention(
            q, q, q, mesh=mesh, causal=True, use_flash=True, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    # ~35s: pallas-interpret backward over the full ring; the per-tile
    # flash gradients above keep fast-slice coverage of the kernel vjp.
    @pytest.mark.slow
    def test_ring_flash_gradients(self):
        """grad must flow through the flash ring (custom vjp; the TPU
        default path is use_flash=True)."""
        from tensor2robot_tpu.parallel import mesh as mesh_lib
        from tensor2robot_tpu.parallel.ring_attention import ring_attention

        n = min(4, len(jax.devices()))
        mesh = mesh_lib.make_mesh(
            data=1, sequence=n, devices=jax.devices()[:n]
        )
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 8 * n, 2, 8).astype(np.float32))

        def loss_flash(q):
            return ring_attention(
                q, q, q, mesh=mesh, causal=True, use_flash=True,
                interpret=True,
            ).sum()

        def loss_ref(q):
            return ring_attention(
                q, q, q, mesh=mesh, causal=True, use_flash=False
            ).sum()

        g_flash = jax.grad(loss_flash)(q)
        g_ref = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(
            np.asarray(g_flash), np.asarray(g_ref), rtol=1e-4, atol=1e-4
        )

    def test_explicit_interpret_false_off_tpu_falls_back(self):
        from tensor2robot_tpu.ops.flash_attention import flash_attention

        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
        out = flash_attention(q, q, q, causal=True, interpret=False)
        ref = reference_attention(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    def test_prime_length_falls_back_to_reference(self):
        """No MXU-viable block divides a prime length > block size: the
        documented einsum fallback must actually engage."""
        from tensor2robot_tpu.ops.flash_attention import _pick_block

        assert _pick_block(257, 128) is None
        assert _pick_block(64, 128) == 64   # single block
        assert _pick_block(256, 128) == 128
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(1, 257, 2, 8).astype(np.float32))
        out = flash_attention(q, q, q, causal=True, interpret=True)
        ref = reference_attention(q, q, q, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_tile_raises_clear_error_off_tpu(self):
        with pytest.raises(ValueError, match="interpreter mode"):
            flash_attention_tile(
                jnp.zeros((1, 16, 1, 8)), jnp.zeros((1, 16, 1, 8)),
                jnp.zeros((1, 16, 1, 8)), interpret=False,
            )
