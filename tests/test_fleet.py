"""serving/router.py + serving/transport.py: the replica-fleet layer.

Every fleet failure mode the router claims to survive is pinned here
with a seeded chaos plan injected into a targeted replica process
(testing/chaos.py rides the T2R_CHAOS env flag through ReplicaSpec.env):
replica crash mid-predict, corrupt reply, straggler hedging, saturation
shed, deadline backstop, health eviction + recovery, slow-restore swap
abort. Replicas run the jax-free mock backend, so each test costs
process spawns, not XLA compiles. No assertion depends on wall-clock
rates — only on typed outcomes, counters, and generous ordering bounds
(an injected 2.5 s stall vs a 0.3 s deadline).
"""

import queue as queue_lib
import time

import numpy as np
import pytest

from tensor2robot_tpu.serving import (
    FleetRouter,
    FleetSaturated,
    ReplicaSpec,
    ReplicaUnavailable,
    RequestAbandoned,
    RouterClosed,
    mock_server_factory,
)
from tensor2robot_tpu.serving import transport


@pytest.fixture(autouse=True)
def _lock_sanitizer_armed(locksmith_sanitizer):
    """Every run of this chaos suite doubles as a deadlock hunt: the
    lock sanitizer (testing/locksmith.py) is armed for each test and
    teardown fails on any observed lock-order cycle or hold-budget
    violation (fixture: tests/conftest.py)."""
    yield


def _spec(service_ms=1.0, chaos=None, version=1):
    env = {"T2R_CHAOS": chaos} if chaos else {}
    return ReplicaSpec(
        factory=mock_server_factory,
        factory_kwargs={"service_ms": service_ms, "version": version},
        env=env,
    )


def _start(specs, num=None, timeout_s=90.0, **kwargs):
    kwargs.setdefault("probe_interval_ms", 50.0)
    kwargs.setdefault("backoff_ms", 5.0)
    router = FleetRouter(specs, num, **kwargs)
    return router.start(timeout_s=timeout_s)


def _wait(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _wait_all_up(router):
    assert _wait(
        lambda: all(s == "up" for s in router.replica_states())
    ), f"fleet never fully up: {router.replica_states()}"


def _features(n=4, value=1.0):
    return {"x": np.full((n,), value, np.float32)}


def _broken_factory():
    raise RuntimeError("this replica can never build its server")


class TestRouting:
    def test_end_to_end_with_provenance(self):
        with _start(_spec(), 2) as router:
            _wait_all_up(router)
            for value in (1.0, 2.0, 3.0):
                response = router.call(
                    _features(value=value), deadline_ms=20000
                )
                assert response.outputs["y"] == pytest.approx(4 * value)
                assert response.model_version == 1
                assert response.attempts == 1 and not response.hedged
                assert response.replica in (0, 1)
                assert response.spans["total_ms"] > 0
            snap = router.snapshot()
            assert snap["counters"]["completed"] == 3
            assert snap["counters"].get("failed", 0) == 0
            assert snap["latency_ms"]["window"] == 3
            assert snap["pending_requests"] == 0

    def test_load_spreads_over_replicas(self):
        with _start(_spec(service_ms=30.0), 2, max_inflight=4) as router:
            _wait_all_up(router)
            futures = [
                router.submit(_features(), deadline_ms=30000)
                for _ in range(8)
            ]
            for future in futures:
                future.result(30)
            served = set()
            for future in futures:
                served.add(future.result(0).replica)
            assert served == {0, 1}

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="num_replicas is required"):
            FleetRouter(_spec())
        with pytest.raises(ValueError, match="at least one replica"):
            FleetRouter([])
        with pytest.raises(ValueError, match="2 specs"):
            FleetRouter([_spec(), _spec()], 3)

    def test_failed_bringup_raises_after_respawn_budget(self):
        router = FleetRouter(
            [ReplicaSpec(factory=_broken_factory)],
            probe_interval_ms=50.0,
            max_respawns=1,
        )
        with pytest.raises(RuntimeError, match="no replica became healthy"):
            router.start(timeout_s=60.0)


class TestFailureHandling:
    def test_replica_kill_mid_predict_is_retried(self):
        """One replica SIGKILLs itself on its first predict; every request
        must still complete (failover), the death must be counted, and
        the killed replica must come back via respawn."""
        specs = [_spec(chaos="predict:1:kill"), _spec()]
        with _start(specs, max_respawns=2) as router:
            _wait_all_up(router)
            futures = [
                router.submit(_features(value=v), deadline_ms=30000)
                for v in (1.0, 2.0, 3.0, 4.0)
            ]
            for value, future in zip((1.0, 2.0, 3.0, 4.0), futures):
                response = future.result(60)
                assert response.outputs["y"] == pytest.approx(4 * value)
            snap = router.snapshot()
            assert snap["counters"]["replica_deaths"] >= 1
            assert snap["counters"]["retries"] >= 1
            assert snap["counters"]["respawns"] >= 1
            assert snap["counters"]["completed"] == 4
            # The respawned replica (fresh process, fresh chaos counters,
            # plan re-armed but predict:1 already consumed by... a NEW
            # process would re-fire; requests may route to its sibling).
            # What matters: the fleet returns to full strength.
            assert _wait(
                lambda: router.replica_states().count("up") == 2
            ), router.replica_states()

    def test_corrupt_reply_detected_and_retried(self):
        """A byte-flipped (checksummed) reply must be treated as a replica
        failure and the request re-dispatched — never decoded into a
        silently-wrong response."""
        specs = [_spec(chaos="reply:1:corrupt"), _spec()]
        with _start(specs) as router:
            _wait_all_up(router)
            for value in (1.0, 2.0, 3.0, 4.0):
                response = router.call(
                    _features(value=value), deadline_ms=30000
                )
                assert response.outputs["y"] == pytest.approx(4 * value)
            snap = router.snapshot()
            assert snap["counters"]["corrupt_replies"] == 1
            assert snap["counters"]["retries"] >= 1
            assert snap["counters"]["completed"] == 4

    def test_hedge_beats_straggler(self):
        """First request lands on the replica whose first predict stalls
        2.5 s; the hedge (after 100 ms) runs on the fast sibling and its
        reply wins long before the straggler wakes."""
        specs = [_spec(), _spec(chaos="predict:1:delay:2500")]
        with _start(specs, hedge_ms=100, default_deadline_ms=20000) as router:
            _wait_all_up(router)
            # Deterministic: the round-robin cursor sends request 1 to
            # replica index 1 (the straggler) when both are idle.
            response = router.call(_features(), deadline_ms=20000)
            assert response.hedged
            assert response.replica == 0
            snap = router.snapshot()
            assert snap["counters"]["hedged"] == 1
            assert snap["counters"]["hedge_wins"] == 1
            assert snap["counters"]["completed"] == 1

    def test_saturated_fleet_sheds_typed_and_recovers(self):
        with _start(
            _spec(service_ms=400.0), 1, max_inflight=1
        ) as router:
            _wait_all_up(router)
            first = router.submit(_features(), deadline_ms=30000)
            with pytest.raises(FleetSaturated, match="in-flight cap"):
                router.submit(_features(), deadline_ms=30000)
            assert first.result(30).outputs["y"] == pytest.approx(4.0)
            snap = router.snapshot()
            assert snap["counters"]["shed_saturated"] == 1
            # Capacity freed: admission works again.
            assert router.call(
                _features(), deadline_ms=30000
            ).outputs["y"] == pytest.approx(4.0)

    def test_deadline_backstop_always_resolves(self):
        """A request whose only replica is wedged (2.5 s stall) and whose
        deadline is 300 ms must fail typed at the deadline — the future
        resolves while the replica is still stuck, because the router
        itself arms a per-request timer."""
        with _start(_spec(chaos="predict:1:delay:2500"), 1) as router:
            _wait_all_up(router)
            future = router.submit(_features(), deadline_ms=300)
            with pytest.raises(RequestAbandoned) as excinfo:
                future.result(2.0)  # well inside the injected 2.5s stall
            assert excinfo.value.reason == "deadline"
            assert router.snapshot()["pending_requests"] == 0

    def test_single_replica_death_abandons_typed_then_unavailable(self):
        """With the whole pool dead (respawn off), in-flight requests fail
        typed through the retry budget and NEW submissions are rejected
        synchronously with ReplicaUnavailable."""
        with _start(
            _spec(chaos="predict:1:kill"), 1, respawn=False, retries=1
        ) as router:
            _wait_all_up(router)
            future = router.submit(_features(), deadline_ms=30000)
            with pytest.raises(RequestAbandoned) as excinfo:
                future.result(60)
            assert excinfo.value.reason == "retries"
            assert "died" in excinfo.value.detail
            assert _wait(
                lambda: router.replica_states() == ["dead"]
            ), router.replica_states()
            with pytest.raises(ReplicaUnavailable):
                router.submit(_features(), deadline_ms=30000)

    def test_silent_replica_evicted_then_readmitted(self):
        """A replica that stops answering health probes (1.5 s stall in
        its loop) must leave the routing set (SUSPECT) and rejoin when it
        answers again. respawn=False pins the eviction path alone — no
        hard-kill racing the recovery."""
        with _start(
            [_spec(chaos="health:2:hang:1500"), _spec()],
            respawn=False,
            probe_interval_ms=50.0,
            probe_miss_limit=3,
        ) as router:
            _wait_all_up(router)
            assert _wait(
                lambda: router.replica_states()[0] == "suspect", timeout=10
            ), router.replica_states()
            # While suspect, traffic still flows via the healthy sibling.
            assert router.call(
                _features(), deadline_ms=20000
            ).replica == 1
            assert _wait(
                lambda: router.replica_states()[0] == "up", timeout=10
            ), router.replica_states()
            assert router.snapshot()["counters"]["evictions"] >= 1

    def test_stop_resolves_pending_with_router_closed(self):
        router = _start(_spec(chaos="predict:1:delay:2000"), 1)
        _wait_all_up(router)
        future = router.submit(_features(), deadline_ms=30000)
        router.stop()
        with pytest.raises(RouterClosed):
            future.result(5)
        with pytest.raises(RouterClosed):
            router.submit(_features())


class TestRollingSwap:
    def test_rolling_swap_entire_fleet(self):
        with _start(_spec(), 3) as router:
            _wait_all_up(router)
            assert router.call(_features(), deadline_ms=20000).model_version == 1
            result = router.rolling_swap(swap_timeout_s=30.0)
            assert result["failed"] is None
            assert sorted(s["replica"] for s in result["swapped"]) == [0, 1, 2]
            assert all(s["version"] == 2 for s in result["swapped"])
            assert router.call(
                _features(), deadline_ms=20000
            ).model_version == 2

    def test_slow_restore_aborts_roll_and_keeps_serving(self):
        """Replica 1's restore stalls past the swap deadline: the roll
        must abort there (bad artifact must not take the fleet down), the
        remaining replica keeps the old version, and traffic still
        completes throughout."""
        specs = [_spec(), _spec(chaos="restore:1:hang:4000"), _spec()]
        with _start(specs) as router:
            _wait_all_up(router)
            result = router.rolling_swap(swap_timeout_s=0.6)
            assert result["failed"] == 1
            assert [s["replica"] for s in result["swapped"]] == [0]
            # Replica 2 was never asked: still the old version.
            versions = {
                r["index"]: r["version"]
                for r in router.snapshot()["replicas"]
            }
            assert versions[0] == 2 and versions[2] == 1
            response = router.call(_features(), deadline_ms=20000)
            assert response.outputs["y"] == pytest.approx(4.0)


class TestTransport:
    def test_pack_unpack_integrity(self):
        crc, blob = transport.pack({"a": 1})
        assert transport.unpack(crc, blob) == {"a": 1}
        bad = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        with pytest.raises(transport.IntegrityError, match="CRC32"):
            transport.unpack(crc, bad)
        # Checksums-but-not-unpickles is the same wire failure.
        garbage = b"\x80\x04nonsense"
        with pytest.raises(transport.IntegrityError, match="decode"):
            transport.unpack(__import__("zlib").crc32(garbage), garbage)

    def test_codec_small_payloads_ride_inline(self):
        codec = transport.RequestCodec(
            queue_lib.Queue(), inline_max_bytes=1 << 20
        )
        payload = codec.encode({"x": np.ones((8,), np.float32)})
        assert payload[0] == "inline"
        decoded = transport.decode_request(
            payload, None, transport.ReplicaSlotCache()
        )
        np.testing.assert_array_equal(decoded["x"], np.ones((8,), np.float32))
        codec.close()

    def test_codec_large_payload_uses_ring_and_recycles_slot(self):
        free = queue_lib.Queue()
        codec = transport.RequestCodec(free, inline_max_bytes=1024, num_slots=2)
        cache = transport.ReplicaSlotCache()
        big = np.arange(64 * 1024, dtype=np.uint8).reshape(256, 256)
        try:
            payload = codec.encode({"big": big, "small": np.int64(7)})
            if payload[0] == "inline":
                pytest.skip("no /dev/shm in this environment")
            assert payload[0] == "shm"
            decoded = transport.decode_request(payload, free, cache)
            np.testing.assert_array_equal(decoded["big"], big)
            assert decoded["small"] == 7
            # decode_request returned the slot: the same name cycles.
            name = payload[1]
            seen = set()
            for _ in range(2 * 2 + 1):
                again = codec.encode({"big": big})
                assert again[0] == "shm"
                seen.add(again[1])
                transport.decode_request(again, free, cache)
            assert name in seen
        finally:
            cache.close()
            codec.close()

    def test_codec_exhausted_ring_degrades_to_inline(self):
        free = queue_lib.Queue()
        codec = transport.RequestCodec(free, inline_max_bytes=1024, num_slots=1)
        big = np.zeros((4096,), np.float64)
        try:
            first = codec.encode({"big": big})
            if first[0] == "inline":
                pytest.skip("no /dev/shm in this environment")
            # Slot never released: the next large payload must go inline
            # rather than block (shed-to-slower, never stuck).
            second = codec.encode({"big": big})
            assert second[0] == "inline"
            decoded = transport.decode_request(
                second, free, transport.ReplicaSlotCache()
            )
            np.testing.assert_array_equal(decoded["big"], big)
        finally:
            codec.close()

    def test_router_ships_large_payloads_intact(self):
        """End-to-end shm transport: a payload far over the inline cap
        round-trips through a replica process bit-exactly (the mock
        echoes a checksum + byte count)."""
        frame = (np.arange(96 * 1024, dtype=np.int64) % 251).astype(np.uint8)
        with _start(
            _spec(), 1, inline_max_bytes=4096, shm_slots=4
        ) as router:
            _wait_all_up(router)
            response = router.call(
                {"frame": frame, "scalar": np.float32(2.5)},
                deadline_ms=30000,
            )
            assert response.outputs["nbytes"] == frame.nbytes + 4
            assert response.outputs["y"] == pytest.approx(
                float(frame.astype(np.float64).sum()) + 2.5
            )
