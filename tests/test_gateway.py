"""serving/gateway.py + serving/autoscaler.py: the multi-tenant tier.

Every contract the front door claims is pinned here over the jax-free
mock replica backend: typed admission (quota throttle, per-tenant
circuit, unknown tenant), strict-priority shedding (bronze before
gold), per-tier queue budgets, identical-observation coalescing with
the model-version-flip guard, end-to-end deadline propagation, chaos
`admit`/`coalesce`/`scale` sites with per-tenant `t<i>` scopes, and the
autoscaler's watermark/hysteresis/cooloff cycle with drain-safe
scale-down. No assertion depends on wall-clock rates — only typed
outcomes, counters, and generous ordering bounds.
"""

import os
import signal
import time

import numpy as np
import pytest

from tensor2robot_tpu.serving import (
    Autoscaler,
    FleetRouter,
    GateDeadline,
    Gateway,
    GatewayClosed,
    ReplicaSpec,
    RequestAbandoned,
    TenantBinding,
    TenantThrottled,
    TenantSuspended,
    TierShed,
    UnknownTenant,
    mock_server_factory,
    multi_policy_mock_factory,
    observation_digest,
)
from tensor2robot_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _lock_sanitizer_armed(locksmith_sanitizer):
    """Every run of this chaos suite doubles as a deadlock hunt: the
    lock sanitizer (testing/locksmith.py) is armed for each test and
    teardown fails on any observed lock-order cycle or hold-budget
    violation (fixture: tests/conftest.py)."""
    yield


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


def _spec(service_ms=1.0, chaos_plan=None):
    env = {"T2R_CHAOS": chaos_plan} if chaos_plan else {}
    return ReplicaSpec(
        factory=mock_server_factory,
        factory_kwargs={"service_ms": service_ms},
        env=env,
    )


def _router(num=1, service_ms=1.0, chaos_plan=None, **kwargs):
    kwargs.setdefault("probe_interval_ms", 50.0)
    kwargs.setdefault("backoff_ms", 5.0)
    router = FleetRouter(
        _spec(service_ms=service_ms, chaos_plan=chaos_plan), num, **kwargs
    )
    return router.start(timeout_s=90.0)


def _wait(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _wait_all_up(router):
    assert _wait(
        lambda: all(s == "up" for s in router.replica_states())
    ), f"fleet never fully up: {router.replica_states()}"


def _features(value=1.0, n=4):
    return {"x": np.full((n,), value, np.float32)}


def _bindings(**overrides):
    base = dict(quota_rps=10_000.0, burst=10_000)
    base.update(overrides)
    return [
        TenantBinding(tenant="gold0", tier="gold", **base),
        TenantBinding(tenant="bronze0", tier="bronze", **base),
    ]


class TestAdmission:
    def test_end_to_end_multi_tenant(self):
        with _router(2) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                for tenant, value in (("gold0", 1.0), ("bronze0", 2.0)):
                    response = gateway.call(
                        tenant, _features(value), deadline_ms=20000
                    )
                    assert response.outputs["y"] == pytest.approx(4 * value)
                    assert response.tenant == tenant
                    assert response.pool == "default"
                    assert not response.coalesced
                assert gateway.call(
                    "gold0", _features(3.0), deadline_ms=20000
                ).tier == "gold"
                snap = gateway.snapshot()
                assert snap["counters"]["completed"] == 3
                assert snap["counters"].get("failed", 0) == 0
                assert snap["tenants"]["gold0"]["counters"]["completed"] == 2
                assert snap["tenants"]["gold0"]["scope"] == "t0"
                assert snap["tenants"]["bronze0"]["scope"] == "t1"
                # Deadline propagation is visible in the span chain: the
                # gateway hop wraps the router's total.
                assert "gateway_ms" in response.spans

    def test_unknown_tenant_and_closed(self):
        with _router(1) as router:
            _wait_all_up(router)
            gateway = Gateway(router, _bindings()).start()
            with pytest.raises(UnknownTenant):
                gateway.submit("nobody", _features())
            gateway.stop()
            with pytest.raises(GatewayClosed):
                gateway.submit("gold0", _features())

    def test_token_bucket_throttles_typed_then_refills(self):
        with _router(1) as router:
            _wait_all_up(router)
            bindings = [
                TenantBinding(
                    tenant="small", tier="silver", quota_rps=50.0, burst=2
                ),
            ]
            with Gateway(router, bindings).start() as gateway:
                futures = [
                    gateway.submit("small", _features(), deadline_ms=20000)
                    for _ in range(2)
                ]
                with pytest.raises(TenantThrottled, match="over quota"):
                    gateway.submit("small", _features(), deadline_ms=20000)
                for future in futures:
                    future.result(30)
                # Refill at 50/s: one token lands well within a second.
                assert _wait(
                    lambda: gateway.snapshot()["tenants"]["small"]["tokens"]
                    >= 1.0,
                    timeout=5,
                )
                assert gateway.call(
                    "small", _features(), deadline_ms=20000
                ).outputs["y"] == pytest.approx(4.0)
                snap = gateway.snapshot()
                assert snap["counters"]["throttled"] == 1
                assert snap["tenants"]["small"]["counters"]["throttled"] == 1

    def test_rogue_tenant_circuit_opens_and_recovers(self):
        """A tenant whose every admitted request dies pool-side (an
        unmeetable deadline) trips its OWN circuit; the healthy tenant
        sharing the pool keeps completing throughout."""
        with _router(1, service_ms=5.0) as router:
            _wait_all_up(router)
            bindings = [
                TenantBinding(tenant="ok", tier="gold", quota_rps=10_000.0,
                              burst=1000),
                TenantBinding(tenant="rogue", tier="bronze",
                              quota_rps=10_000.0, burst=1000,
                              deadline_ms=1.0),
            ]
            with Gateway(
                router, bindings, circuit_threshold=3,
                circuit_cooloff_ms=400.0,
            ).start() as gateway:
                suspended = None
                for _ in range(50):
                    try:
                        future = gateway.submit("rogue", _features())
                    except TenantSuspended as err:
                        suspended = err
                        break
                    with pytest.raises(
                        (GateDeadline, RequestAbandoned, TierShed)
                    ):
                        future.result(10)
                assert suspended is not None, "circuit never opened"
                snap = gateway.snapshot()
                assert snap["counters"]["circuit_opens"] >= 1
                assert snap["tenants"]["rogue"]["circuit_open"] is True
                # The pool is fine for everyone else, before and after.
                assert gateway.call(
                    "ok", _features(), deadline_ms=20000
                ).outputs["y"] == pytest.approx(4.0)
                # Cooloff passes; the rogue is readmitted (typed, counted).
                assert _wait(
                    lambda: not gateway.snapshot()["tenants"]["rogue"][
                        "circuit_open"
                    ],
                    timeout=5,
                )
                assert gateway.call(
                    "rogue", _features(), deadline_ms=20000
                ).outputs["y"] == pytest.approx(4.0)

    def test_pool_blip_retried_at_the_gateway(self):
        """The router abandons a request typed when ITS retry budget
        dies with the replica (retries=0, killer replica, respawn off) —
        but the gateway still holds end-to-end deadline, re-queues the
        request, and the healthy sibling serves it. The kill-window blip
        never surfaces to the tenant."""
        specs = [_spec(), _spec(chaos_plan="predict:1:kill")]
        router = FleetRouter(
            specs, probe_interval_ms=50.0, backoff_ms=5.0,
            retries=0, respawn=False,
        ).start(timeout_s=90.0)
        with router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                # The round-robin tie-break sends request 1 to replica 1
                # (the killer) when both are idle — deterministic.
                response = gateway.call(
                    "gold0", _features(), deadline_ms=30000
                )
                assert response.outputs["y"] == pytest.approx(4.0)
                assert response.replica == 0  # served by the survivor
                snap = gateway.snapshot()
                assert snap["counters"]["pool_retries"] >= 1
                assert snap["counters"]["completed"] == 1
                assert router.snapshot()["counters"]["replica_deaths"] == 1

    def test_deadline_rides_to_the_replica_backstop(self):
        """A 300 ms gateway deadline against a replica stalled 2.5 s must
        fail typed long before the stall ends — proof the budget rode
        through the router (whose backstop resolves it) rather than
        being re-minted per hop."""
        with _router(1, chaos_plan="predict:1:delay:2500") as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                future = gateway.submit(
                    "gold0", _features(), deadline_ms=300
                )
                with pytest.raises((RequestAbandoned, GateDeadline)):
                    future.result(2.0)  # well inside the injected stall


class TestCrossPoolFailover:
    """Pools as availability zones (round 21): a request whose home
    pool cannot serve moves to a sibling pool — gated on
    artifact-fingerprint equality (interchangeability is proven, never
    assumed) and counted (`cross_pool_retries`, per-pool
    `retried_away`/`retried_in`)."""

    @staticmethod
    def _pool(fingerprint, **kwargs):
        kwargs.setdefault("probe_interval_ms", 50.0)
        kwargs.setdefault("backoff_ms", 5.0)
        spec = ReplicaSpec(
            factory=mock_server_factory,
            factory_kwargs={"service_ms": 1.0, "fingerprint": fingerprint},
        )
        return FleetRouter(spec, 1, **kwargs).start(timeout_s=90.0)

    @staticmethod
    def _kill_pool(router):
        def _pid():
            host = router.snapshot()["replicas"][0].get("host")
            return host and host.get("pid")

        assert _wait(lambda: _pid() is not None), "host pid never reported"
        os.kill(_pid(), signal.SIGKILL)
        assert _wait(
            lambda: router.load()["replicas_up"] == 0
        ), "dead pool still reports capacity"

    @staticmethod
    def _gold_binding():
        return [TenantBinding(
            tenant="gold0", pool="home", tier="gold",
            quota_rps=10_000.0, burst=10_000,
        )]

    def test_dead_home_pool_fails_over_at_dispatch(self):
        """The home pool has NO healthy replica (`ReplicaUnavailable`
        raised at dispatch — the partitioned/dead-zone shape): the
        gateway moves the request to the fingerprint-equal sibling
        instead of spinning it in place until its deadline expires."""
        home = self._pool("artifact-X", respawn=False)
        other = self._pool("artifact-X")
        with home, other:
            _wait_all_up(home)
            _wait_all_up(other)
            with Gateway(
                {"home": home, "other": other}, self._gold_binding()
            ).start() as gateway:
                assert gateway.call(
                    "gold0", _features(), deadline_ms=20000
                ).pool == "home"
                self._kill_pool(home)
                response = gateway.call(
                    "gold0", _features(), deadline_ms=20000
                )
                assert response.outputs["y"] == pytest.approx(4.0)
                assert response.pool == "other"
                snap = gateway.snapshot()
                assert snap["counters"]["cross_pool_retries"] >= 1
                assert snap["pools"]["home"]["counters"][
                    "retried_away"] >= 1
                assert snap["pools"]["other"]["counters"][
                    "retried_in"] >= 1

    def test_failover_requires_fingerprint_equality(self):
        """A sibling pool serving a DIFFERENT artifact never absorbs
        the failover — the request fails typed at its deadline rather
        than silently landing on the wrong model."""
        home = self._pool("artifact-X", respawn=False)
        other = self._pool("artifact-Y")
        with home, other:
            _wait_all_up(home)
            _wait_all_up(other)
            with Gateway(
                {"home": home, "other": other}, self._gold_binding()
            ).start() as gateway:
                self._kill_pool(home)
                future = gateway.submit(
                    "gold0", _features(), deadline_ms=600
                )
                with pytest.raises(GateDeadline):
                    future.result(30)
                snap = gateway.snapshot()
                assert snap["counters"].get("cross_pool_retries", 0) == 0
                assert snap["pools"]["other"]["counters"].get(
                    "retried_in", 0) == 0


class TestPriorityShedding:
    def _saturated_gateway(self, router, **kwargs):
        kwargs.setdefault("max_queue", 4)
        return Gateway(router, _bindings(), **kwargs).start()

    def test_overload_sheds_bronze_before_gold(self):
        """One slow replica at in-flight cap 1; the queue fills with
        bronze, then gold arrives: every displaced request is BRONZE and
        typed, and every gold completes."""
        with _router(1, service_ms=120.0, max_inflight=1) as router:
            _wait_all_up(router)
            with self._saturated_gateway(router) as gateway:
                bronze = [
                    gateway.submit(
                        "bronze0", _features(float(i)), deadline_ms=60000
                    )
                    for i in range(4)
                ]
                gold = [
                    gateway.submit(
                        "gold0", _features(10.0 + i), deadline_ms=60000
                    )
                    for i in range(4)
                ]
                for future in gold:
                    assert future.result(60).tier == "gold"
                shed = [f for f in bronze if isinstance(f.error(), TierShed)]
                assert len(shed) >= 3  # queue was 4 deep; gold displaced them
                for future in shed:
                    assert future.error().tier == "bronze"
                snap = gateway.snapshot()
                assert snap["counters"]["shed_queue_bronze"] >= 3
                assert snap["counters"].get("shed_queue_gold", 0) == 0

    def test_full_queue_of_higher_tier_rejects_incoming_low(self):
        with _router(1, service_ms=120.0, max_inflight=1) as router:
            _wait_all_up(router)
            with self._saturated_gateway(router) as gateway:
                gold = [
                    gateway.submit("gold0", _features(0.0), deadline_ms=60000)
                ]
                # Let the head gold occupy the single replica slot before
                # filling the queue, or the 5th gold would shed the 1st.
                assert _wait(
                    lambda: gateway.snapshot()["counters"].get(
                        "dispatched", 0
                    ) == 1
                )
                gold += [
                    gateway.submit(
                        "gold0", _features(float(i)), deadline_ms=60000
                    )
                    for i in range(1, 5)  # 4 queue (full)
                ]
                # Wait until the queue really holds 4 golds (the
                # dispatcher transiently holds one in hand during a
                # saturation retry), then offer a DISTINCT bronze
                # observation: every queued entry outranks it, so the
                # incoming request is the one rejected.
                assert _wait(
                    lambda: gateway.snapshot()["pools"]["default"][
                        "queue_depth"
                    ]["gold"] == 4
                )
                with pytest.raises(TierShed, match="no bronze-or-lower"):
                    gateway.submit(
                        "bronze0", _features(100.0), deadline_ms=60000
                    )
                for future in gold:
                    future.result(60)

    def test_tier_queue_budget_sheds_typed(self):
        """Bronze carries a 150 ms queue budget; with the pool pinned by
        a long request, queued bronze resolves GateDeadline(queue_budget)
        near the budget — not at its (much longer) request deadline."""
        with _router(1, service_ms=400.0, max_inflight=1) as router:
            _wait_all_up(router)
            with Gateway(
                router, _bindings(),
                tier_queue_budget_ms={"bronze": 150.0},
            ).start() as gateway:
                # Distinct observations: an identical one would COALESCE
                # onto the gold dispatch instead of queueing.
                pin = gateway.submit(
                    "gold0", _features(1.0), deadline_ms=60000
                )
                blocked = gateway.submit(
                    "bronze0", _features(99.0), deadline_ms=60000
                )
                with pytest.raises(GateDeadline) as excinfo:
                    blocked.result(5.0)  # far below the 60 s deadline
                assert excinfo.value.reason == "queue_budget"
                pin.result(60)

    def test_stop_resolves_queued_with_gateway_closed(self):
        with _router(1, service_ms=300.0, max_inflight=1) as router:
            _wait_all_up(router)
            gateway = Gateway(router, _bindings(), max_queue=8).start()
            stuck = [
                gateway.submit("bronze0", _features(), deadline_ms=60000)
                for _ in range(4)
            ]
            gateway.stop()
            resolved = 0
            for future in stuck:
                try:
                    future.result(10)
                    resolved += 1
                except (GatewayClosed, RequestAbandoned, GateDeadline):
                    resolved += 1
            assert resolved == 4  # zero hung futures


class TestCoalescing:
    def test_identical_observations_share_one_dispatch(self):
        """Five bitwise-identical submits against a slow pool: one
        replica dispatch serves all five with the same outputs object
        (bitwise equality by construction), and the riders are marked
        coalesced."""
        with _router(1, service_ms=150.0) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                features = _features(7.0)
                futures = [
                    gateway.submit("gold0", features, deadline_ms=60000)
                    for _ in range(5)
                ]
                responses = [f.result(60) for f in futures]
                leader_outputs = responses[0].outputs
                for response in responses:
                    assert response.outputs is leader_outputs
                    assert response.outputs["y"] == pytest.approx(28.0)
                assert sum(r.coalesced for r in responses) == 4
                snap = gateway.snapshot()
                assert snap["counters"]["coalesced_joins"] == 4
                assert snap["counters"]["dispatched"] == 1
                # The router saw ONE request for five completions.
                assert router.snapshot()["counters"]["completed"] == 1

    def test_different_observations_do_not_coalesce(self):
        with _router(1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                a = gateway.call("gold0", _features(1.0), deadline_ms=20000)
                b = gateway.call("gold0", _features(2.0), deadline_ms=20000)
                assert a.outputs["y"] != b.outputs["y"]
                assert gateway.snapshot()["counters"].get(
                    "coalesced_joins", 0
                ) == 0

    def test_never_coalesces_across_a_version_flip(self):
        """A leader queued before rolling_swap() must not pick up riders
        admitted after it: the swap bumps the pool epoch and the new
        identical observation dispatches fresh."""
        with _router(1, service_ms=250.0, max_inflight=1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                features = _features(5.0)
                leader = gateway.submit(
                    "gold0", features, deadline_ms=60000
                )
                swap = gateway.rolling_swap(swap_timeout_s=30.0)
                assert swap["failed"] is None
                follower = gateway.submit(
                    "gold0", features, deadline_ms=60000
                )
                first = leader.result(60)
                second = follower.result(60)
                assert not second.coalesced
                assert gateway.snapshot()["counters"].get(
                    "coalesced_joins", 0
                ) == 0
                assert gateway.snapshot()["counters"]["dispatched"] == 2
                # And the post-flip request really saw the new version.
                assert second.model_version >= first.model_version

    def test_rider_never_joins_a_lower_priority_leader(self):
        """Priority inversion guard: a gold request must not ride a
        BRONZE leader (it would inherit the leader's shed/starvation
        fate); the reverse direction — low tier riding high — is fine."""
        with _router(1, service_ms=250.0, max_inflight=1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                pin = gateway.submit(
                    "gold0", _features(50.0), deadline_ms=60000
                )
                features = _features(7.0)
                bronze_leader = gateway.submit(
                    "bronze0", features, deadline_ms=60000
                )
                gold_request = gateway.submit(
                    "gold0", features, deadline_ms=60000
                )
                assert not gold_request.result(60).coalesced
                # Strict priority served gold BEFORE the bronze leader,
                # which is exactly why joining it would have been wrong.
                bronze_leader.result(60)
                pin.result(60)
                assert gateway.snapshot()["counters"].get(
                    "coalesced_joins", 0
                ) == 0

    def test_rider_with_shorter_deadline_does_not_join(self):
        """Deadline inheritance guard: a dispatch carries the LEADER's
        budget, so a rider whose own deadline is shorter must dispatch
        (and expire) on its own terms — never be served late by a
        longer-lived leader."""
        with _router(1, service_ms=400.0, max_inflight=1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                pin = gateway.submit(
                    "gold0", _features(50.0), deadline_ms=60000
                )
                features = _features(9.0)
                leader = gateway.submit(
                    "gold0", features, deadline_ms=60000
                )
                short_rider = gateway.submit(
                    "gold0", features, deadline_ms=150
                )
                with pytest.raises((GateDeadline, RequestAbandoned)):
                    short_rider.result(5.0)  # typed at ITS deadline
                assert leader.result(60).outputs["y"] == pytest.approx(36.0)
                pin.result(60)
                assert gateway.snapshot()["counters"].get(
                    "coalesced_joins", 0
                ) == 0

    def test_coalesce_disabled_by_flag_override(self):
        with _router(1, service_ms=100.0) as router:
            _wait_all_up(router)
            with Gateway(
                router, _bindings(), coalesce=False
            ).start() as gateway:
                features = _features(2.0)
                futures = [
                    gateway.submit("gold0", features, deadline_ms=60000)
                    for _ in range(3)
                ]
                for future in futures:
                    assert not future.result(60).coalesced
                assert gateway.snapshot()["counters"]["dispatched"] == 3


class TestChaosSites:
    def test_admit_site_scoped_to_one_tenant(self):
        """t1/admit:2:raise fires at tenant t1's SECOND admission only;
        tenant t0's admissions never see it."""
        chaos.configure("t1/admit:2:raise")
        with _router(1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                assert gateway.tenant_scope("gold0") == "t0"
                assert gateway.tenant_scope("bronze0") == "t1"
                gateway.call("bronze0", _features(), deadline_ms=20000)
                gateway.call("gold0", _features(), deadline_ms=20000)
                gateway.call("gold0", _features(), deadline_ms=20000)
                with pytest.raises(chaos.ChaosFault, match="t1/admit"):
                    gateway.submit("bronze0", _features())
                # The plan is spent; the tenant serves again.
                gateway.call("bronze0", _features(), deadline_ms=20000)
                assert chaos.counters()["admit@t1"] == 3
                assert chaos.counters()["admit@t0"] == 2

    def test_admit_drop_sheds_typed(self):
        chaos.configure("t0/admit:1:drop")
        with _router(1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                with pytest.raises(TierShed, match="chaos"):
                    gateway.submit("gold0", _features())
                assert gateway.snapshot()["counters"][
                    "chaos_admit_drops"
                ] == 1
                gateway.call("gold0", _features(), deadline_ms=20000)

    def test_coalesce_drop_bypasses_the_join(self):
        """A drop at the coalesce site forces an individual dispatch:
        both requests complete, zero joins, two dispatches."""
        chaos.configure("t0/coalesce:1:drop")
        with _router(1, service_ms=150.0) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                features = _features(3.0)
                first = gateway.submit("gold0", features, deadline_ms=60000)
                second = gateway.submit("gold0", features, deadline_ms=60000)
                first.result(60)
                second.result(60)
                snap = gateway.snapshot()
                assert snap["counters"]["chaos_coalesce_bypass"] == 1
                assert snap["counters"].get("coalesced_joins", 0) == 0
                assert snap["counters"]["dispatched"] == 2


class TestAutoscaler:
    def test_constructor_validation(self):
        with _router(1) as router:
            with pytest.raises(ValueError, match="min_replicas"):
                Autoscaler(router, min_replicas=0)
            with pytest.raises(ValueError, match="max_replicas"):
                Autoscaler(router, min_replicas=3, max_replicas=2)
            with pytest.raises(ValueError, match="low"):
                Autoscaler(
                    router, low_watermark=0.8, high_watermark=0.5
                )

    def test_scale_up_on_sustained_high_watermark(self):
        with _router(1, service_ms=400.0, max_inflight=2) as router:
            _wait_all_up(router)
            scaler = Autoscaler(
                router, min_replicas=1, max_replicas=3,
                scale_up_ticks=2, cooloff_base_ms=50.0, seed=7,
            )
            futures = [
                router.submit(_features(), deadline_ms=60000)
                for _ in range(2)  # inflight 2/2 = utilization 1.0
            ]
            assert scaler.tick() is None  # hysteresis: one tick moves nothing
            assert scaler.tick() == "up"
            assert _wait(
                lambda: router.load()["replicas_up"] == 2
            ), router.replica_states()
            for future in futures:
                future.result(60)
            snap = scaler.snapshot()
            assert snap["counters"]["scale_up"] == 1
            assert snap["actions"][0]["direction"] == "up"
            router_snap = router.snapshot()
            assert router_snap["counters"]["scale_ups"] == 1
            # Boot attribution for the scale-up: the new replica reports
            # how long its spawn->started took and which restore tier
            # each bucket prewarmed from (off its health snapshot), so
            # scale-up latency is attributable to deserialize vs
            # compile. The prewarm source arrives with the first health
            # probe; boot_ms is measured router-side at "started".
            new_index = snap["actions"][0]["replica"]
            new_replica = router_snap["replicas"][new_index]
            assert new_replica["boot_ms"] is not None
            assert new_replica["boot_ms"] > 0
            assert _wait(
                lambda: router.snapshot()["replicas"][new_index][
                    "prewarm_source"
                ] is not None
            ), "scale-up replica never reported its prewarm source"
            assert router.snapshot()["replicas"][new_index][
                "prewarm_source"
            ] == {"1": "mock"}
            boots = scaler.snapshot()["scale_up_boots"]
            assert [b["replica"] for b in boots] == [new_index]
            assert boots[0]["boot_ms"] == new_replica["boot_ms"]

    def test_scale_down_drains_without_killing_inflight(self):
        """Retirement must let the in-flight request finish: the drained
        replica leaves routing immediately but its request completes,
        and the exit is counted as retirement, not death."""
        with _router(2, service_ms=500.0) as router:
            _wait_all_up(router)
            inflight = [
                router.submit(_features(float(i)), deadline_ms=60000)
                for i in range(2)
            ]
            scaler = Autoscaler(
                router, min_replicas=1, max_replicas=2,
                scale_down_ticks=2, cooloff_base_ms=50.0,
                drain_timeout_s=30.0, seed=7,
            )
            # Let the slow requests land on the replicas, then wait them
            # out so utilization reads low for the down-ticks.
            for future in inflight:
                future.result(60)
            assert scaler.tick() is None
            assert scaler.tick() == "down"
            assert _wait(
                lambda: router.load()["replicas_up"] == 1
            ), router.replica_states()
            load = router.load()
            assert load["replicas_up"] == 1
            snap = router.snapshot()
            assert snap["counters"]["retirements"] == 1
            assert snap["counters"].get("replica_deaths", 0) == 0
            assert _wait(
                lambda: router.snapshot()["counters"].get(
                    "retired_exits", 0
                ) == 1
            )
            # The surviving fleet still serves.
            assert router.call(
                _features(), deadline_ms=20000
            ).outputs["y"] == pytest.approx(4.0)

    def test_retire_mid_flight_waits_for_the_request(self):
        with _router(2, service_ms=400.0) as router:
            _wait_all_up(router)
            futures = [
                router.submit(_features(float(i)), deadline_ms=60000)
                for i in range(2)
            ]
            # Retire whichever replica carries request 0 — mid-flight.
            target = None
            for r in router.snapshot()["replicas"]:
                if r["inflight"] > 0:
                    target = r["index"]
                    break
            assert target is not None
            assert router.retire_replica(target, drain_timeout_s=30.0)
            for future in futures:
                assert future.result(60).outputs["y"] >= 0  # completed
            assert router.snapshot()["counters"]["retirements"] == 1

    def test_bounds_respected_and_cooloff_quiets(self):
        with _router(1, service_ms=300.0, max_inflight=1) as router:
            _wait_all_up(router)
            scaler = Autoscaler(
                router, min_replicas=1, max_replicas=1,
                scale_up_ticks=1, cooloff_base_ms=50.0, seed=7,
            )
            future = router.submit(_features(), deadline_ms=60000)
            # Utilization 1.0 but the ceiling is 1: no action, ever.
            assert scaler.tick() is None
            assert scaler.tick() is None
            future.result(60)
            assert scaler.snapshot()["counters"].get("scale_up", 0) == 0

    def test_chaos_scale_site_drops_an_action(self):
        chaos.configure("scale:1:drop")
        with _router(1, service_ms=400.0, max_inflight=1) as router:
            _wait_all_up(router)
            scaler = Autoscaler(
                router, min_replicas=1, max_replicas=2,
                scale_up_ticks=1, cooloff_base_ms=10.0, seed=7,
            )
            future = router.submit(_features(), deadline_ms=60000)
            assert scaler.tick() is None  # the actuator missed its beat
            assert scaler.snapshot()["counters"]["chaos_skipped"] == 1
            assert router.load()["replicas_up"] == 1
            assert scaler.tick() == "up"  # next decision lands
            future.result(60)


_CATALOG = {
    "pA": {"scale": 2.0, "bias": 1.0, "version": 3, "mem_bytes": 1 << 20},
    "pB": {"scale": -1.0, "bias": 0.5, "version": 4, "mem_bytes": 1 << 20},
}


def _policy_router(num=1, service_ms=1.0, **kwargs):
    kwargs.setdefault("probe_interval_ms", 50.0)
    kwargs.setdefault("backoff_ms", 5.0)
    spec = ReplicaSpec(
        factory=multi_policy_mock_factory,
        factory_kwargs={"catalog": _CATALOG, "service_ms": service_ms},
    )
    return FleetRouter(spec, num, **kwargs).start(timeout_s=90.0)


class TestMultiPolicy:
    def test_digest_folds_policy_and_model_identity(self):
        """The satellite-1 regression at the digest level: the coalesce
        key domain-separates policy id and model fingerprint from the
        feature bytes, so two tenants asking DIFFERENT policies the same
        observation can never share one digest (they would have joined
        one dispatch and one of them would get the wrong policy's
        outputs)."""
        arrays = _features(3.0)
        base = observation_digest(arrays)
        assert observation_digest(arrays) == base  # deterministic
        assert observation_digest(arrays, policy_id="pA") != base
        assert observation_digest(
            arrays, policy_id="pA"
        ) != observation_digest(arrays, policy_id="pB")
        assert observation_digest(
            arrays, model_fingerprint="f1"
        ) != observation_digest(arrays, model_fingerprint="f2")
        # Domain separation: a policy id must never collide with the
        # same string in the fingerprint slot.
        assert observation_digest(
            arrays, policy_id="x"
        ) != observation_digest(arrays, model_fingerprint="x")
        assert observation_digest(
            arrays, policy_id="pA", model_fingerprint="f"
        ) == observation_digest(arrays, policy_id="pA", model_fingerprint="f")

    def test_cross_policy_identical_observations_never_join(self):
        """The would-have-joined regression, live: identical features
        against pA and pB queue behind a pinned slow replica. Same-
        policy riders coalesce; the other policy's request MUST dispatch
        on its own — pre-fix, the feature-only digest would have joined
        it to pA's leader and served it pA's outputs."""
        with _policy_router(1, service_ms=150.0, max_inflight=1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                pin = gateway.submit(
                    "gold0", _features(50.0), deadline_ms=60000,
                    policy_id="pA",
                )
                features = _features(7.0)
                leader_a = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pA"
                )
                rider_a = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pA"
                )
                leader_b = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pB"
                )
                rider_b = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pB"
                )
                a1, a2 = leader_a.result(60), rider_a.result(60)
                b1, b2 = leader_b.result(60), rider_b.result(60)
                pin.result(60)
                # sum(features) = 28: pA -> 2*28+1, pB -> -28+0.5.
                for response in (a1, a2):
                    assert response.outputs["y"] == pytest.approx(57.0)
                    assert response.policy_id == "pA"
                for response in (b1, b2):
                    assert response.outputs["y"] == pytest.approx(-27.5)
                    assert response.policy_id == "pB"
                assert a2.coalesced and b2.coalesced
                snap = gateway.snapshot()
                assert snap["counters"]["coalesced_joins"] == 2
                assert snap["counters"]["dispatched"] == 3  # pin + 2

    def test_per_policy_swap_epoch_isolates_coalescing(self):
        """rolling_swap(policy_id='pB') bumps ONLY pB's coalesce epoch:
        a pB observation queued before the swap never adopts post-swap
        riders, while pA's identical observations keep coalescing right
        through pB's publish — one policy's deploy never blips
        another's traffic."""
        with _policy_router(1, service_ms=150.0, max_inflight=1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                pin = gateway.submit(
                    "gold0", _features(50.0), deadline_ms=60000,
                    policy_id="pA",
                )
                features = _features(9.0)
                leader_b = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pB"
                )
                swap = gateway.rolling_swap(
                    swap_timeout_s=30.0, policy_id="pB"
                )
                assert swap["failed"] is None
                follower_b = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pB"
                )
                leader_a = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pA"
                )
                rider_a = gateway.submit(
                    "gold0", features, deadline_ms=60000, policy_id="pA"
                )
                assert not follower_b.result(60).coalesced
                assert rider_a.result(60).coalesced
                leader_b.result(60), leader_a.result(60), pin.result(60)
                snap = gateway.snapshot()
                pool = snap["pools"]["default"]
                assert pool["policy_epochs"] == {"pB": 1}
                assert pool["swap_epoch"] == 0  # global epoch untouched
                assert snap["counters"]["coalesced_joins"] == 1

    def test_admission_buckets_keyed_per_tenant_and_policy(self):
        """One tenant, burst=1: draining pA's bucket must not throttle
        the SAME tenant's pB traffic (or its default stream) — quotas
        are per (tenant, policy) stream."""
        with _policy_router(1) as router:
            _wait_all_up(router)
            bindings = [
                TenantBinding(
                    tenant="gold0", tier="gold", quota_rps=0.001, burst=1
                ),
                TenantBinding(
                    tenant="bronze0", tier="bronze", quota_rps=0.001, burst=1
                ),
            ]
            with Gateway(router, bindings).start() as gateway:
                first = gateway.submit(
                    "gold0", _features(1.0), deadline_ms=20000,
                    policy_id="pA",
                )
                with pytest.raises(TenantThrottled):
                    gateway.submit(
                        "gold0", _features(2.0), deadline_ms=20000,
                        policy_id="pA",
                    )
                other_stream = gateway.submit(
                    "gold0", _features(3.0), deadline_ms=20000,
                    policy_id="pB",
                )
                default_stream = gateway.submit(
                    "gold0", _features(4.0), deadline_ms=20000
                )
                assert first.result(60).policy_id == "pA"
                assert other_stream.result(60).policy_id == "pB"
                assert default_stream.result(60).policy_id is None
                snap = gateway.snapshot()["tenants"]["gold0"]
                assert set(snap["policy_tokens"]) == {"pA", "pB"}
                assert snap["counters"]["throttled"] == 1

    def test_placement_surfaces_in_router_and_autoscaler_snapshots(self):
        """The placement surface rides health probes into BOTH control-
        plane snapshots: per-replica resident sets, eviction/cold-load
        counters, and the model fingerprint slot — the data a capacity
        decision needs to avoid scaling up a replica that must cold-load
        the hot policy."""
        with _policy_router(1) as router:
            _wait_all_up(router)
            with Gateway(router, _bindings()).start() as gateway:
                for pid in ("pA", "pB"):
                    gateway.call(
                        "gold0", _features(1.0), deadline_ms=20000,
                        policy_id=pid,
                    )
                assert _wait(
                    lambda: any(
                        set(r.get("resident_policies") or ())
                        >= {"pA", "pB"}
                        for r in router.snapshot()["replicas"]
                    )
                ), router.snapshot()["replicas"]
                replica = router.snapshot()["replicas"][0]
                assert replica["policy_evictions"] == 0
                assert replica["policy_cold_loads"] >= 1
                assert "model_fingerprint" in replica
                placements = Autoscaler(router).snapshot()["policies"]
                assert placements, "autoscaler saw no multi-policy replicas"
                assert set(placements[0]["resident_policies"]) >= {
                    "pA", "pB"
                }
                assert placements[0]["policy_cold_loads"] >= 1
                # Per-policy epoch and fingerprint ride the pool
                # snapshot for the coalesce key.
                pool = gateway.snapshot()["pools"]["default"]
                assert pool["policy_epochs"] == {}
                assert pool["model_fingerprint"] is not None
