"""Grasp2Vec workload tests (reference research/grasp2vec/*_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.research import grasp2vec
from tensor2robot_tpu.research.grasp2vec import visualization
from tensor2robot_tpu.specs import make_random_numpy


def small_model(**kwargs):
    return grasp2vec.Grasp2VecModel(
        scene_size=(32, 32),
        goal_size=(32, 32),
        resnet_size=18,
        device_type="cpu",
        **kwargs,
    )


class TestLosses:
    def test_npairs_loss_prefers_matched_pairs(self):
        rng = np.random.RandomState(0)
        emb = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        labels = jnp.arange(8, dtype=jnp.int32)
        matched = grasp2vec.npairs_loss(labels, emb, emb)
        shuffled = grasp2vec.npairs_loss(labels, emb, jnp.roll(emb, 1, axis=0))
        assert float(matched) < float(shuffled)

    def test_l2_arithmetic_loss_zero_when_consistent(self):
        pre = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
        post = jnp.zeros((4, 8))
        goal = pre  # pre - goal - post == 0
        mask = jnp.ones((4,), jnp.int32)
        loss = grasp2vec.l2_arithmetic_loss(pre, goal, post, mask)
        np.testing.assert_allclose(float(loss), 0.0, atol=1e-6)

    def test_masked_losses_empty_mask_is_zero(self):
        x = jnp.ones((4, 8))
        mask = jnp.zeros((4,), jnp.int32)
        assert float(grasp2vec.l2_arithmetic_loss(x, x, x, mask)) == 0.0
        assert float(grasp2vec.send_to_zero_loss(x, mask)) == 0.0
        assert np.isfinite(
            float(grasp2vec.cosine_arithmetic_loss(x, x, x, mask))
        )

    def test_triplet_loss_finite(self):
        rng = np.random.RandomState(0)
        pre = jnp.asarray(rng.randn(4, 8), jnp.float32)
        goal = jnp.asarray(rng.randn(4, 8), jnp.float32)
        post = jnp.asarray(rng.randn(4, 8), jnp.float32)
        loss, pairs, labels = grasp2vec.triplet_embedding_loss(pre, goal, post)
        assert np.isfinite(float(loss))
        assert pairs.shape == (8, 8)
        assert labels.shape == (8,)

    def test_keypoint_accuracy_perfect(self):
        # Keypoints exactly at quadrant centers.
        keypoints = jnp.asarray(
            [[0.5, -0.5], [-0.5, -0.5], [0.5, 0.5], [-0.5, 0.5]]
        )
        labels = jnp.arange(4)
        accuracy, loss = grasp2vec.keypoint_accuracy(keypoints, labels)
        assert float(accuracy) == 1.0
        assert np.isfinite(float(loss))


class TestGrasp2VecModel:
    # ~26s: end-to-end trainer run; the labels-subtree regression it
    # guards is also covered by the cheap forward/loss tests above.
    @pytest.mark.slow
    def test_trains_through_train_eval_model(self, tmp_path):
        """Label-less (self-supervised) end to end through the public
        trainer: generators emit no 'labels' subtree for an empty label
        spec, and the trainer must tolerate that (regression — it used to
        KeyError on batch['labels'])."""
        from tensor2robot_tpu.data.input_generators import (
            DefaultRandomInputGenerator,
        )
        from tensor2robot_tpu.train.train_eval import train_eval_model

        train_eval_model(
            small_model(),
            model_dir=str(tmp_path / "run"),
            input_generator_train=DefaultRandomInputGenerator(batch_size=2),
            max_train_steps=2,
            save_checkpoints_steps=2,
        )
        import os

        assert os.path.isdir(str(tmp_path / "run" / "checkpoints"))

    def test_specs(self):
        model = small_model()
        spec = model.get_feature_specification("train")
        assert spec["pregrasp_image"].shape == (32, 32, 3)
        assert spec["goal_image"].name == "present_image"
        assert len(model.get_label_specification("train").keys()) == 0

    def test_preprocessor_specs_declare_jpeg_source(self):
        model = small_model()
        in_spec = model.preprocessor.get_in_feature_specification("train")
        assert in_spec["pregrasp_image"].shape == (512, 640, 3)
        assert in_spec["pregrasp_image"].dtype == np.uint8
        assert in_spec["pregrasp_image"].data_format == "jpeg"

    def test_preprocess_crops_and_normalizes(self):
        model = grasp2vec.Grasp2VecModel(
            scene_size=(472, 472), goal_size=(472, 472),
            resnet_size=18, device_type="cpu",
        )
        pre = model.preprocessor
        features = make_random_numpy(
            pre.get_in_feature_specification("train"), batch_size=2
        )
        out, _ = pre.preprocess(
            features, None, mode="train", rng=jax.random.PRNGKey(0)
        )
        assert out["pregrasp_image"].shape == (2, 472, 472, 3)
        assert out["pregrasp_image"].dtype == jnp.float32
        assert float(jnp.max(out["pregrasp_image"])) <= 1.0

    def test_default_preprocessor_honors_model_sizes(self):
        # Regression: scene_size/goal_size must reach the default
        # preprocessor's crop windows, not stay pinned at 472x472.
        model = small_model()
        pre = model.preprocessor
        features = make_random_numpy(
            pre.get_in_feature_specification("train"), batch_size=1
        )
        out, _ = pre.preprocess(
            features, None, mode="train", rng=jax.random.PRNGKey(0)
        )
        assert out["pregrasp_image"].shape == (1, 32, 32, 3)
        assert out["goal_image"].shape == (1, 32, 32, 3)

    # ~12s: the three-tower forward + npairs loss; the same towers and
    # model_train_fn stay fast via test_triplet_loss_variant below, the
    # npairs math via TestLosses, and the full pipeline rides the slow
    # trainer run above.
    @pytest.mark.slow
    def test_forward_and_loss(self):
        model = small_model()
        features = {
            "pregrasp_image": jnp.asarray(
                np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32
            ),
            "postgrasp_image": jnp.asarray(
                np.random.RandomState(1).rand(2, 32, 32, 3), jnp.float32
            ),
            "goal_image": jnp.asarray(
                np.random.RandomState(2).rand(2, 32, 32, 3), jnp.float32
            ),
        }
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(variables, features, "eval")
        assert outputs["pre_vector"].shape == (2, 512)
        assert outputs["goal_spatial"].shape[0] == 2
        loss, metrics = model.model_train_fn(features, {}, outputs, "train")
        assert np.isfinite(float(loss))
        assert "embed_loss" in metrics

    def test_triplet_loss_variant(self):
        model = small_model(
            embedding_loss_fn=grasp2vec.triplet_embedding_loss
        )
        features = {
            k: jnp.zeros((2, 32, 32, 3))
            for k in ["pregrasp_image", "postgrasp_image", "goal_image"]
        }
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(variables, features, "eval")
        loss, _ = model.model_train_fn(features, {}, outputs, "train")
        assert np.isfinite(float(loss))


class TestVisualization:
    def test_heatmap_shapes(self):
        query = jnp.ones((2, 16))
        fmap = jnp.ones((2, 5, 7, 16))
        heatmaps, softmaxed = visualization.compute_heatmap(query, fmap)
        assert heatmaps.shape == (2, 5, 7, 1)
        np.testing.assert_allclose(
            np.asarray(softmaxed.sum(axis=(1, 2, 3))), 1.0, atol=1e-5
        )

    def test_soft_argmax_peak(self):
        heatmap = np.full((1, 9, 9, 1), -1e9, np.float32)
        heatmap[0, 4, 8, 0] = 0.0  # right edge center -> x=1, y=0
        xy = visualization.heatmap_soft_argmax(jnp.asarray(heatmap))
        np.testing.assert_allclose(np.asarray(xy[0, 0]), [1.0, 0.0], atol=1e-4)

    def test_render_keypoints(self):
        image = np.random.RandomState(0).rand(2, 32, 32, 3)
        locations = np.zeros((2, 4, 2))
        out = visualization.np_render_keypoints(image, locations, num_images=2)
        assert out.shape == (2, 32, 32, 3)
        assert out.dtype == np.uint8

    def test_softmax_viz_grid(self):
        image = np.random.RandomState(0).rand(1, 16, 16, 3)
        softmax = np.random.RandomState(1).rand(1, 8, 8, 4)
        out = visualization.get_softmax_viz(image, softmax)
        assert out.shape == (1, 16 * 2, 16 * 2, 3)
