"""Hook subsystem tests (reference hooks/*_test.py: checkpoint_hooks_test,
td3_test, golden values, async export)."""

import os
import time

import numpy as np
import pytest

from tensor2robot_tpu.hooks import (
    AsyncExportHookBuilder,
    CheckpointExportListener,
    ConfigLoggerHookBuilder,
    GoldenValuesHookBuilder,
    LaggedCheckpointListener,
    TD3Hooks,
    VariableLoggerHookBuilder,
    add_golden_tensor,
    load_golden_values,
)
from tensor2robot_tpu.predictors import ExportedSavedModelPredictor
from tensor2robot_tpu.train import train_eval
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel


def _fake_export_fn(counter):
    """Creates versioned dirs like the real export fn."""

    def export_fn(export_dir, global_step):
        counter["n"] += 1
        path = os.path.join(export_dir, f"{counter['n']:010d}")
        os.makedirs(path)
        with open(os.path.join(path, "model.txt"), "w") as f:
            f.write(str(global_step))
        return path

    return export_fn


class TestCheckpointExportListener:
    def test_export_and_gc(self, tmp_path):
        counter = {"n": 0}
        listener = CheckpointExportListener(
            _fake_export_fn(counter), str(tmp_path / "export"), num_versions=2
        )
        for step in range(4):
            listener.after_save(step)
        versions = sorted(os.listdir(tmp_path / "export"))
        assert versions == ["0000000003", "0000000004"]

    def test_preexisting_dirs_counted_by_gc(self, tmp_path):
        export_dir = tmp_path / "export"
        os.makedirs(export_dir / "0000000001")
        counter = {"n": 1}
        listener = CheckpointExportListener(
            _fake_export_fn(counter), str(export_dir), num_versions=2
        )
        listener.after_save(1)
        listener.after_save(2)
        versions = sorted(os.listdir(export_dir))
        assert versions == ["0000000002", "0000000003"]


class TestLaggedCheckpointListener:
    def make(self, tmp_path, counter=None):
        counter = counter or {"n": 0}
        return LaggedCheckpointListener(
            _fake_export_fn(counter),
            str(tmp_path / "latest"),
            str(tmp_path / "lagged"),
            num_versions=3,
        ), counter

    def test_lagged_stays_one_behind(self, tmp_path):
        listener, _ = self.make(tmp_path)
        listener.after_save(1)
        # First export: lagged mirrors it (nothing older exists).
        assert sorted(os.listdir(tmp_path / "latest")) == ["0000000001"]
        assert sorted(os.listdir(tmp_path / "lagged")) == ["0000000001"]
        listener.after_save(2)
        assert sorted(os.listdir(tmp_path / "latest")) == [
            "0000000001", "0000000002",
        ]
        assert sorted(os.listdir(tmp_path / "lagged")) == ["0000000001"]
        listener.after_save(3)
        assert sorted(os.listdir(tmp_path / "lagged")) == [
            "0000000001", "0000000002",
        ]

    def test_startup_resync(self, tmp_path):
        # Two prior exports, empty lagged dir: startup copies the
        # second-newest into lagged (reference :128-155).
        os.makedirs(tmp_path / "latest" / "0000000001")
        os.makedirs(tmp_path / "latest" / "0000000002")
        counter = {"n": 2}
        listener, _ = self.make(tmp_path, counter)
        assert sorted(os.listdir(tmp_path / "lagged")) == ["0000000001"]
        listener.after_save(3)
        assert sorted(os.listdir(tmp_path / "lagged")) == [
            "0000000001", "0000000002",
        ]


class _GoldenMockModel(MockT2RModel):
    def model_train_fn(self, features, labels, inference_outputs, mode):
        loss, metrics = super().model_train_fn(
            features, labels, inference_outputs, mode
        )
        add_golden_tensor(metrics, inference_outputs["a_predicted"], "logits")
        return loss, metrics


class TestGoldenValuesHook:
    def test_capture_through_training(self, tmp_path):
        model_dir = str(tmp_path / "run")
        train_eval.train_eval_model(
            t2r_model=_GoldenMockModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=4),
            model_dir=model_dir,
            max_train_steps=5,
            save_checkpoints_steps=5,
            log_every_steps=1,
            hook_builders=[GoldenValuesHookBuilder(model_dir)],
        )
        values = load_golden_values(model_dir)
        assert len(values) == 5
        assert values[0]["logits"].shape == (4, 1)
        # Values evolve as training progresses.
        assert not np.allclose(values[0]["logits"], values[-1]["logits"])


class TestAsyncExportHooks:
    def test_periodic_export_and_reload(self, tmp_path):
        model_dir = str(tmp_path / "run")
        export_dir = str(tmp_path / "export")
        builder = AsyncExportHookBuilder(
            export_dir=export_dir, save_secs=0.0, num_versions=3
        )
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=4),
            model_dir=model_dir,
            max_train_steps=4,
            save_checkpoints_steps=4,
            log_every_steps=2,
            hook_builders=[builder],
        )
        versions = sorted(os.listdir(export_dir))
        assert versions, "No exports produced"
        assert len(versions) <= 3
        # The exported artifact serves predictions (reference
        # async_export_hook_builder_tpu_test :33-66).
        predictor = ExportedSavedModelPredictor(export_dir=export_dir)
        assert predictor.restore()
        features = {"x": np.zeros((2, 3), np.float32)}
        outputs = predictor.predict(features)
        assert outputs["a_predicted"].shape == (2, 1)

    def test_td3_lagged_dirs(self, tmp_path):
        model_dir = str(tmp_path / "run")
        export_dir = str(tmp_path / "export")
        lagged_dir = str(tmp_path / "lagged")
        builder = TD3Hooks(
            export_dir=export_dir,
            lagged_export_dir=lagged_dir,
            save_secs=0.0,
            num_versions=5,
        )
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=4),
            model_dir=model_dir,
            max_train_steps=4,
            save_checkpoints_steps=2,
            log_every_steps=2,
            hook_builders=[builder],
        )
        latest_versions = sorted(os.listdir(export_dir))
        lagged_versions = sorted(os.listdir(lagged_dir))
        assert latest_versions and lagged_versions
        # Lagged holds strictly older-or-equal versions, never the newest
        # when more than one exists.
        if len(latest_versions) > 1:
            assert lagged_versions[-1] <= latest_versions[-2]
        # Both directories hold loadable artifacts.
        lagged_predictor = ExportedSavedModelPredictor(export_dir=lagged_dir)
        assert lagged_predictor.restore()


class TestMiscHooks:
    def test_variable_logger_and_config_logger_run(self, tmp_path, caplog):
        import logging as pylogging

        caplog.set_level(pylogging.INFO)
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=4),
            model_dir=str(tmp_path / "run"),
            max_train_steps=2,
            save_checkpoints_steps=2,
            log_every_steps=1,
            hook_builders=[
                VariableLoggerHookBuilder(every_steps=1),
                ConfigLoggerHookBuilder(),
            ],
        )
        messages = " ".join(r.message for r in caplog.records)
        assert "mean=" in messages
        assert "Operative config" in messages


class TestProfilingHooks:
    def test_step_timing_hook_reports_steps_per_sec(self, tmp_path):
        from tensor2robot_tpu.hooks import StepTimingHookBuilder
        from tensor2robot_tpu.train import train_eval
        from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

        builder = StepTimingHookBuilder(
            sync_every=10, flops_per_step=1e6, peak_flops=1e12
        )
        model_dir = str(tmp_path / "run")
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=8),
            model_dir=model_dir,
            max_train_steps=30,
            save_checkpoints_steps=30,
            log_every_steps=10,
            hook_builders=[builder],
        )
        rows = builder.hook.rows
        assert len(rows) >= 2
        assert all(r["steps_per_sec"] > 0 for r in rows)
        assert all(0 < r["mfu"] for r in rows)
        jsonl = os.path.join(model_dir, "profiling", "step_timing.jsonl")
        assert os.path.exists(jsonl)
        with open(jsonl) as f:
            assert len(f.read().strip().splitlines()) == len(rows)

    # ~5s (profiler capture) on 1 cpu: slow slice — tooling smoke.
    @pytest.mark.slow
    def test_profiler_hook_writes_trace(self, tmp_path):
        from tensor2robot_tpu.hooks import ProfilerHookBuilder
        from tensor2robot_tpu.train import train_eval
        from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

        model_dir = str(tmp_path / "run")
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=8),
            model_dir=model_dir,
            max_train_steps=10,
            save_checkpoints_steps=10,
            log_every_steps=10,
            hook_builders=[ProfilerHookBuilder(start_step=2, num_steps=3)],
        )
        trace_root = os.path.join(model_dir, "profiling")
        assert os.path.isdir(trace_root)
        # jax writes plugins/profile/<ts>/ under the trace dir.
        found = []
        for root, _, files in os.walk(trace_root):
            found.extend(f for f in files if f.endswith((".xplane.pb", ".trace.json.gz", ".json.gz")))
        assert found, f"no trace artifacts under {trace_root}"

    def test_profiling_hooks_fire_in_multistep_regime(self, tmp_path):
        """ctx.step advances by iterations_per_loop; windows/gates must not
        require exact step multiples."""
        from tensor2robot_tpu.hooks import (
            ProfilerHookBuilder,
            StepTimingHookBuilder,
        )
        from tensor2robot_tpu.train import train_eval
        from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

        timing = StepTimingHookBuilder(sync_every=7, flops_per_step=1e6)
        model_dir = str(tmp_path / "run")
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=8),
            model_dir=model_dir,
            max_train_steps=48,
            save_checkpoints_steps=48,
            log_every_steps=16,
            iterations_per_loop=16,
            hook_builders=[
                timing,
                ProfilerHookBuilder(start_step=10, num_steps=5),
            ],
        )
        assert timing.hook.rows, "timing hook never fired under scan dispatch"
        traces = []
        for root, _, files in os.walk(os.path.join(model_dir, "profiling")):
            traces += [f for f in files if "xplane" in f or f.endswith(".json.gz")]
        assert traces, "profiler trace missing under scan dispatch"
