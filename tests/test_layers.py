"""Layer library tests: shapes + numerics (reference layers/*_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import layers


class TestSpatialSoftmax:
    def test_delta_feature_map_recovers_location(self):
        # A single hot pixel per feature map -> expected point at its coords.
        batch, h, w, c = 2, 9, 9, 3
        features = np.full((batch, h, w, c), -1e9, np.float32)
        # Feature 0 peak at (row 0, col 8) -> x=+1, y=-1.
        features[:, 0, 8, 0] = 0.0
        # Feature 1 peak at center -> (0, 0).
        features[:, 4, 4, 1] = 0.0
        # Feature 2 peak at (row 8, col 0) -> x=-1, y=+1.
        features[:, 8, 0, 2] = 0.0
        points, softmax = layers.spatial_softmax(jnp.asarray(features))
        assert points.shape == (batch, 2 * c)
        assert softmax.shape == (batch, h, w, c)
        np.testing.assert_allclose(
            points[0], [1.0, 0.0, -1.0, -1.0, 0.0, 1.0], atol=1e-5
        )
        np.testing.assert_allclose(np.sum(softmax, axis=(1, 2)), 1.0, atol=1e-5)

    def test_gumbel_mode_runs(self):
        features = jnp.zeros((1, 4, 4, 2))
        points, _ = layers.spatial_softmax(
            features, gumbel_rng=jax.random.PRNGKey(0)
        )
        assert points.shape == (1, 4)


class TestVisionLayers:
    def test_images_to_features_shapes(self):
        model = layers.ImagesToFeaturesNet()
        images = jnp.zeros((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), images)
        points, extra = model.apply(variables, images)
        assert points.shape == (2, 64)  # 2 * num_output_maps
        assert "softmax" in extra

    def test_film_changes_output(self):
        model = layers.ImagesToFeaturesNet(num_blocks=2)
        images = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
        film = jnp.ones((2, 2 * 2 * 32))
        variables = model.init(jax.random.PRNGKey(0), images, False, film)
        with_film, _ = model.apply(variables, images, False, film)
        without, _ = model.apply(variables, images, False, jnp.zeros_like(film))
        assert not np.allclose(np.asarray(with_film), np.asarray(without))

    def test_film_wrong_size_raises(self):
        model = layers.ImagesToFeaturesNet(num_blocks=2)
        images = jnp.zeros((2, 32, 32, 3))
        with pytest.raises(ValueError):
            model.init(jax.random.PRNGKey(0), images, False, jnp.ones((2, 7)))

    def test_high_res_net(self):
        model = layers.ImagesToFeaturesHighResNet(num_blocks=3)
        images = jnp.zeros((1, 128, 128, 3))
        variables = model.init(jax.random.PRNGKey(0), images)
        points, extra = model.apply(variables, images)
        assert points.shape == (1, 64)
        assert extra["softmax"].ndim == 4

    def test_pose_head_with_aux(self):
        model = layers.ImageFeaturesToPoseNet(num_outputs=7, aux_output_dim=3)
        feats = jnp.zeros((4, 64))
        aux = jnp.zeros((4, 5))
        variables = model.init(jax.random.PRNGKey(0), feats, aux)
        pose, aux_out = model.apply(variables, feats, aux)
        assert pose.shape == (4, 7)
        assert aux_out.shape == (4, 3)

    def test_film_params_layer(self):
        model = layers.FilmParams(film_output_size=320)
        emb = jnp.zeros((2, 16))
        variables = model.init(jax.random.PRNGKey(0), emb)
        assert model.apply(variables, emb).shape == (2, 320)


class TestResNet:
    # The resnet-50 tower costs ~7s of conv compiles on 1 cpu: slow
    # slice; both v1/v2 paths stay fast at depth 18.
    @pytest.mark.parametrize(
        "size,version",
        [(18, 1), (18, 2), pytest.param(50, 2, marks=pytest.mark.slow)],
    )
    def test_shapes_and_endpoints(self, size, version):
        model = layers.ResNet(num_classes=10, resnet_size=size, version=version)
        images = jnp.zeros((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), images)
        logits, endpoints = model.apply(
            variables, images, False, None, True
        )
        assert logits.shape == (2, 10)
        expected_c = 512 * (4 if size >= 50 else 1)
        assert endpoints["block_layer4"].shape[-1] == expected_c
        assert endpoints["final_dense"].shape == (2, 10)

    def test_film_conditioning_changes_output(self):
        model = layers.ResNet(num_classes=4, resnet_size=18)
        images = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
        emb = jnp.ones((2, 8))
        variables = model.init(jax.random.PRNGKey(0), images, False, emb)
        out1 = model.apply(variables, images, False, emb)
        out2 = model.apply(variables, images, False, jnp.zeros_like(emb))
        assert not np.allclose(np.asarray(out1), np.asarray(out2))

    # ~12s: resnet-50 init + two applies just for the v1-bottleneck
    # FiLM width regression; FiLM conditioning stays fast on resnet-18
    # above, and the 50/v2 tower rides the slow shapes column already.
    @pytest.mark.slow
    def test_film_v1_bottleneck_runs(self):
        # Regression: FiLM must be applied at the filters-wide point in v1
        # bottleneck blocks (2*filters generator outputs vs 4*filters bn3).
        model = layers.ResNet(num_classes=2, resnet_size=50, version=1)
        images = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3))
        emb = jnp.ones((1, 8))
        variables = model.init(jax.random.PRNGKey(0), images, False, emb)
        out1 = model.apply(variables, images, False, emb)
        out2 = model.apply(variables, images, False, jnp.zeros_like(emb))
        assert not np.allclose(np.asarray(out1), np.asarray(out2))

    def test_batch_stats_update_in_train(self):
        model = layers.ResNet(num_classes=2, resnet_size=18)
        images = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), images)
        _, mutated = model.apply(
            variables, images, True, mutable=["batch_stats"]
        )
        assert "batch_stats" in mutated

    def test_invalid_size_raises(self):
        with pytest.raises(ValueError):
            layers.get_block_sizes(42)


class TestSnail:
    def test_causal_conv_shape_preserved(self):
        model = layers.CausalConv(filters=8, dilation_rate=2)
        x = jnp.zeros((3, 16, 4))
        variables = model.init(jax.random.PRNGKey(0), x)
        assert model.apply(variables, x).shape == (3, 16, 8)

    def test_causality(self):
        # Changing a later timestep must not change earlier outputs.
        model = layers.TCBlock(sequence_length=8, filters=4)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 3))
        variables = model.init(jax.random.PRNGKey(1), x)
        y1 = model.apply(variables, x)
        x2 = x.at[0, 5, :].set(100.0)
        y2 = model.apply(variables, x2)
        np.testing.assert_allclose(
            np.asarray(y1[0, :5]), np.asarray(y2[0, :5]), atol=1e-5
        )
        assert y1.shape == (1, 8, 3 + 3 * 4)  # log2(8)=3 dense blocks

    def test_attention_block_causal(self):
        model = layers.AttentionBlock(key_size=8, value_size=6)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 10, 5))
        variables = model.init(jax.random.PRNGKey(1), x)
        out, end_points = model.apply(variables, x)
        assert out.shape == (2, 10, 5 + 6)
        probs = np.asarray(end_points["attn_prob"])
        # Upper triangle must be exactly zero.
        for i in range(10):
            np.testing.assert_allclose(probs[:, i, i + 1 :], 0.0, atol=1e-7)
            np.testing.assert_allclose(
                probs[:, i, : i + 1].sum(-1), 1.0, atol=1e-5
            )

    def test_masked_softmax_rows_sum_to_one(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 6))
        probs = layers.causally_masked_softmax(logits)
        np.testing.assert_allclose(
            np.asarray(probs.sum(-1)), 1.0, atol=1e-5
        )


class TestMDN:
    def test_param_packing_and_log_prob(self):
        num_alphas, d = 3, 2
        rng = np.random.RandomState(0)
        params = rng.randn(4, num_alphas + 2 * num_alphas * d).astype(np.float32)
        gm = layers.get_mixture_distribution(jnp.asarray(params), num_alphas, d)
        x = jnp.asarray(rng.randn(4, d).astype(np.float32))
        logp = gm.log_prob(x)
        assert logp.shape == (4,)
        # Manual reference computation.
        alphas = params[:, :num_alphas]
        mus = params[:, num_alphas : num_alphas + num_alphas * d].reshape(
            4, num_alphas, d
        )
        sigmas = (
            np.log1p(np.exp(params[:, num_alphas + num_alphas * d :]))
            .reshape(4, num_alphas, d)
            + 1e-4
        )
        log_mix = alphas - np.log(np.sum(np.exp(alphas), -1, keepdims=True))
        comp = -0.5 * np.sum(
            ((np.asarray(x)[:, None] - mus) / sigmas) ** 2, -1
        ) - np.sum(np.log(sigmas), -1) - 0.5 * d * np.log(2 * np.pi)
        expected = np.log(np.sum(np.exp(log_mix + comp), -1))
        np.testing.assert_allclose(np.asarray(logp), expected, rtol=1e-4)

    def test_wrong_param_size_raises(self):
        with pytest.raises(ValueError):
            layers.get_mixture_distribution(jnp.zeros((2, 5)), 3, 2)

    def test_approximate_mode_picks_top_component(self):
        logits = jnp.asarray([[10.0, -10.0]])
        mus = jnp.asarray([[[1.0, 2.0], [3.0, 4.0]]])
        sigmas = jnp.ones((1, 2, 2))
        gm = layers.GaussianMixture(logits, mus, sigmas)
        np.testing.assert_allclose(
            np.asarray(gm.approximate_mode()), [[1.0, 2.0]]
        )

    def test_decoder_end_to_end(self):
        model = layers.MDNDecoder(num_mixture_components=2)
        inputs = jnp.zeros((4, 6, 8))  # works over extra batch dims
        variables = model.init(jax.random.PRNGKey(0), inputs, 3)
        action, gm = model.apply(variables, inputs, 3)
        assert action.shape == (4, 6, 3)
        targets = jnp.zeros((4, 6, 3))
        loss = layers.mdn_loss(gm, targets)
        assert np.isfinite(float(loss))

    def test_sample_shape(self):
        gm = layers.GaussianMixture(
            jnp.zeros((5, 3)), jnp.zeros((5, 3, 2)), jnp.ones((5, 3, 2))
        )
        assert gm.sample(jax.random.PRNGKey(0)).shape == (5, 2)


class TestTEC:
    def test_embed_fullstate(self):
        model = layers.EmbedFullstate(embed_size=16)
        x = jnp.zeros((4, 10))
        variables = model.init(jax.random.PRNGKey(0), x)
        assert model.apply(variables, x).shape == (4, 16)

    def test_embed_condition_images_fc(self):
        model = layers.EmbedConditionImages(fc_layers=(32, 8))
        images = jnp.zeros((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), images)
        assert model.apply(variables, images).shape == (2, 8)

    def test_embed_condition_images_rank_check(self):
        model = layers.EmbedConditionImages()
        with pytest.raises(ValueError):
            model.init(jax.random.PRNGKey(0), jnp.zeros((2, 64, 64)))

    def test_reduce_temporal_embeddings(self):
        model = layers.ReduceTemporalEmbeddings(output_size=12)
        x = jnp.zeros((3, 40, 20))
        variables = model.init(jax.random.PRNGKey(0), x)
        assert model.apply(variables, x).shape == (3, 12)

    def test_reduce_temporal_avg_mode(self):
        model = layers.ReduceTemporalEmbeddings(
            output_size=12, combine_mode="avg"
        )
        x = jnp.zeros((3, 40, 20))
        variables = model.init(jax.random.PRNGKey(0), x)
        assert model.apply(variables, x).shape == (3, 12)

    def test_contrastive_loss_zero_for_perfect(self):
        # Positive at distance 0, negative beyond margin -> zero loss.
        anchor = jnp.asarray([[1.0, 0.0]])
        emb = jnp.asarray([[1.0, 0.0], [-5.0, 0.0]])
        labels = jnp.asarray([True, False])
        loss = layers.contrastive_loss(labels, anchor, emb)
        np.testing.assert_allclose(float(loss), 0.0, atol=1e-6)

    @pytest.mark.parametrize(
        "mode",
        [
            "default",
            "both_directions",
            "reverse_direction",
            "cross_entropy",
            "triplet",
        ],
    )
    def test_embedding_contrastive_modes(self, mode):
        rng = jax.random.PRNGKey(0)
        inf_e = jax.random.normal(rng, (4, 2, 8))
        con_e = jax.random.normal(jax.random.PRNGKey(1), (4, 3, 8))
        inf_e = inf_e / jnp.linalg.norm(inf_e, axis=-1, keepdims=True)
        con_e = con_e / jnp.linalg.norm(con_e, axis=-1, keepdims=True)
        loss = layers.compute_embedding_contrastive_loss(
            inf_e, con_e, contrastive_loss_mode=mode
        )
        assert np.isfinite(float(loss))

    def test_embedding_contrastive_bad_mode(self):
        with pytest.raises(ValueError):
            layers.compute_embedding_contrastive_loss(
                jnp.zeros((2, 1, 4)),
                jnp.zeros((2, 1, 4)),
                contrastive_loss_mode="nope",
            )
