"""Tests for the runtime lock sanitizer (testing/locksmith.py).

The cycle detector must fire DETERMINISTICALLY from a sequentially
executed inversion (no timing, no real deadlock needed); the hold
budget must fire when a chaos delay lands inside a critical section;
the off path must hand back the plain threading primitives; and the
report artifact must round-trip deterministically.
"""

import json
import threading
import time

import pytest

from tensor2robot_tpu.testing import chaos, locksmith


@pytest.fixture(autouse=True)
def _armed(monkeypatch):
    monkeypatch.setenv("T2R_LOCK_SANITIZER", "1")
    locksmith.reset()
    yield
    locksmith.reset()


class TestOrderCycleDetection:
    def test_sequential_inversion_detected_without_deadlock(self):
        # ONE thread, fully sequential: A->B then B->A. A timing-based
        # detector would need two racing threads to actually collide;
        # the order-graph detector fires on the edge alone.
        a = locksmith.make_lock("T._a")
        b = locksmith.make_lock("T._b")
        with a:
            with b:
                pass
        assert locksmith.violations(locksmith.ORDER_CYCLE) == []
        with b:
            with a:
                pass
        cycles = locksmith.violations(locksmith.ORDER_CYCLE)
        assert len(cycles) == 1
        assert sorted(cycles[0]["edge"]) == ["T._a", "T._b"]
        # Both acquisition paths are reported as stacks.
        assert cycles[0]["stack"] and cycles[0]["held_stack"]
        assert cycles[0]["reverse_stacks"]

    def test_cross_thread_inversion_detected(self):
        a = locksmith.make_lock("T._a")
        b = locksmith.make_lock("T._b")
        with a:
            with b:
                pass

        def invert():
            with b:
                with a:
                    pass

        t = threading.Thread(target=invert)
        t.start()
        t.join()
        assert len(locksmith.violations(locksmith.ORDER_CYCLE)) == 1

    def test_three_lock_transitive_cycle(self):
        a = locksmith.make_lock("T._a")
        b = locksmith.make_lock("T._b")
        c = locksmith.make_lock("T._c")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass  # closes A->B->C->A
        cycles = locksmith.violations(locksmith.ORDER_CYCLE)
        assert len(cycles) == 1
        assert cycles[0]["locks"] == ["T._a", "T._b", "T._c"]

    def test_consistent_order_clean(self):
        a = locksmith.make_lock("T._a")
        b = locksmith.make_lock("T._b")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert locksmith.violations(locksmith.ORDER_CYCLE) == []

    def test_rlock_reentry_is_one_logical_hold(self):
        r = locksmith.make_rlock("T._r")
        with r:
            with r:
                pass
        assert locksmith.violations() == []
        assert all(
            e["held"] != e["acquired"]
            for e in locksmith.report()["edges"]
        )


class TestHoldBudget:
    def test_chaos_delay_inside_critical_section_fires(self, monkeypatch):
        # A chaos `delay` clause landing inside a critical section is
        # exactly the production scenario the budget exists for.
        monkeypatch.setenv("T2R_LOCK_HOLD_BUDGET_MS", "20")
        monkeypatch.setenv("T2R_CHAOS", "lockhold:1:delay:50")
        chaos.reset()
        lock = locksmith.make_lock("T._slow")
        with lock:
            fired = chaos.maybe_fire("lockhold")
            assert fired, "seeded chaos plan must fire deterministically"
        over = locksmith.violations(locksmith.HOLD_BUDGET)
        assert len(over) == 1
        assert over[0]["lock"] == "T._slow"
        assert over[0]["hold_ms"] > over[0]["budget_ms"] == 20
        # The sleep also records blocking-under-lock — report, not kill.
        assert locksmith.violations(locksmith.BLOCKING_UNDER_LOCK)
        chaos.reset()

    def test_within_budget_is_clean(self, monkeypatch):
        monkeypatch.setenv("T2R_LOCK_HOLD_BUDGET_MS", "5000")
        lock = locksmith.make_lock("T._fast")
        with lock:
            pass
        assert locksmith.violations(locksmith.HOLD_BUDGET) == []

    def test_budget_zero_exempts_designed_long_holds(self, monkeypatch):
        monkeypatch.setenv("T2R_LOCK_HOLD_BUDGET_MS", "1")
        lock = locksmith.make_lock("T._load", budget_ms=0)
        with lock:
            time.sleep(0.02)
        assert locksmith.violations(locksmith.HOLD_BUDGET) == []

    def test_per_lock_budget_overrides_flag(self, monkeypatch):
        monkeypatch.setenv("T2R_LOCK_HOLD_BUDGET_MS", "60000")
        lock = locksmith.make_lock("T._tight", budget_ms=5)
        with lock:
            time.sleep(0.02)
        over = locksmith.violations(locksmith.HOLD_BUDGET)
        assert len(over) == 1 and over[0]["budget_ms"] == 5


class TestBlockingUnderLock:
    def test_sleep_under_lock_reported(self):
        lock = locksmith.make_lock("T._l")
        with lock:
            time.sleep(0.001)
        bl = locksmith.violations(locksmith.BLOCKING_UNDER_LOCK)
        assert len(bl) == 1
        assert bl[0]["locks"] == ["T._l"]

    def test_sleep_without_lock_not_reported(self):
        locksmith.make_lock("T._l")  # hook installed, nothing held
        time.sleep(0.001)
        assert locksmith.violations(locksmith.BLOCKING_UNDER_LOCK) == []

    def test_untimed_condition_wait_while_other_lock_held(self):
        outer = locksmith.make_lock("T._outer")
        cond = locksmith.make_condition("T._cond")

        def late_notify():
            time.sleep(0.02)
            with cond:
                cond.notify_all()

        t = threading.Thread(target=late_notify)
        t.start()
        with outer:
            with cond:
                cond.wait()
        t.join()
        waits = [
            v
            for v in locksmith.violations(locksmith.BLOCKING_UNDER_LOCK)
            if "wait" in v["call"]
        ]
        assert len(waits) == 1

    def test_timed_condition_wait_is_fine(self):
        outer = locksmith.make_lock("T._outer")
        cond = locksmith.make_condition("T._cond")
        with outer:
            with cond:
                cond.wait(timeout=0.01)
        waits = [
            v
            for v in locksmith.violations(locksmith.BLOCKING_UNDER_LOCK)
            if "wait" in v["call"]
        ]
        assert waits == []

    def test_condition_wait_releases_hold_accounting(self, monkeypatch):
        # wait() releases the lock; the wall-clock spent parked must
        # NOT count against the hold budget.
        monkeypatch.setenv("T2R_LOCK_HOLD_BUDGET_MS", "20")
        cond = locksmith.make_condition("T._cond")

        def notify_later():
            time.sleep(0.06)
            with cond:
                cond.notify_all()

        t = threading.Thread(target=notify_later)
        t.start()
        with cond:
            cond.wait(timeout=1.0)
        t.join()
        assert locksmith.violations(locksmith.HOLD_BUDGET) == []


class TestOffPath:
    def test_disabled_returns_plain_threading_primitives(self, monkeypatch):
        monkeypatch.setenv("T2R_LOCK_SANITIZER", "0")
        lock = locksmith.make_lock("T._l")
        rlock = locksmith.make_rlock("T._r")
        cond = locksmith.make_condition("T._c")
        assert type(lock) is type(threading.Lock())
        assert type(rlock) is type(threading.RLock())
        assert type(cond) is threading.Condition
        with lock:
            pass
        with rlock:
            pass
        with cond:
            pass
        assert locksmith.report()["edges"] == []
        assert locksmith.violations() == []

    def test_disabled_reset_uninstalls_sleep_hook(self, monkeypatch):
        locksmith.make_lock("T._l")  # enabled: hook goes in
        assert time.sleep is not locksmith._real_sleep
        monkeypatch.setenv("T2R_LOCK_SANITIZER", "0")
        locksmith.reset()
        assert time.sleep is locksmith._real_sleep


class TestReportArtifact:
    def test_round_trip_and_determinism(self, tmp_path):
        a = locksmith.make_lock("T._a")
        b = locksmith.make_lock("T._b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        path = str(tmp_path / "locks.json")
        locksmith.dump_report(path)
        loaded = locksmith.load_report(path)
        assert loaded["schema"] == "t2r-locksmith-v1"
        assert [
            (e["held"], e["acquired"]) for e in loaded["edges"]
        ] == [("T._a", "T._b"), ("T._b", "T._a")]
        kinds = [v["kind"] for v in loaded["violations"]]
        assert locksmith.ORDER_CYCLE in kinds
        # Stacks are repo-relative path:line:func frames.
        frame = loaded["edges"][0]["stack"][-1]
        assert frame.startswith("tests/test_locksmith.py:")
        # Byte-identical on re-dump: the artifact is deterministic.
        first = open(path).read()
        locksmith.dump_report(path)
        assert open(path).read() == first

    def test_load_rejects_foreign_json(self, tmp_path):
        path = tmp_path / "other.json"
        path.write_text(json.dumps({"schema": "something-else"}))
        with pytest.raises(ValueError):
            locksmith.load_report(str(path))

    def test_reset_clears_graph_and_violations(self):
        a = locksmith.make_lock("T._a")
        with a:
            time.sleep(0.001)
        assert locksmith.violations()
        locksmith.reset()
        assert locksmith.violations() == []
        assert locksmith.report()["edges"] == []
