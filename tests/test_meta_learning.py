"""Meta-learning subsystem tests (reference meta_learning/*_test.py,
especially maml_inner_loop_test.py numerics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.data.encoder import encode_example
from tensor2robot_tpu.data.parser import SpecParser
from tensor2robot_tpu.meta_learning import (
    FixedLenMetaExamplePreprocessor,
    MAMLInnerLoopGradientDescent,
    MAMLModel,
    MAMLPreprocessorV2,
    create_maml_feature_spec,
    create_maml_label_spec,
    create_metaexample_spec,
    meta_example,
    meta_tfdata,
    stack_intra_task_episodes,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    flatten_spec_structure,
)
from tensor2robot_tpu.utils.mocks import MockT2RModel

LEARNING_RATE = 0.001
COEFF_A_VALUE = 0.25
X_INIT = 2.0


class TestMetaTfdata:
    def test_flatten_unflatten_roundtrip(self):
        x = jnp.arange(24.0).reshape(2, 3, 4)
        flat = meta_tfdata.flatten_batch_examples({"x": x})
        assert flat["x"].shape == (6, 4)
        back = meta_tfdata.unflatten_batch_examples(flat, 3)
        np.testing.assert_array_equal(back["x"], x)

    def test_rank1_passes_through(self):
        x = jnp.arange(4.0)
        flat = meta_tfdata.flatten_batch_examples({"x": x})
        assert flat["x"].shape == (4,)

    def test_merge_expand(self):
        x = jnp.zeros((2, 3, 4, 5))
        merged = meta_tfdata.merge_first_n_dims({"x": x}, 3)
        assert merged["x"].shape == (24, 5)
        expanded = meta_tfdata.expand_batch_dims(merged, (2, 3, 4))
        assert expanded["x"].shape == (2, 3, 4, 5)

    def test_multi_batch_apply(self):
        def fn(d):
            return {"y": d["x"] * 2.0}

        out = meta_tfdata.multi_batch_apply(fn, 2, {"x": jnp.ones((2, 3, 5))})
        assert out["y"].shape == (2, 3, 5)
        np.testing.assert_allclose(out["y"], 2.0)

    def test_split_train_val_and_tile(self):
        x = jnp.arange(12.0).reshape(2, 6)
        train, val = meta_tfdata.split_train_val({"x": x}, 4)
        assert train["x"].shape == (2, 4)
        assert val["x"].shape == (2, 2)
        tiled = meta_tfdata.tile_val_mode(val, 3)
        assert tiled["x"].shape == (2, 6)


def _quadratic_setup(**inner_kwargs):
    """The reference fixture: minimize (x * coeff_a - 0)^2 with x init 2.0
    (maml_inner_loop_test.py:25-62)."""
    inner = MAMLInnerLoopGradientDescent(
        learning_rate=LEARNING_RATE, **inner_kwargs
    )
    params = {"x": jnp.asarray([X_INIT])}
    variables = {"params": params}
    features = {"coeff_a": jnp.asarray([COEFF_A_VALUE])}
    labels = {"target": jnp.asarray([0.0])}

    def inference_network_fn(variables, feats, mode, labels=None):
        return {"prediction": variables["params"]["x"] * feats["coeff_a"]}, {}

    def model_train_fn(feats, labs, outputs, mode):
        return jnp.mean(jnp.square(outputs["prediction"] - labs["target"]))

    return inner, variables, features, labels, inference_network_fn, model_train_fn


class TestMAMLInnerLoop:
    @pytest.mark.parametrize("learn_inner_lr", [False, True])
    @pytest.mark.parametrize("use_second_order", [False, True])
    def test_inner_losses_decrease(self, learn_inner_lr, use_second_order):
        inner, variables, features, labels, net_fn, train_fn = (
            _quadratic_setup(
                use_second_order=use_second_order,
                learn_inner_lr=learn_inner_lr,
            )
        )
        inner_lrs = inner.create_inner_lr_params(variables["params"])
        inputs = [(features, labels)] * 3
        outputs, inner_outputs, inner_losses = inner.inner_loop(
            variables, inputs, net_fn, train_fn, "train",
            inner_lrs=inner_lrs or None,
        )
        # Progress with every adaptation step (reference :188-195).
        values = [float(l) for l in inner_losses]
        for previous, current in zip(values, values[1:]):
            assert current < previous
        # 3 entries: 2 gradient steps + final monitored pass.
        assert len(inner_losses) == 3
        assert len(inner_outputs) == 3
        # Conditioned val output differs from unconditioned.
        uncond, cond = outputs
        assert not np.allclose(
            np.asarray(uncond["prediction"]), np.asarray(cond["prediction"])
        )

    def test_outer_optimization_converges(self):
        inner, variables, features, labels, net_fn, train_fn = (
            _quadratic_setup(use_second_order=True)
        )

        def outer_loss(params):
            outputs, _, _ = inner.inner_loop(
                {"params": params},
                [(features, labels)] * 3,
                net_fn,
                train_fn,
                "train",
            )
            conditioned = outputs[1]
            return train_fn(features, labels, conditioned, "train")

        params = variables["params"]
        x_previous = float(params["x"][0])
        grad_fn = jax.jit(jax.grad(outer_loss))
        for _ in range(10):
            grads = grad_fn(params)
            params = jax.tree_util.tree_map(
                lambda p, g: p - LEARNING_RATE * g, params, grads
            )
            x_new = float(params["x"][0])
            assert x_new < x_previous  # reference :209-216
            x_previous = x_new

    def test_second_order_changes_meta_gradient(self):
        # The JAX analogue of "the second-order graph is larger": the meta
        # gradients must differ numerically between FOMAML and full MAML.
        metas = {}
        for use_second_order in (False, True):
            inner, variables, features, labels, net_fn, train_fn = (
                _quadratic_setup(use_second_order=use_second_order)
            )

            def outer_loss(params):
                outputs, _, _ = inner.inner_loop(
                    {"params": params},
                    [(features, labels)] * 3,
                    net_fn,
                    train_fn,
                    "train",
                )
                return train_fn(features, labels, outputs[1], "train")

            metas[use_second_order] = float(
                jax.grad(outer_loss)(variables["params"])["x"][0]
            )
        assert metas[False] != metas[True]

    def test_learned_inner_lr_receives_gradient(self):
        inner, variables, features, labels, net_fn, train_fn = (
            _quadratic_setup(learn_inner_lr=True)
        )
        inner_lrs = inner.create_inner_lr_params(variables["params"])
        assert float(inner_lrs["x"]) == pytest.approx(LEARNING_RATE)

        def outer_loss(params, lrs):
            outputs, _, _ = inner.inner_loop(
                {"params": params},
                [(features, labels)] * 3,
                net_fn,
                train_fn,
                "train",
                inner_lrs=lrs,
            )
            return train_fn(features, labels, outputs[1], "train")

        lr_grads = jax.grad(outer_loss, argnums=1)(
            variables["params"], inner_lrs
        )
        assert float(jnp.abs(lr_grads["x"])) > 0.0

    def test_var_scope_freezes_other_params(self):
        inner = MAMLInnerLoopGradientDescent(
            learning_rate=0.1, var_scope="adapt"
        )
        params = {"adapt": jnp.ones((2,)), "frozen": jnp.ones((2,))}
        features = {"coeff_a": jnp.ones((2,))}
        labels = {"target": jnp.zeros((2,))}

        def net_fn(variables, feats, mode, labels=None):
            p = variables["params"]
            return {"prediction": (p["adapt"] + p["frozen"]) * feats["coeff_a"]}, {}

        def train_fn(feats, labs, outputs, mode):
            return jnp.mean(jnp.square(outputs["prediction"] - labs["target"]))

        _, _, losses = inner.inner_loop(
            {"params": params}, [(features, labels)] * 3, net_fn, train_fn,
            "train",
        )
        assert float(losses[-1]) < float(losses[0])


class TestMAMLSpecs:
    def test_create_maml_feature_spec_structure(self):
        model = MockT2RModel()
        spec = create_maml_feature_spec(
            model.get_feature_specification("train"),
            model.get_label_specification("train"),
        )
        flat = flatten_spec_structure(spec)
        assert "condition/features/x" in flat.keys()
        assert "condition/labels/a_target" in flat.keys()
        assert "inference/features/x" in flat.keys()
        # Per-task samples dim is a wildcard; names gain routing prefixes.
        assert flat["condition/features/x"].shape == (None, 3)
        assert flat["condition/features/x"].name.startswith(
            "condition_features/"
        )

    def test_create_maml_label_spec(self):
        model = MockT2RModel()
        spec = create_maml_label_spec(model.get_label_specification("train"))
        flat = flatten_spec_structure(spec)
        assert flat["a_target"].shape == (None, 1)
        assert flat["a_target"].name.startswith("meta_labels/")

    def test_metaexample_spec_and_stacking(self):
        model = MockT2RModel()
        spec = create_metaexample_spec(
            model.get_feature_specification("train"), 2, "condition"
        )
        assert spec["x/0"].name == "condition_ep0/measured_position"
        assert spec["x/1"].name == "condition_ep1/measured_position"
        tensors = TensorSpecStruct()
        tensors["x/0"] = jnp.zeros((4, 3))
        tensors["x/1"] = jnp.ones((4, 3))
        stacked = stack_intra_task_episodes(tensors, 2)
        assert stacked["x"].shape == (4, 2, 3)
        np.testing.assert_allclose(stacked["x"][:, 1], 1.0)


class TestMAMLPreprocessor:
    def test_preprocess_roundtrip(self):
        model = MockT2RModel()
        preprocessor = MAMLPreprocessorV2(model.preprocessor)
        tasks, num_condition, num_inference = 2, 4, 3
        features = TensorSpecStruct()
        features["condition/features/x"] = np.zeros(
            (tasks, num_condition, 3), np.float32
        )
        features["condition/labels/a_target"] = np.zeros(
            (tasks, num_condition, 1), np.float32
        )
        features["inference/features/x"] = np.zeros(
            (tasks, num_inference, 3), np.float32
        )
        labels = TensorSpecStruct()
        labels["a_target"] = np.zeros((tasks, num_inference, 1), np.float32)
        out_features, out_labels = preprocessor.preprocess(
            features, labels, mode="train", rng=jax.random.PRNGKey(0)
        )
        assert out_features["condition/features/x"].shape == (
            tasks, num_condition, 3,
        )
        assert out_features["inference/features/x"].shape == (
            tasks, num_inference, 3,
        )
        assert out_labels["a_target"].shape == (tasks, num_inference, 1)


class _MockMAMLModel(MAMLModel):
    """Concrete MAML model: selects the classifier logit as both outputs."""

    def _select_inference_output(self, predictions):
        predictions["condition_output"] = predictions[
            "full_condition_output/a_predicted"
        ]
        predictions["inference_output"] = predictions[
            "full_inference_output/a_predicted"
        ]
        return predictions


def _meta_batch(tasks=4, num_condition=8, num_inference=8, seed=0):
    """Linearly separable per-task data with task-dependent label flips so
    adaptation has something to learn."""
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, size=(tasks, num_condition + num_inference, 3))
    y = (x.sum(axis=-1, keepdims=True) > 0).astype(np.float32)
    features = TensorSpecStruct()
    features["condition/features/x"] = x[:, :num_condition].astype(np.float32)
    features["condition/labels/a_target"] = y[:, :num_condition]
    features["inference/features/x"] = x[:, num_condition:].astype(np.float32)
    labels = TensorSpecStruct()
    labels["a_target"] = y[:, num_condition:]
    return features, labels


class TestMAMLModel:
    def make_model(self, **kwargs):
        base = MockT2RModel(device_type="cpu", use_batch_norm=False)
        return _MockMAMLModel(base_model=base, **kwargs)

    def test_specs_match_reference_layout(self):
        model = self.make_model()
        feature_spec = flatten_spec_structure(
            model.get_feature_specification("train")
        )
        assert "condition/features/x" in feature_spec.keys()
        packing = model.get_feature_specification_for_packing("train")
        assert "x" in flatten_spec_structure(packing).keys()

    def test_init_and_forward(self):
        model = self.make_model(num_inner_loop_steps=2)
        features, labels = _meta_batch()
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        assert "base" in variables["params"]
        outputs, mutable = model.inference_network_fn(
            variables, features, "train"
        )
        assert mutable == {}
        assert outputs["inference_output"].shape == (4, 8, 1)
        assert outputs["condition_output"].shape == (4, 8, 1)
        # k+1 = 3 condition step outputs recorded.
        assert "full_condition_outputs/output_2/a_predicted" in outputs.keys()
        loss, metrics = model.model_train_fn(
            features, labels, outputs, "train"
        )
        assert np.isfinite(float(loss))
        assert "inner_loss_0" in metrics and "inner_loss_2" in metrics

    def test_missing_selection_keys_raises(self):
        class BadModel(MAMLModel):
            def _select_inference_output(self, predictions):
                return predictions

        base = MockT2RModel(device_type="cpu", use_batch_norm=False)
        model = BadModel(base_model=base)
        features, _ = _meta_batch()
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        with pytest.raises(ValueError, match="condition_output"):
            model.inference_network_fn(variables, features, "train")

    def test_meta_training_reduces_loss(self):
        model = self.make_model(
            num_inner_loop_steps=1, inner_learning_rate=0.1,
        )
        features, labels = _meta_batch()
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        optimizer = model.create_optimizer()

        @jax.jit
        def train_step(params, opt_state):
            def loss_fn(p):
                outputs, _ = model.inference_network_fn(
                    {"params": p}, features, "train"
                )
                loss, _ = model.model_train_fn(
                    features, labels, outputs, "train"
                )
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        params = variables["params"]
        opt_state = optimizer.init(params)
        first_loss = None
        for _ in range(30):
            params, opt_state, loss = train_step(params, opt_state)
            if first_loss is None:
                first_loss = float(loss)
        assert float(loss) < first_loss

    def test_learned_inner_lr_is_meta_param(self):
        model = self.make_model(learn_inner_lr=True)
        features, _ = _meta_batch()
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        lr_leaves = jax.tree_util.tree_leaves(
            variables["params"]["inner_lrs"]
        )
        assert lr_leaves and all(leaf.shape == () for leaf in lr_leaves)


class TestMetaExample:
    def test_make_meta_example_and_parse(self):
        model = MockT2RModel()
        base_pre = model.preprocessor
        meta_pre = FixedLenMetaExamplePreprocessor(
            base_pre,
            num_condition_samples_per_task=2,
            num_inference_samples_per_task=1,
        )
        feature_spec = model.get_feature_specification("train")
        label_spec = model.get_label_specification("train")

        def episode(seed):
            rng = np.random.RandomState(seed)
            values = TensorSpecStruct()
            values["x"] = rng.rand(3).astype(np.float32)
            values["a_target"] = rng.rand(1).astype(np.float32)
            spec = TensorSpecStruct()
            spec["x"] = feature_spec["x"]
            spec["a_target"] = label_spec["a_target"]
            from tensor2robot_tpu.proto import example_pb2

            proto = example_pb2.Example()
            proto.ParseFromString(encode_example(spec, values))
            return proto

        meta = meta_example.make_meta_example(
            [episode(0), episode(1)], [episode(2)]
        )
        serialized = meta.SerializeToString()

        # Parse through the FixedLen MetaExample spec: names must line up.
        parser = SpecParser(meta_pre.get_in_feature_specification("train"))
        parsed = parser.parse_batch([serialized, serialized])
        assert parsed["condition/features/x/0"].shape == (2, 3)
        assert parsed["condition/features/x/1"].shape == (2, 3)
        assert parsed["inference/features/x/0"].shape == (2, 3)

        # And the full preprocess produces task-structured tensors.
        label_parser = SpecParser(meta_pre.get_in_label_specification("train"))
        parsed_labels = label_parser.parse_batch([serialized, serialized])
        out_features, out_labels = meta_pre.preprocess(
            parsed, parsed_labels, mode="train", rng=jax.random.PRNGKey(0)
        )
        assert out_features["condition/features/x"].shape == (2, 2, 3)
        assert out_features["inference/features/x"].shape == (2, 1, 3)
        assert out_labels["a_target"].shape == (2, 1, 1)
