"""Legacy TrainValPair meta-learning path (meta_models.py): spec algebra,
select_mode switching, MetaPreprocessor round trip, MetalearningModel
plumbing with a concrete RL^2-style subclass over the mock model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.meta_learning.meta_models import (
    MetalearningModel,
    MetaPreprocessor,
    create_meta_spec,
    select_mode,
)
from tensor2robot_tpu.specs import TensorSpecStruct
from tensor2robot_tpu.utils.mocks import MockT2RModel

TRAIN = "train"


class TestCreateMetaSpec:
    def test_structure_names_and_optionality(self):
        base = MockT2RModel()
        spec = create_meta_spec(
            base.get_feature_specification(TRAIN), "features", 5, 3
        )
        # Flattened paths carry both branches plus the switch.
        assert "train/x" in spec
        assert "val/x" in spec
        assert spec.val_mode.dtype == np.bool_
        assert spec.val_mode.name == "val_mode/features"
        # Serialized names are branch-prefixed (reference
        # _create_meta_spec via copy_tensorspec :773-778).
        assert spec["train/x"].name.startswith("train/")
        assert spec["val/x"].name.startswith("val/")
        # Branch batch dims are the per-task sample counts, non-optional.
        assert spec["train/x"].shape[0] == 5
        assert spec["val/x"].shape[0] == 3
        assert not spec["train/x"].is_optional
        assert not spec["val/x"].is_optional

    def test_rejects_unknown_spec_type(self):
        base = MockT2RModel()
        with pytest.raises(ValueError, match="spec_type"):
            create_meta_spec(
                base.get_feature_specification(TRAIN), "outputs", 5, 3
            )


class TestSelectMode:
    def test_switches_whole_tasks(self):
        train = {"a": jnp.zeros((4, 2, 3))}
        val = {"a": jnp.ones((4, 2, 3))}
        val_mode = jnp.array([[True], [False], [True], [False]])
        out = select_mode(val_mode, train, val)
        got = np.asarray(out["a"])[:, 0, 0]
        np.testing.assert_array_equal(got, [1.0, 0.0, 1.0, 0.0])

    def test_structure_mismatch_raises(self):
        with pytest.raises(ValueError, match="identical train/val"):
            select_mode(
                jnp.asarray(True),
                {"a": jnp.zeros((2,))},
                {"b": jnp.zeros((2,))},
            )

    def test_scalar_mode(self):
        train = {"a": jnp.zeros((2, 2))}
        val = {"a": jnp.ones((2, 2))}
        np.testing.assert_array_equal(
            np.asarray(select_mode(jnp.asarray(True), train, val)["a"]),
            np.ones((2, 2)),
        )


def _meta_batch(model, num_tasks, n_train, n_val, with_labels=True):
    """Builds a [tasks, samples, ...] TrainValPair batch for the mock spec
    (one feature 'x' of shape (3,), one label 'a_target' of shape (1,))."""
    rng = np.random.RandomState(0)
    features = TensorSpecStruct()
    features["train/x"] = rng.rand(num_tasks, n_train, 3).astype(np.float32)
    features["val/x"] = rng.rand(num_tasks, n_val, 3).astype(np.float32)
    features["val_mode"] = np.zeros((num_tasks, 1), bool)
    labels = None
    if with_labels:
        labels = TensorSpecStruct()
        labels["train/a_target"] = rng.randint(
            0, 2, (num_tasks, n_train, 1)
        ).astype(np.float32)
        labels["val/a_target"] = rng.randint(0, 2, (num_tasks, n_val, 1)).astype(
            np.float32
        )
        labels["val_mode"] = np.zeros((num_tasks, 1), bool)
    return features, labels


class TestMetaPreprocessor:
    def test_round_trip_shapes(self):
        base = MockT2RModel()
        pre = MetaPreprocessor(base.preprocessor, 5, 3)
        features, labels = _meta_batch(base, num_tasks=4, n_train=5, n_val=3)
        out_f, out_l = pre.preprocess(
            features, labels, mode=TRAIN, rng=jax.random.PRNGKey(0)
        )
        assert out_f["train/x"].shape == (4, 5, 3)
        assert out_f["val/x"].shape == (4, 3, 3)
        assert out_f.val_mode.shape == (4, 1)
        assert out_l["train/a_target"].shape == (4, 5, 1)
        assert out_l["val/a_target"].shape == (4, 3, 1)

    def test_spec_surface_matches_model(self):
        base = MockT2RModel()
        pre = MetaPreprocessor(base.preprocessor, 5, 3)
        for getter in (
            pre.get_in_feature_specification,
            pre.get_out_feature_specification,
        ):
            spec = getter(TRAIN)
            assert "train/x" in spec and "val/x" in spec

    def test_mode_required(self):
        base = MockT2RModel()
        pre = MetaPreprocessor(base.preprocessor, 2, 2)
        features, labels = _meta_batch(base, 1, 2, 2)
        with pytest.raises(ValueError):
            pre._preprocess_fn(features, labels, None, None)


class _RL2Mock(MetalearningModel):
    """Concrete subclass: runs the base network on the val_mode-selected
    branch (equal sample counts), flattened over the meta dim — the
    minimal RL^2-style composition the legacy base class exists for."""

    def init_variables(self, rng, features, mode=TRAIN):
        from tensor2robot_tpu.meta_learning import meta_tfdata

        flat = meta_tfdata.flatten_batch_examples(
            {"x": features["train/x"]}
        )
        return self._base_model.init_variables(rng, flat, mode)

    def inference_network_fn(self, variables, features, mode, rng=None,
                             labels=None):
        from tensor2robot_tpu.meta_learning import meta_tfdata

        selected = select_mode(
            features.val_mode,
            {"x": features["train/x"]},
            {"x": features["val/x"]},
        )
        num_samples = features["train/x"].shape[1]
        flat = meta_tfdata.flatten_batch_examples(selected)
        outputs, mutable = self._base_model.inference_network_fn(
            variables, flat, mode, rng=rng
        )
        outputs = meta_tfdata.unflatten_batch_examples(outputs, num_samples)
        return outputs, mutable

    def model_train_fn(self, features, labels, inference_outputs, mode):
        from tensor2robot_tpu.meta_learning import meta_tfdata

        selected_labels = select_mode(
            labels.val_mode,
            {"a_target": labels["train/a_target"]},
            {"a_target": labels["val/a_target"]},
        )
        flat_outputs = meta_tfdata.flatten_batch_examples(inference_outputs)
        flat_labels = meta_tfdata.flatten_batch_examples(selected_labels)
        return self._base_model.model_train_fn(
            None, flat_labels, flat_outputs, mode
        )


class TestMetalearningModel:
    def test_spec_surface_and_preprocessor(self):
        model = _RL2Mock(MockT2RModel(), 4, 4)
        fspec = model.get_feature_specification(TRAIN)
        assert "train/x" in fspec and "val/x" in fspec
        pre = model.preprocessor
        assert isinstance(pre, MetaPreprocessor)
        assert pre.base_preprocessor is not None

    def test_end_to_end_loss_and_grads(self):
        model = _RL2Mock(MockT2RModel(use_batch_norm=False), 4, 4)
        features, labels = _meta_batch(model, num_tasks=3, n_train=4, n_val=4)
        features = TensorSpecStruct(dict(features.items()))
        variables = model.init_variables(
            jax.random.PRNGKey(0), features, TRAIN
        )

        def loss_fn(params):
            v = dict(variables)
            v["params"] = params
            outputs, _ = model.inference_network_fn(
                v, features, TRAIN, rng=jax.random.PRNGKey(1)
            )
            loss, _ = model.model_train_fn(
                features, labels, outputs, TRAIN
            )
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        assert np.isfinite(float(loss))
        gnorm = sum(
            float(jnp.sum(g**2))
            for g in jax.tree_util.tree_leaves(grads)
        )
        assert gnorm > 0

    def test_flatten_and_add_meta_dim(self):
        model = _RL2Mock(MockT2RModel(), 2, 2)
        train = {"x": np.zeros((2, 3), np.float32)}
        val = {"x": np.ones((2, 3), np.float32)}
        flat = model.flatten_and_add_meta_dim(
            train, val, np.zeros((1,), bool)
        )
        assert flat["train/x"].shape == (1, 2, 3)
        assert flat["val/x"].shape == (1, 2, 3)
