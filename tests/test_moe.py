"""Mixture-of-Experts routing + expert-parallel execution.

Oracle for the full layer: with ample capacity, each token's output must
equal sum_k gate_k * FFN_{expert_k}(token) computed directly per token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.layers.moe import MoEBlock
from tensor2robot_tpu.ops import moe as moe_ops
from tensor2robot_tpu.parallel import mesh as mesh_lib


class TestTopKRouting:
    def test_dispatch_slots_are_unique_and_within_capacity(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(12, 4).astype(np.float32))
        routing = moe_ops.top_k_routing(logits, num_selected=2, capacity=6)
        dispatch = np.asarray(routing.dispatch)
        # Each (expert, slot) holds at most one token.
        assert dispatch.sum(axis=0).max() <= 1.0 + 1e-6
        # Each token occupies at most num_selected slots.
        assert dispatch.sum(axis=(1, 2)).max() <= 2.0 + 1e-6

    def test_gates_renormalized(self):
        logits = jnp.asarray(
            np.random.RandomState(1).randn(8, 4).astype(np.float32)
        )
        routing = moe_ops.top_k_routing(logits, num_selected=2, capacity=8)
        combine = np.asarray(routing.combine)
        # With ample capacity every token keeps both picks: combine mass 1.
        np.testing.assert_allclose(
            combine.sum(axis=(1, 2)), np.ones(8), rtol=1e-5
        )

    def test_capacity_drops_overflow_tokens(self):
        # All tokens want expert 0; capacity 2 keeps the first two only.
        logits = jnp.asarray(np.full((5, 3), 0.0, np.float32))
        logits = logits.at[:, 0].set(10.0)
        routing = moe_ops.top_k_routing(logits, num_selected=1, capacity=2)
        kept = np.asarray(routing.dispatch).sum(axis=(1, 2))
        np.testing.assert_array_equal(kept, [1, 1, 0, 0, 0])

    def test_aux_loss_uniform_is_one(self):
        # Perfectly uniform router: aux = E * sum(1/E * 1/E * E) = 1.
        logits = jnp.zeros((16, 4), jnp.float32)
        routing = moe_ops.top_k_routing(logits, num_selected=1, capacity=16)
        assert abs(float(routing.aux_loss) - 1.0) < 1e-5

    def test_primary_picks_win_capacity_over_secondary(self):
        # Token 0's SECOND choice is expert 0; tokens 1-2 pick expert 0
        # first. With capacity 2, the primaries must win the slots.
        logits = jnp.asarray(
            [[1.0, 5.0, -9.0], [5.0, 1.0, -9.0], [5.0, 1.0, -9.0]],
            jnp.float32,
        )
        routing = moe_ops.top_k_routing(logits, num_selected=2, capacity=2)
        expert0 = np.asarray(routing.dispatch)[:, 0, :].sum(axis=1)
        np.testing.assert_array_equal(expert0, [0, 1, 1])

    def test_slot_accounting_saturates_at_capacity(self):
        # Three tokens pick expert 0 first (capacity 2 drops token 2's
        # primary); token 3 picks expert 1 first with expert 0 second.
        logits = jnp.asarray(
            [
                [5.0, 1.0, -9.0],
                [5.0, 1.0, -9.0],
                [5.0, 1.0, -9.0],
                [1.0, 5.0, -9.0],
            ],
            jnp.float32,
        )
        routing = moe_ops.top_k_routing(logits, num_selected=2, capacity=2)
        dispatch = np.asarray(routing.dispatch)
        # Expert 0: tokens 0-1 fill both slots; token 2's primary and
        # token 3's secondary are both dropped (full is full — a dropped
        # primary never frees capacity, because drops only start once the
        # expert is saturated).
        expert0 = dispatch[:, 0, :].sum(axis=1)
        np.testing.assert_array_equal(expert0, [1, 1, 0, 0])
        # Expert 1 candidates in slot order: token 3's primary (k=0
        # round), then tokens 0-2's secondaries in token order. Capacity 2
        # keeps the primary + token 0's secondary; per-slot occupancy is
        # exactly one token each (slots-filled accounting saturates at
        # capacity, it never over-counts dropped assignments).
        expert1 = dispatch[:, 1, :].sum(axis=1)
        np.testing.assert_array_equal(expert1, [1, 0, 0, 1])
        assert dispatch[:, 1, :].sum() == 2
        assert dispatch.sum(axis=0).max() <= 1 + 1e-6


class TestMoEMLP:
    def _reference(self, x, router_kernel, w_in, w_out, num_selected):
        """Per-token oracle: gate-weighted sum of selected experts' FFNs."""
        probs = jax.nn.softmax(x @ router_kernel, axis=-1)
        gates, ids = jax.lax.top_k(probs, num_selected)
        gates = gates / gates.sum(axis=-1, keepdims=True)
        outs = []
        for t in range(x.shape[0]):
            acc = jnp.zeros_like(x[t])
            for k in range(num_selected):
                e = int(ids[t, k])
                h = jax.nn.gelu(x[t] @ w_in[e])
                acc = acc + gates[t, k] * (h @ w_out[e])
            outs.append(acc)
        return jnp.stack(outs)

    def test_matches_per_token_reference(self):
        rng = np.random.RandomState(2)
        tokens, features, hidden, experts = 10, 6, 8, 4
        x = jnp.asarray(rng.randn(tokens, features).astype(np.float32))
        router = jnp.asarray(rng.randn(features, experts).astype(np.float32))
        w_in = jnp.asarray(
            rng.randn(experts, features, hidden).astype(np.float32) * 0.3
        )
        w_out = jnp.asarray(
            rng.randn(experts, hidden, features).astype(np.float32) * 0.3
        )
        y, aux = moe_ops.moe_mlp(
            x, router, w_in, w_out, num_selected=2, capacity_factor=8.0
        )
        expected = self._reference(x, router, w_in, w_out, 2)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(expected), rtol=1e-4, atol=1e-5
        )
        assert float(aux) > 0

    def test_expert_parallel_matches_single_device(self):
        """The same computation over an 8-way expert mesh must agree with
        the unsharded run — GSPMD inserts the all_to_alls, not the math."""
        rng = np.random.RandomState(3)
        tokens, features, hidden, experts = 16, 4, 8, 8
        x = jnp.asarray(rng.randn(tokens, features).astype(np.float32))
        router = jnp.asarray(rng.randn(features, experts).astype(np.float32))
        w_in = jnp.asarray(
            rng.randn(experts, features, hidden).astype(np.float32) * 0.3
        )
        w_out = jnp.asarray(
            rng.randn(experts, hidden, features).astype(np.float32) * 0.3
        )
        y_plain, _ = moe_ops.moe_mlp(
            x, router, w_in, w_out, num_selected=2, capacity_factor=8.0
        )

        mesh = mesh_lib.make_mesh(data=1, expert=8)
        expert_sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(mesh_lib.EXPERT_AXIS)
        )
        w_in_sharded = jax.device_put(w_in, expert_sharding)
        w_out_sharded = jax.device_put(w_out, expert_sharding)

        @jax.jit
        def run(x, router, w_in, w_out):
            y, aux = moe_ops.moe_mlp(
                x, router, w_in, w_out,
                num_selected=2, capacity_factor=8.0, mesh=mesh,
            )
            return y, aux

        y_sharded, _ = run(x, router, w_in_sharded, w_out_sharded)
        np.testing.assert_allclose(
            np.asarray(y_sharded), np.asarray(y_plain), rtol=1e-4, atol=1e-5
        )

    def test_gradients_flow_to_all_param_groups(self):
        rng = np.random.RandomState(4)
        tokens, features, hidden, experts = 8, 4, 6, 4
        x = jnp.asarray(rng.randn(tokens, features).astype(np.float32))
        params = {
            "router": jnp.asarray(
                rng.randn(features, experts).astype(np.float32)
            ),
            "w_in": jnp.asarray(
                rng.randn(experts, features, hidden).astype(np.float32)
            ),
            "w_out": jnp.asarray(
                rng.randn(experts, hidden, features).astype(np.float32)
            ),
        }

        def loss(params):
            y, aux = moe_ops.moe_mlp(
                x, params["router"], params["w_in"], params["w_out"],
                num_selected=2, capacity_factor=4.0,
            )
            return jnp.mean(y ** 2) + 0.01 * aux

        grads = jax.grad(loss)(params)
        for key, grad in grads.items():
            assert float(jnp.max(jnp.abs(grad))) > 0, f"zero grad for {key}"


class TestMoEBlock:
    def test_forward_shapes_and_aux(self):
        block = MoEBlock(num_experts=4, hidden_dim=16, num_selected=2)
        x = jnp.ones((2, 6, 8), jnp.float32)
        params = block.init(jax.random.PRNGKey(0), x)
        y, aux = block.apply(params, x)
        assert y.shape == (2, 6, 8)
        assert np.isfinite(float(aux))

    @pytest.mark.parametrize("num_selected", [1, 2])
    def test_trains_under_jit(self, num_selected):
        block = MoEBlock(
            num_experts=4, hidden_dim=8, num_selected=num_selected
        )
        x = jnp.asarray(
            np.random.RandomState(0).randn(2, 4, 6).astype(np.float32)
        )
        params = block.init(jax.random.PRNGKey(0), x)

        @jax.jit
        def loss_fn(params):
            y, aux = block.apply(params, x)
            return jnp.mean((y - 1.0) ** 2) + 0.01 * aux

        grads = jax.grad(loss_fn)(params)
        flat = jax.tree_util.tree_leaves(grads)
        assert any(float(jnp.max(jnp.abs(g))) > 0 for g in flat)


class TestGroupedRouting:
    def test_groups_route_independently(self):
        """group_size=g must equal running moe_mlp on each group alone —
        groups are independent routing domains (GShard grouping)."""
        rng = np.random.RandomState(6)
        tokens, features, hidden, experts, g = 12, 4, 6, 3, 4
        x = jnp.asarray(rng.randn(tokens, features).astype(np.float32))
        router = jnp.asarray(rng.randn(features, experts).astype(np.float32))
        w_in = jnp.asarray(
            rng.randn(experts, features, hidden).astype(np.float32) * 0.3
        )
        w_out = jnp.asarray(
            rng.randn(experts, hidden, features).astype(np.float32) * 0.3
        )
        kwargs = dict(num_selected=2, capacity_factor=4.0)
        y_grouped, _ = moe_ops.moe_mlp(
            x, router, w_in, w_out, group_size=g, **kwargs
        )
        y_parts = [
            moe_ops.moe_mlp(x[i : i + g], router, w_in, w_out, **kwargs)[0]
            for i in range(0, tokens, g)
        ]
        np.testing.assert_allclose(
            np.asarray(y_grouped),
            np.asarray(jnp.concatenate(y_parts)),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_bad_group_size_raises(self):
        x = jnp.ones((10, 4), jnp.float32)
        with pytest.raises(ValueError, match="does not divide"):
            moe_ops.moe_mlp(
                x,
                jnp.ones((4, 2)),
                jnp.ones((2, 4, 4)),
                jnp.ones((2, 4, 4)),
                group_size=3,
            )

    def test_top1_router_learns_from_task_loss(self):
        """Switch-style top-1 keeps the raw probability as the gate, so
        the router gradient from the task loss ALONE is nonzero."""
        rng = np.random.RandomState(9)
        x = jnp.asarray(rng.randn(8, 4).astype(np.float32))
        router = jnp.asarray(rng.randn(4, 3).astype(np.float32))
        w_in = jnp.asarray(rng.randn(3, 4, 6).astype(np.float32))
        w_out = jnp.asarray(rng.randn(3, 6, 4).astype(np.float32))

        def task_loss(router):
            y, _ = moe_ops.moe_mlp(
                x, router, w_in, w_out, num_selected=1, capacity_factor=4.0
            )
            return jnp.mean(y ** 2)  # aux loss deliberately excluded

        grad = jax.grad(task_loss)(router)
        assert float(jnp.max(jnp.abs(grad))) > 0
