"""True multi-process distributed bring-up: 2 OS processes, one
coordinator, cross-host collectives over the DCN (gRPC) path.

Beyond the reference's test strategy (SURVEY §4: "there are no true
multi-process/multi-worker tests" — SyncReplicas/TF_CONFIG paths were
untested in OSS): this spawns two real processes that each own one CPU
device, join via `initialize_distributed` (the TF_CONFIG analogue), build
the global data mesh, contribute per-process shards, and check a pjit
global mean plus a process_allgather. The same code path a TPU pod uses
over DCN, minus the chips.
"""

import os
import subprocess
import sys

import pytest

_WORKER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "_mp_worker.py",
)


@pytest.mark.slow
def test_two_process_distributed_collectives(tmp_path):
    import socket

    import numpy as np

    from tensor2robot_tpu.data import tfrecord
    from tensor2robot_tpu.data.encoder import encode_example
    from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    # Record shards for the per-host infeed leg (shard_by_host).
    spec = TensorSpecStruct()
    spec["y"] = ExtendedTensorSpec(shape=(), dtype=np.int64, name="y")
    for shard in range(4):
        tfrecord.write_tfrecords(
            str(tmp_path / f"s-{shard}.tfrecord"),
            [encode_example(spec, {"y": np.asarray(shard, np.int64)})],
        )

    env = dict(os.environ)
    # Each worker must see exactly its own single CPU device; scrub the
    # virtual-device flag the surrounding test session sets.
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)

    workers = [
        subprocess.Popen(
            [sys.executable, _WORKER, coordinator, "2", str(pid), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outputs = []
    try:
        for proc in workers:
            out, _ = proc.communicate(timeout=240)
            outputs.append(out)
    except subprocess.TimeoutExpired:
        for proc in workers:
            proc.kill()
        pytest.fail(f"distributed workers hung; partial output: {outputs}")
    for pid, (proc, out) in enumerate(zip(workers, outputs)):
        assert proc.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"mp_worker {pid}: OK" in out
