"""Property-based encode->parse roundtrip over randomized spec structures.

The spec-driven parser generator is the subtlest data component (SURVEY
§7 hard parts: "bfloat16 features, varlen pad/clip, zero-image fallback,
dataset_key prefixing, sequence _length handling — many interacting
corner cases"). Example-based tests pin known cases (test_data.py);
these hypothesis properties pin the INVARIANT across arbitrary spec
combinations: any spec structure the framework can declare, filled with
conforming random data, must encode to records and parse back to the
same values (exactly for int/f32, to rounding for bf16), with sequence
lengths reported and batch stacking correct.
"""

import string

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis (requirements-test.txt)"
)
from hypothesis import given, settings
from hypothesis import strategies as st

from tensor2robot_tpu.data.encoder import encode_example
from tensor2robot_tpu.data.parser import SpecParser
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
    make_random_numpy,
)

name = st.text(string.ascii_lowercase, min_size=1, max_size=5)


@st.composite
def leaf_specs(draw, key):
    """One random fixed-shape leaf: int64 / float32 / bfloat16 declared."""
    dtype = draw(st.sampled_from([np.int64, np.float32, "bfloat16"]))
    rank = draw(st.integers(0, 3))
    shape = tuple(draw(st.integers(1, 4)) for _ in range(rank))
    if dtype == "bfloat16":
        import jax.numpy as jnp

        return ExtendedTensorSpec(shape=shape, dtype=jnp.bfloat16, name=key)
    return ExtendedTensorSpec(shape=shape, dtype=dtype, name=key)


@st.composite
def spec_structs(draw):
    keys = draw(
        st.lists(name, min_size=1, max_size=5, unique=True)
    )
    struct = TensorSpecStruct()
    for key in keys:
        struct[key] = draw(leaf_specs(key))
    return struct


class TestEncodeParseRoundtrip:
    @settings(max_examples=40, deadline=None)
    @given(spec_structs(), st.integers(0, 2 ** 31 - 1))
    def test_fixed_shape_roundtrip(self, specs, seed):
        batch = 3
        values = make_random_numpy(specs, batch_size=batch, seed=seed)
        records = [
            encode_example(
                specs, {k: np.asarray(v[i]) for k, v in values.items()}
            )
            for i in range(batch)
        ]
        parsed = SpecParser(specs).parse_batch(records)
        for key, spec in specs.items():
            got = np.asarray(parsed[key])
            want = np.asarray(values[key])
            assert got.shape == want.shape, key
            if str(spec.dtype) == "bfloat16":
                # Declared-bf16 features travel as f32 and cast at egress.
                np.testing.assert_allclose(
                    got.astype(np.float32),
                    want.astype(np.float32),
                    rtol=1e-2,
                    atol=1e-2,
                )
            else:
                np.testing.assert_array_equal(got, want, err_msg=key)

    @settings(max_examples=30, deadline=None)
    @given(
        st.integers(1, 4),  # feature dim
        st.lists(st.integers(1, 6), min_size=2, max_size=4),  # per-row lens
        st.integers(0, 2 ** 31 - 1),
    )
    def test_sequence_lengths_and_padding(self, dim, lengths, seed):
        """Variable-length sequences: per-row lengths survive, rows pad to
        the batch max, and the `<key>_length` tensor reports truth."""
        specs = TensorSpecStruct()
        specs["seq"] = ExtendedTensorSpec(
            shape=(dim,), dtype=np.float32, name="seq", is_sequence=True
        )
        rng = np.random.RandomState(seed)
        rows = [
            rng.randn(length, dim).astype(np.float32) for length in lengths
        ]
        records = [encode_example(specs, {"seq": row}) for row in rows]
        parsed = SpecParser(specs).parse_batch(records)
        max_len = max(lengths)
        assert parsed["seq"].shape == (len(rows), max_len, dim)
        np.testing.assert_array_equal(
            np.asarray(parsed["seq_length"]).ravel(), lengths
        )
        for i, row in enumerate(rows):
            np.testing.assert_array_equal(
                np.asarray(parsed["seq"])[i, : lengths[i]], row
            )
            # Padding is zeros beyond each row's true length.
            np.testing.assert_array_equal(
                np.asarray(parsed["seq"])[i, lengths[i]:], 0.0
            )

    @settings(max_examples=30, deadline=None)
    @given(st.integers(1, 5), st.integers(2, 8), st.integers(0, 2 ** 31 - 1))
    def test_varlen_pad_and_clip_roundtrip(self, true_len, spec_len, seed):
        """VarLen leaves pad (zeros) or clip to the spec's declared length
        regardless of the encoded length."""
        specs = TensorSpecStruct()
        specs["v"] = ExtendedTensorSpec(
            shape=(spec_len,),
            dtype=np.float32,
            name="v",
            varlen_default_value=0.0,
        )
        rng = np.random.RandomState(seed)
        row = rng.randn(true_len).astype(np.float32)
        parsed = SpecParser(specs).parse_batch(
            [encode_example(specs, {"v": row})]
        )
        got = np.asarray(parsed["v"])[0]
        assert got.shape == (spec_len,)
        keep = min(true_len, spec_len)
        np.testing.assert_array_equal(got[:keep], row[:keep])
        np.testing.assert_array_equal(got[keep:], 0.0)
