"""Pipeline parallelism: GPipe scheduling over the pipe mesh axis.

Correctness oracle: pipeline_apply must equal the plain sequential
composition of the stages (and so must its gradients) — the schedule is an
execution strategy, not a semantic change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel import pipeline


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(num_stages, features, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "w": jnp.asarray(
                rng.randn(features, features).astype(np.float32) * 0.3
            ),
            "b": jnp.asarray(rng.randn(features).astype(np.float32) * 0.1),
        }
        for _ in range(num_stages)
    ]


def _sequential(stages, x):
    for params in stages:
        x = _stage_fn(params, x)
    return x


class TestPipelineApply:
    @pytest.mark.parametrize("num_stages,num_micro", [(2, 4), (4, 8), (8, 8)])
    def test_matches_sequential(self, num_stages, num_micro):
        mesh = mesh_lib.make_mesh(pipe=num_stages)
        features, batch = 6, 16
        stages = _make_stages(num_stages, features)
        stacked = pipeline.stack_stage_params(stages)
        stacked = jax.device_put(
            stacked, pipeline.stage_sharding(mesh, stacked)
        )
        x = jnp.asarray(
            np.random.RandomState(1)
            .randn(batch, features)
            .astype(np.float32)
        )
        out = pipeline.pipeline_apply(
            _stage_fn, stacked, x, mesh=mesh, num_microbatches=num_micro
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_sequential(stages, x)),
            rtol=1e-5, atol=1e-5,
        )

    def test_single_stage_identity_schedule(self):
        mesh = mesh_lib.make_mesh(data=8, pipe=1)
        stages = _make_stages(1, 4)
        stacked = pipeline.stack_stage_params(stages)
        x = jnp.ones((8, 4), jnp.float32)
        out = pipeline.pipeline_apply(
            _stage_fn, stacked, x, mesh=mesh, num_microbatches=2
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_sequential(stages, x)),
            rtol=1e-6, atol=1e-6,
        )

    def test_batch_not_divisible_raises(self):
        mesh = mesh_lib.make_mesh(pipe=4)
        stages = _make_stages(4, 4)
        stacked = pipeline.stack_stage_params(stages)
        with pytest.raises(ValueError, match="not divisible"):
            pipeline.pipeline_apply(
                _stage_fn,
                stacked,
                jnp.ones((10, 4)),
                mesh=mesh,
                num_microbatches=3,
            )

    def test_gradients_match_sequential(self):
        """Pipeline-parallel TRAINING: grads through the schedule equal
        grads through the plain composition, for params and inputs."""
        num_stages, num_micro = 4, 4
        mesh = mesh_lib.make_mesh(pipe=num_stages)
        features, batch = 4, 8
        stages = _make_stages(num_stages, features, seed=3)
        stacked = pipeline.stack_stage_params(stages)
        x = jnp.asarray(
            np.random.RandomState(5).randn(batch, features).astype(np.float32)
        )
        target = jnp.ones((batch, features), jnp.float32)

        def pipe_loss(stacked_params, x):
            out = pipeline.pipeline_apply(
                _stage_fn, stacked_params, x, mesh=mesh,
                num_microbatches=num_micro,
            )
            return jnp.mean((out - target) ** 2)

        def seq_loss(stacked_params, x):
            for i in range(num_stages):
                params = jax.tree_util.tree_map(
                    lambda leaf: leaf[i], stacked_params
                )
                x = _stage_fn(params, x)
            return jnp.mean((x - target) ** 2)

        pipe_grads = jax.jit(jax.grad(pipe_loss, argnums=(0, 1)))(stacked, x)
        seq_grads = jax.jit(jax.grad(seq_loss, argnums=(0, 1)))(stacked, x)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            pipe_grads,
            seq_grads,
        )

    def test_stage_params_actually_sharded(self):
        mesh = mesh_lib.make_mesh(pipe=8)
        stages = _make_stages(8, 8)
        stacked = pipeline.stack_stage_params(stages)
        placed = jax.device_put(
            stacked, pipeline.stage_sharding(mesh, stacked)
        )
        assert not placed["w"].sharding.is_fully_replicated
        assert placed["w"].sharding.spec[0] == mesh_lib.PIPE_AXIS


class TestShardMapRematScanVma:
    """Root cause of the (former) pipeline+grad_accum+remat seed failure.

    jax's shard_map replication tracking (check_rep / varying manual
    axes) loses its carry annotations when a scan INSIDE a shard_map is
    differentiated THROUGH jax.checkpoint: partial-eval extends the
    loop carry with residual/tangent slots whose zero initializers are
    born *unvarying* while the (collective-touching) body emits them
    *varying*, and scan's type check then fails with "Scan carry input
    and output got mismatched replication types ... pass the
    check_rep=False argument to shard_map". The three ingredients are
    all required — drop the remat, the scan, or the collective in the
    body and the program checks clean (see the passing pipeline grad
    tests above, which differentiate the same scan WITHOUT remat).

    The fix: pipeline_apply runs its shard_map with check_rep=False
    (parallel/pipeline.py), leaning on the sequential-parity tests for
    correctness instead of the static replication checker. This repro
    pins the upstream failure mode at its minimal shape so a jax
    upgrade that fixes (or changes) the behavior is noticed here, not
    as a mystery flip in the composed trainer test.
    """

    def _repro(self, check_rep: bool):
        from tensor2robot_tpu.parallel import collectives

        mesh = mesh_lib.make_mesh(pipe=2, devices=jax.devices()[:2])

        def body_fn(x):
            def tick(carry, _):
                shifted = collectives.ppermute(
                    carry, mesh_lib.PIPE_AXIS, perm=[(0, 1)]
                )
                return shifted + x, None

            carry0 = jnp.zeros_like(x)
            if hasattr(jax.lax, "pcast"):
                carry0 = jax.lax.pcast(
                    carry0, (mesh_lib.PIPE_AXIS,), to="varying"
                )
            out, _ = jax.lax.scan(tick, carry0, jnp.arange(3))
            return collectives.psum(out, mesh_lib.PIPE_AXIS)

        mapped = collectives.shard_map(
            body_fn,
            mesh=mesh,
            in_specs=pipeline.PartitionSpec(),
            out_specs=pipeline.PartitionSpec(),
            check_rep=check_rep,
        )

        def loss(x):
            return jnp.sum(jax.checkpoint(mapped)(x))

        # jit: eager shard_map cannot evaluate the closed_call remat
        # introduces; the production path (CompiledModel) is always jit.
        return jax.jit(jax.grad(loss))(jnp.ones((4,), jnp.float32))

    def test_check_rep_off_differentiates_under_remat(self):
        grads = self._repro(check_rep=False)
        assert np.all(np.isfinite(np.asarray(grads)))

    def test_check_rep_on_pins_upstream_vma_bug(self):
        """The minimal repro: scan-in-shard_map under jax.checkpoint
        with replication checking ON. Pinned to fail with the exact
        upstream complaint; if a jax upgrade makes this pass, the
        workaround in pipeline_apply can be retired."""
        try:
            self._repro(check_rep=True)
        except Exception as err:
            # Depending on where the tracker loses the annotation first,
            # jax reports either the scan-carry mismatch ("Scan carry
            # input and output got mismatched replication types" — the
            # composed trainer test's form) or the collective-input form
            # ("ppermute must be applied to a device-varying replication
            # type, but got None"); both prescribe the same workaround.
            message = str(err)
            assert (
                "replication type" in message
                or "check_rep=False" in message
            ), err
        else:
            pytest.fail(
                "jax now tracks scan-carry replication through remat: "
                "check_rep=False workaround in pipeline_apply (and this "
                "pin) can be retired"
            )
