"""The persistent plan cache (parallel/plan_cache.py) and the measured
plan-search probe it fronts (train_eval.measure_plan_candidate).

Pins the PR's contracts:
  * envelope integrity: every corpus corruption variant of a valid entry
    is a typed PlanCacheCorrupt, and the tolerant `load()` falls back to
    None (fresh search) instead of trusting the bytes;
  * all-or-nothing cache key: a changed model fingerprint, device
    topology, jax version, or planner schema version is a typed
    PlanCacheKeyMismatch — a winner ranked under different rules never
    shadows a fresh search;
  * the zero-compile warm path: the second T2R_PLAN=auto run on the same
    (model, topology) key deserializes the FIRST run's winner
    byte-for-byte and pays zero search compiles (audited via the probe
    compile counter);
  * the measured probe bypasses jax's persistent compilation cache — a
    cache-hit executable has near-zero compile time and would poison the
    ranking.
"""

import os

import pytest

import jax

from tensor2robot_tpu import flags
from tensor2robot_tpu.analysis import corpus
from tensor2robot_tpu.export import aot
from tensor2robot_tpu.parallel import plan_cache, planner
from tensor2robot_tpu.train import train_eval
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

N = 8


def _mock_model_and_batch():
    model = MockT2RModel(device_type="cpu", use_batch_norm=False)
    generator = MockInputGenerator(batch_size=16, seed=0)
    generator.set_specification_from_model(model, "train")
    batch = next(iter(generator.create_dataset("train")))
    return model, batch


def _mock_spec():
    model, batch = _mock_model_and_batch()
    return planner.ModelSpec.from_model(model, batch)


def _payload_doc(spec=None):
    spec = spec if spec is not None else _mock_spec()
    result = planner.plan(spec, planner.Topology(num_devices=N))
    return {"plan": result.best.to_json(), "table": list(result.table)}


_TOPOLOGY = {"platform": "cpu", "device_kind": "host", "device_count": N}


class TestEnvelope:
    def test_pack_unpack_roundtrip(self):
        doc = _payload_doc()
        blob = plan_cache.pack_entry("f" * 64, doc, topology=_TOPOLOGY)
        header, payload = plan_cache.unpack_entry(
            blob, expect_fingerprint="f" * 64, expect_topology=_TOPOLOGY
        )
        assert header["format_version"] == plan_cache.PLAN_CACHE_FORMAT_VERSION
        assert header["jax"] == jax.__version__
        assert payload == doc
        # The winner survives serialization byte-for-byte: the plan json
        # re-hydrates into an identical ShardingPlan.
        plan = planner.ShardingPlan.from_json(payload["plan"])
        assert plan.to_json() == doc["plan"]

    def test_store_load_hit_is_byte_identical(self, tmp_path):
        spec = _mock_spec()
        fingerprint = plan_cache.model_fingerprint(spec)
        doc = _payload_doc(spec)
        path = plan_cache.store(fingerprint, doc, str(tmp_path))
        assert path and os.path.exists(path)
        payload = plan_cache.load(
            fingerprint, str(tmp_path), topology=None
        )
        assert payload is not None
        assert payload["plan"] == doc["plan"]
        assert payload["table"] == doc["table"]

    def test_store_disabled_without_directory(self):
        saved = flags.read_raw("T2R_PLAN_CACHE_DIR")
        try:
            flags.restore_env("T2R_PLAN_CACHE_DIR", None)
            assert plan_cache.cache_dir() is None
            assert plan_cache.store("f" * 64, {"plan": {}}) is None
            assert plan_cache.load("f" * 64) is None
        finally:
            flags.restore_env("T2R_PLAN_CACHE_DIR", saved)

    def test_forged_length_bounded_before_allocation(self):
        import struct

        blob = plan_cache.pack_entry("f" * 64, {"plan": {}})
        forged = (
            blob[:4]
            + struct.pack("<I", plan_cache.MAX_PLAN_ENTRY_BYTES + 1)
            + blob[8:]
        )
        with pytest.raises(plan_cache.PlanCacheCorrupt, match="forged"):
            plan_cache.unpack_entry(forged)

    def test_fingerprint_sensitive_to_model_shape(self):
        spec = _mock_spec()
        fp = plan_cache.model_fingerprint(spec)
        assert fp == plan_cache.model_fingerprint(spec)  # deterministic
        import dataclasses

        other = dataclasses.replace(spec, batch_size=spec.batch_size * 2)
        assert plan_cache.model_fingerprint(other) != fp


class TestCorruption:
    """Every corpus corruption family member is a TYPED corrupt error
    from the strict reader, and a logged None from the tolerant one —
    never a trusted payload."""

    def test_every_variant_typed(self):
        blob = plan_cache.pack_entry(
            "f" * 64, _payload_doc(), topology=_TOPOLOGY
        )
        variants = corpus.corrupt_frame_variants(blob)
        assert len(variants) >= 20
        for name, bad in sorted(variants.items()):
            with pytest.raises(plan_cache.PlanCacheCorrupt):
                plan_cache.unpack_entry(
                    bad,
                    expect_fingerprint="f" * 64,
                    expect_topology=_TOPOLOGY,
                )

    def test_load_falls_back_on_corrupt_file(self, tmp_path):
        spec = _mock_spec()
        fingerprint = plan_cache.model_fingerprint(spec)
        path = plan_cache.store(fingerprint, _payload_doc(spec), str(tmp_path))
        with open(path, "rb") as f:
            blob = f.read()
        for name, bad in sorted(
            corpus.corrupt_frame_variants(blob).items()
        ):
            with open(path, "wb") as f:
                f.write(bad)
            assert (
                plan_cache.load(fingerprint, str(tmp_path)) is None
            ), name

    def test_load_missing_file_is_quiet_miss(self, tmp_path):
        assert plan_cache.load("0" * 64, str(tmp_path)) is None


class TestKeyInvalidation:
    """The all-or-nothing cache key: each component differing forces a
    fresh search, loudly typed."""

    def _blob(self, **kwargs):
        return plan_cache.pack_entry(
            "f" * 64, {"plan": {}}, topology=_TOPOLOGY, **kwargs
        )

    def test_fingerprint_mismatch(self):
        with pytest.raises(
            plan_cache.PlanCacheKeyMismatch, match="fingerprint"
        ):
            plan_cache.unpack_entry(
                self._blob(), expect_fingerprint="0" * 64,
                expect_topology=_TOPOLOGY,
            )

    def test_device_count_mismatch(self):
        grown = dict(_TOPOLOGY, device_count=2 * N)
        with pytest.raises(
            plan_cache.PlanCacheKeyMismatch, match="topology"
        ):
            plan_cache.unpack_entry(
                self._blob(), expect_fingerprint="f" * 64,
                expect_topology=grown,
            )

    def test_device_kind_mismatch(self):
        tpu = dict(_TOPOLOGY, platform="tpu", device_kind="TPU v4")
        with pytest.raises(
            plan_cache.PlanCacheKeyMismatch, match="topology"
        ):
            plan_cache.unpack_entry(
                self._blob(), expect_fingerprint="f" * 64,
                expect_topology=tpu,
            )

    def test_jax_version_mismatch(self):
        with pytest.raises(plan_cache.PlanCacheKeyMismatch, match="jax"):
            plan_cache.unpack_entry(
                self._blob(jax_version="0.0.0-other"),
                expect_fingerprint="f" * 64,
                expect_topology=_TOPOLOGY,
            )

    def test_schema_bump_invalidates(self):
        """A winner chosen from a narrower search space must not shadow
        the wider one: bumping PLAN_CACHE_FORMAT_VERSION orphans every
        old entry."""
        stale = self._blob(
            format_version=plan_cache.PLAN_CACHE_FORMAT_VERSION + 1
        )
        with pytest.raises(
            plan_cache.PlanCacheKeyMismatch, match="schema"
        ):
            plan_cache.unpack_entry(
                stale, expect_fingerprint="f" * 64,
                expect_topology=_TOPOLOGY,
            )

    def test_load_falls_back_on_key_mismatch(self, tmp_path):
        """The tolerant reader treats a keyed-out entry like a miss: the
        caller re-searches rather than crashing or trusting it."""
        spec = _mock_spec()
        fingerprint = plan_cache.model_fingerprint(spec)
        # An entry keyed for a DIFFERENT jax runtime at this model's path.
        blob = plan_cache.pack_entry(
            fingerprint, _payload_doc(spec), jax_version="0.0.0-other"
        )
        os.makedirs(str(tmp_path), exist_ok=True)
        with open(
            plan_cache.entry_path(str(tmp_path), fingerprint), "wb"
        ) as f:
            f.write(blob)
        assert plan_cache.load(fingerprint, str(tmp_path)) is None


class TestParseMeasureSetting:
    def test_off_and_shortlist(self):
        assert planner.parse_measure_setting("off") is None
        assert planner.parse_measure_setting("") is None
        assert planner.parse_measure_setting(None) is None
        assert planner.parse_measure_setting("shortlist-1") == 1
        assert planner.parse_measure_setting("shortlist-8") == 8

    @pytest.mark.parametrize(
        "bad", ["on", "shortlist-0", "shortlist-x", "shortlist-", "4"]
    )
    def test_typo_is_loud(self, bad):
        with pytest.raises(ValueError, match="T2R_PLAN_MEASURE"):
            planner.parse_measure_setting(bad)


class TestCompileCacheBypass:
    """The measured probe must never time a persistent-compile-cache
    HIT: a cached executable carries near-zero compile time and object
    code XLA didn't just build, poisoning both the ranking and the
    compile counter the warm-path audit reads."""

    def test_bypass_disables_and_restores(self):
        prev = bool(jax.config.jax_enable_compilation_cache)
        jax.config.update("jax_enable_compilation_cache", True)
        try:
            with train_eval._plan_probe_compile_cache_bypass():
                assert not jax.config.jax_enable_compilation_cache
            assert jax.config.jax_enable_compilation_cache
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)

    def test_bypass_restores_on_error(self):
        prev = bool(jax.config.jax_enable_compilation_cache)
        jax.config.update("jax_enable_compilation_cache", True)
        try:
            with pytest.raises(RuntimeError, match="boom"):
                with train_eval._plan_probe_compile_cache_bypass():
                    raise RuntimeError("boom")
            assert jax.config.jax_enable_compilation_cache
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)

    def test_probe_skips_plans_the_model_cannot_run(self):
        """A shortlisted plan the given model cannot execute (pipeline
        axes on a stage-less model) is a recorded skip, not a crash —
        and pays no compile."""
        model, batch = _mock_model_and_batch()
        before = train_eval.plan_probe_compile_count()
        record = train_eval.measure_plan_candidate(
            model,
            planner.ShardingPlan(name="dp4_pp2", data=4, pipe=2),
            batch,
        )
        assert "skipped" in record
        assert "step_time_ms" not in record
        assert train_eval.plan_probe_compile_count() == before


class TestAutoSearchCache:
    """The acceptance contract end-to-end on the 8-device host mesh: a
    cold T2R_PLAN=auto run searches, measures, and stores; the warm run
    returns the SAME plan byte-for-byte with ZERO search compiles."""

    def _with_auto_flags(self, cache_dir, measure):
        saved = {
            name: flags.read_raw(name)
            for name in (
                "T2R_PLAN",
                "T2R_PLAN_CACHE_DIR",
                "T2R_PLAN_MEASURE",
                "T2R_PLAN_MEASURE_STEPS",
            )
        }
        flags.write_env("T2R_PLAN", "auto")
        flags.write_env("T2R_PLAN_CACHE_DIR", cache_dir)
        flags.write_env("T2R_PLAN_MEASURE", measure)
        flags.write_env("T2R_PLAN_MEASURE_STEPS", 1)
        return saved

    def _restore(self, saved):
        for name, value in saved.items():
            flags.restore_env(name, value)

    def test_cold_measures_then_warm_is_zero_compile(self, tmp_path):
        model, batch = _mock_model_and_batch()
        saved = self._with_auto_flags(str(tmp_path), "shortlist-2")
        try:
            cold = planner.resolve_plan_from_flag(model, batch)
            cold_stats = planner.last_search()
            assert cold_stats["source"] == "measured"
            assert cold_stats["probe_compiles"] >= 1
            assert cold_stats["stored"]
            assert cold_stats["measured"]["shortlist"] >= 1

            warm = planner.resolve_plan_from_flag(model, batch)
            warm_stats = planner.last_search()
            assert warm_stats["source"] == "cache"
            assert warm_stats["probe_compiles"] == 0
            assert warm.to_json() == cold.to_json()
            assert warm_stats["fingerprint"] == cold_stats["fingerprint"]
        finally:
            self._restore(saved)

    def test_analytic_only_when_measure_off(self, tmp_path):
        model, batch = _mock_model_and_batch()
        saved = self._with_auto_flags(str(tmp_path), "off")
        try:
            plan = planner.resolve_plan_from_flag(model, batch)
            stats = planner.last_search()
            assert stats["source"] == "analytic"
            assert stats["probe_compiles"] == 0
            # Still cached: the second run is a hit.
            warm = planner.resolve_plan_from_flag(model, batch)
            assert planner.last_search()["source"] == "cache"
            assert warm.to_json() == plan.to_json()
        finally:
            self._restore(saved)

    def test_corrupt_entry_forces_fresh_search(self, tmp_path):
        model, batch = _mock_model_and_batch()
        saved = self._with_auto_flags(str(tmp_path), "off")
        try:
            planner.resolve_plan_from_flag(model, batch)
            fingerprint = planner.last_search()["fingerprint"]
            path = plan_cache.entry_path(str(tmp_path), fingerprint)
            with open(path, "rb") as f:
                blob = f.read()
            with open(path, "wb") as f:
                f.write(blob[: len(blob) // 2])
            planner.resolve_plan_from_flag(model, batch)
            stats = planner.last_search()
            assert stats["source"] == "analytic"  # not "cache"
            assert stats["stored"]  # and the entry was repaired
            planner.resolve_plan_from_flag(model, batch)
            assert planner.last_search()["source"] == "cache"
        finally:
            self._restore(saved)


class TestTopologyKeySource:
    def test_device_topology_matches_live_mesh(self):
        topo = aot.device_topology()
        assert topo["device_count"] == N
        assert topo["platform"] == "cpu"
