"""The unified sharding planner (parallel/planner.py).

Pins the tentpole contracts:
  * factorization enumeration: every candidate's axes multiply to the
    device count; memory-infeasible plans are rejected with the estimate
    in the error;
  * preset byte-equality: every hand-wired regime's planner preset
    places a TrainState with LEAF-FOR-LEAF identical shardings, and the
    `none`-regime train step is bitwise equal to the hand-wired twin;
  * checkpoint round-trip: a planner-built state restores bitwise into
    the same plan and fails loudly into a different-layout plan;
  * composition with the T2R_COLLECTIVE_QUANT regimes (the plan is
    authoritative — ambient env flags cannot change a pinned plan);
  * the 3D DP x SP x PP regime (fast one-step sibling here; the slow
    slice runs the multi-step loss-parity twin).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.flatten_util

from tensor2robot_tpu import flags
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel import planner
from tensor2robot_tpu.specs import make_random_numpy
from tensor2robot_tpu.train import train_eval
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

N = 8  # conftest forces the 8-device host mesh
BLOCK = 64


def _mock_setup(plan=None, batch_size=16, **kwargs):
    model = MockT2RModel(device_type="cpu", use_batch_norm=False)
    generator = MockInputGenerator(batch_size=batch_size, seed=0)
    generator.set_specification_from_model(model, "train")
    batch = next(iter(generator.create_dataset("train")))
    compiled = train_eval.CompiledModel(
        model, donate_state=False, plan=plan, **kwargs
    )
    state = compiled.init_state(jax.random.PRNGKey(0), batch)
    return compiled, state, batch


def _mock_model_spec():
    model = MockT2RModel(device_type="cpu", use_batch_norm=False)
    generator = MockInputGenerator(batch_size=16, seed=0)
    generator.set_specification_from_model(model, "train")
    batch = next(iter(generator.create_dataset("train")))
    return planner.ModelSpec.from_model(model, batch)


def _transformer(mesh, **kwargs):
    from tensor2robot_tpu.models.transformer_models import TransformerBCModel

    kwargs = dict(
        dict(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_layers=2, num_heads=4, use_flash=False,
        ),
        **kwargs,
    )
    return TransformerBCModel(mesh=mesh, **kwargs)


def _transformer_batch(model, batch_size=8, seed=0):
    features = make_random_numpy(
        model.get_feature_specification("train"),
        batch_size=batch_size, seed=seed,
    )
    labels = make_random_numpy(
        model.get_label_specification("train"),
        batch_size=batch_size, seed=seed + 1,
    )
    return {"features": features, "labels": labels}


def _transformer_model_spec():
    mesh = mesh_lib.make_mesh(data=N)
    model = _transformer(mesh)
    return planner.ModelSpec.from_model(model, _transformer_batch(model))


def _big_synthetic_spec():
    """A hand-built ModelSpec with 8-divisible shapes, for estimate
    tests where the mock's 100-wide (8-indivisible) layers would keep
    every leaf replicated."""
    import jax.numpy as jnp

    w = jax.ShapeDtypeStruct((4096, 4096), jnp.float32)
    return planner.ModelSpec(
        param_shapes={"w": w},
        opt_shapes={"mu": {"w": w}, "nu": {"w": w}},
        batch_shapes={"x": jax.ShapeDtypeStruct((16, 8), jnp.float32)},
        batch_size=16,
    )


def _leaf_shardings(state):
    return [
        (jax.tree_util.keystr(path), str(leaf.sharding))
        for path, leaf in jax.tree_util.tree_leaves_with_path(state)
        if hasattr(leaf, "sharding")
    ]


def _flat_params(state):
    return jax.flatten_util.ravel_pytree(jax.device_get(state.params))[0]


def _run_steps(compiled, state, batch, steps, rng_seed=7):
    rng = jax.random.PRNGKey(rng_seed)
    for _ in range(steps):
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), rng
        )
    return state, metrics


class TestFactorization:
    def test_every_candidate_multiplies_to_device_count(self):
        result = planner.plan(
            _transformer_model_spec(), planner.Topology(num_devices=N)
        )
        assert len(result.table) >= 4
        for entry in result.table:
            axes = entry["plan"]
            product = (
                axes["data"] * axes["sequence"] * axes["pipe"]
                * axes["fsdp"] * axes["model"] * axes["expert"]
            )
            assert product == N, entry["plan"]["name"]
        assert result.best.num_devices == N

    def test_divisibility_constraints_mark_infeasible(self):
        """sp must divide the sequence length, pp the layer count; a
        spec with neither marks every composed candidate infeasible with
        the reason recorded."""
        result = planner.plan(
            _mock_model_spec(), planner.Topology(num_devices=N)
        )
        composed = [
            e for e in result.table
            if e["plan"]["sequence"] > 1 or e["plan"]["pipe"] > 1
        ]
        assert composed and all(not e["feasible"] for e in composed)
        assert all(e["reasons"] for e in composed)
        # Pure DP survives: the mock has no sequence/pipe structure.
        assert result.best.sequence == 1 and result.best.pipe == 1

    def test_memory_infeasible_rejected_with_estimate_in_error(self):
        spec = _mock_model_spec()
        with pytest.raises(planner.PlanError) as err:
            planner.plan(
                spec, planner.Topology(num_devices=N), memory_budget=64
            )
        message = str(err.value)
        assert "64 B" in message
        assert "B/device" in message  # the estimate rides the error

    def test_budget_flag_consulted(self):
        saved = flags.read_raw("T2R_PLAN_MEM_BUDGET")
        try:
            # 1 MB is far below a 64 MB parameter matrix's footprint.
            flags.write_env("T2R_PLAN_MEM_BUDGET", 1)
            with pytest.raises(planner.PlanError):
                planner.plan(
                    _big_synthetic_spec(), planner.Topology(num_devices=N)
                )
        finally:
            flags.restore_env("T2R_PLAN_MEM_BUDGET", saved)

    def test_comm_scoring_uses_wire_formats(self):
        """A quantized constraint must cut the DP comm estimate by the
        collective's real wire ratio (~3.9x for int8 at block 512 on a
        large tree; block padding softens it on tiny trees)."""
        spec = _big_synthetic_spec()
        exact = planner.plan(
            spec, planner.Topology(num_devices=N),
            constraints=planner.Constraints(collective_quant="none"),
        )
        quant = planner.plan(
            spec, planner.Topology(num_devices=N),
            constraints=planner.Constraints(collective_quant="int8"),
        )
        ratio = exact.best.comm_bytes / quant.best.comm_bytes
        assert ratio > 3.5

    def test_pinned_axes_respected(self):
        result = planner.plan(
            _transformer_model_spec(),
            planner.Topology(num_devices=N),
            constraints=planner.Constraints(pinned={"pipe": 2}),
        )
        assert all(e["plan"]["pipe"] == 2 for e in result.table)
        assert result.best.pipe == 2


class TestPresets:
    """Byte-equality pins: the planner preset and the hand-wired twin
    place LEAF-FOR-LEAF identical layouts, and `none`-regime training is
    bitwise."""

    @pytest.mark.parametrize(
        "preset,kwargs",
        [
            ("dp", {}),
            ("dp_zero2", dict(shard_weight_update=True)),
            (
                "dp_zero2_fp16",
                dict(
                    shard_weight_update=True,
                    collective_quant="fp16",
                    collective_block=BLOCK,
                ),
            ),
            (
                "dp_zero2_int8",
                dict(
                    shard_weight_update=True,
                    collective_quant="int8",
                    collective_block=BLOCK,
                ),
            ),
            (
                "dp_zero2_fp8_e4m3",
                dict(
                    shard_weight_update=True,
                    collective_quant="fp8_e4m3",
                    collective_block=BLOCK,
                ),
            ),
        ],
    )
    def test_dp_family_byte_equality_and_bitwise_step(self, preset, kwargs):
        plan = planner.resolve_preset(preset)
        if "collective_block" in kwargs:
            plan = dataclasses.replace(plan, collective_block=BLOCK)
        hand, state_h, batch = _mock_setup(**kwargs)
        planned, state_p, _ = _mock_setup(plan=plan)
        assert _leaf_shardings(state_h) == _leaf_shardings(state_p)
        audit = planner.audit_state_layout(plan, planned.mesh, state_p)
        assert audit["leaves"] > 0 and not audit["mismatches"]
        # Identical regime -> identical program -> bitwise trajectory
        # (for 'none' this IS the pre-PR GSPMD step).
        state_h, _ = _run_steps(hand, state_h, batch, 3)
        state_p, _ = _run_steps(planned, state_p, batch, 3)
        np.testing.assert_array_equal(
            _flat_params(state_h), _flat_params(state_p)
        )

    @pytest.mark.parametrize(
        "preset,mesh_kwargs,model_kwargs,compiled_kwargs",
        [
            # The two ring-attention twins pay ~75s of manual-mode
            # shard_map compiles (x2: hand + planned) for a layout-only
            # assertion — they ride the slow slice per the PR 5 budget
            # discipline. Round 21 moved sp_ulysses (~12s) and plain pp
            # (~8s) there too: dp_pp/dp_pp_zero2 below keep composed
            # pipeline coverage in tier-1 (pp is their strict subset),
            # and ulysses stays fast via test_sp_ulysses_preset_runs +
            # the planner's ulysses-in-pipe enumeration pin.
            pytest.param(
                "dp_sp", dict(data=2, sequence=4), {}, {},
                marks=pytest.mark.slow,
            ),
            pytest.param(
                "sp_ring", dict(data=1, sequence=8), {}, {},
                marks=pytest.mark.slow,
            ),
            pytest.param(
                "sp_ulysses",
                dict(data=1, sequence=8),
                dict(
                    sequence_parallel_mode="ulysses",
                    num_heads=8, head_dim=8,
                ),
                {},
                marks=pytest.mark.slow,
            ),
            pytest.param(
                "pp",
                dict(data=1, pipe=2),
                dict(pipeline_stages=2, pipeline_microbatches=2),
                {},
                marks=pytest.mark.slow,
            ),
            (
                "dp_pp",
                dict(data=2, pipe=2),
                dict(pipeline_stages=2, pipeline_microbatches=2),
                {},
            ),
            (
                "dp_pp_zero2",
                dict(data=2, pipe=2),
                dict(pipeline_stages=2, pipeline_microbatches=2),
                dict(shard_weight_update=True, param_min_shard_size=0),
            ),
        ],
    )
    def test_composed_presets_byte_equal(
        self, preset, mesh_kwargs, model_kwargs, compiled_kwargs
    ):
        plan = planner.resolve_preset(preset)
        if compiled_kwargs.get("param_min_shard_size") == 0:
            plan = dataclasses.replace(plan, param_min_shard_size=0)
        n_dev = int(np.prod(list(mesh_kwargs.values())))
        mesh = mesh_lib.make_mesh(
            devices=jax.devices()[:n_dev], **mesh_kwargs
        )
        model = _transformer(mesh, **model_kwargs)
        batch = _transformer_batch(model)
        hand = train_eval.CompiledModel(
            model, mesh=mesh, donate_state=False, **compiled_kwargs
        )
        state_h = hand.init_state(jax.random.PRNGKey(0), batch)
        plan_mesh = plan.build_mesh()
        model_p = _transformer(plan_mesh, **model_kwargs)
        planned = train_eval.CompiledModel(
            model_p, donate_state=False, plan=plan
        )
        state_p = planned.init_state(jax.random.PRNGKey(0), batch)
        assert _leaf_shardings(state_h) == _leaf_shardings(state_p)
        audit = planner.audit_state_layout(plan, planned.mesh, state_p)
        assert audit["leaves"] > 0 and not audit["mismatches"]

    def test_sp_ulysses_preset_runs(self):
        plan = planner.resolve_preset("sp_ulysses")
        mesh = plan.build_mesh()
        # Ulysses scatters HEADS: an 8-way axis needs heads % 8 == 0.
        model = _transformer(
            mesh, num_heads=8, head_dim=8, **plan.model_kwargs()
        )
        planned = train_eval.CompiledModel(
            model, donate_state=False, plan=plan
        )
        batch = _transformer_batch(model)
        state = planned.init_state(jax.random.PRNGKey(0), batch)
        _, metrics = _run_steps(planned, state, batch, 1)
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    def test_unknown_preset_names_flag_and_menu(self):
        with pytest.raises(KeyError) as err:
            planner.resolve_preset("dp_zero2_int4")
        message = str(err.value)
        assert "T2R_PLAN" in message
        for name in ("dp_zero2_int8", "dp_sp_pp"):
            assert name in message

    def test_model_must_match_plan_structure(self):
        """A plan can place layouts but cannot retrofit model structure:
        a mesh-less model under an SP plan (or a stage-less model under
        a PP plan) would silently train fully replicated behind a green
        replicated-regime audit — it must be rejected at construction."""
        plan = planner.resolve_preset("dp_sp")
        model = _transformer(None)
        with pytest.raises(ValueError, match="sequence"):
            train_eval.CompiledModel(model, donate_state=False, plan=plan)
        plan_pp = planner.resolve_preset("dp_pp")
        model_pp = _transformer(plan_pp.build_mesh())  # pipeline_stages=1
        with pytest.raises(ValueError, match="pipeline_stages"):
            train_eval.CompiledModel(
                model_pp, donate_state=False, plan=plan_pp
            )

    def test_mesh_plan_disagreement_rejected(self):
        plan = planner.resolve_preset("dp_sp")
        mesh = mesh_lib.make_mesh(data=N)
        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        with pytest.raises(ValueError, match="disagree"):
            train_eval.CompiledModel(model, mesh=mesh, plan=plan)


class TestFlagResolution:
    def test_off_resolves_to_none(self):
        saved = flags.read_raw("T2R_PLAN")
        try:
            flags.restore_env("T2R_PLAN", None)
            assert planner.resolve_plan_from_flag() is None
            flags.write_env("T2R_PLAN", "off")
            assert planner.resolve_plan_from_flag() is None
        finally:
            flags.restore_env("T2R_PLAN", saved)

    def test_preset_name_resolves(self):
        saved = flags.read_raw("T2R_PLAN")
        try:
            flags.write_env("T2R_PLAN", "dp_zero2")
            plan = planner.resolve_plan_from_flag()
            assert plan.name == "dp_zero2"
            assert plan.shard_weight_update
        finally:
            flags.restore_env("T2R_PLAN", saved)

    def test_auto_requires_model(self):
        saved = flags.read_raw("T2R_PLAN")
        try:
            flags.write_env("T2R_PLAN", "auto")
            with pytest.raises(ValueError, match="auto"):
                planner.resolve_plan_from_flag()
        finally:
            flags.restore_env("T2R_PLAN", saved)

    def test_plan_is_authoritative_over_env_quant(self):
        """A pinned plan must not pick up ambient T2R_COLLECTIVE_QUANT:
        dp_zero2 stays exact even with int8 exported fleet-wide."""
        saved = flags.read_raw("T2R_COLLECTIVE_QUANT")
        try:
            flags.write_env("T2R_COLLECTIVE_QUANT", "int8")
            planned, state, _ = _mock_setup(
                plan=planner.resolve_preset("dp_zero2")
            )
            assert planned._quant_collective is None
            assert state.collective_residual is None
            planned_q, state_q, _ = _mock_setup(
                plan=planner.resolve_preset("dp_zero2_fp8_e5m2")
            )
            assert planned_q._quant_collective.name == "fp8_e5m2"
            assert state_q.collective_residual is not None
        finally:
            flags.restore_env("T2R_COLLECTIVE_QUANT", saved)


class TestCheckpointRoundtrip:
    def test_same_plan_restores_bitwise(self, tmp_path):
        plan = dataclasses.replace(
            planner.resolve_preset("dp_zero2_int8"), collective_block=BLOCK
        )
        compiled, state, batch = _mock_setup(plan=plan)
        state, _ = _run_steps(compiled, state, batch, 3)
        manager = train_eval.create_checkpoint_manager(
            str(tmp_path), save_interval_steps=1
        )
        manager.save(
            3,
            args=train_eval.ocp.args.StandardSave(
                compiled.persistable_state(state)
            ),
            force=True,
        )
        manager.wait_until_finished()
        compiled_r, _, _ = _mock_setup(plan=plan)
        restored = train_eval.restore_or_init_state(
            manager, compiled_r, jax.random.PRNGKey(0), batch
        )
        manager.close()
        assert int(jax.device_get(restored.step)) == 3
        state, _ = _run_steps(compiled, state, batch, 3, rng_seed=11)
        restored, _ = _run_steps(compiled_r, restored, batch, 3, rng_seed=11)
        np.testing.assert_array_equal(
            _flat_params(state), _flat_params(restored)
        )

    def test_different_plan_fails_loudly(self, tmp_path):
        """A quant-plan checkpoint (flat opt layout) must not silently
        restore into the tree-layout dp_zero2 plan."""
        plan = dataclasses.replace(
            planner.resolve_preset("dp_zero2_int8"), collective_block=BLOCK
        )
        compiled, state, batch = _mock_setup(plan=plan)
        state, _ = _run_steps(compiled, state, batch, 2)
        manager = train_eval.create_checkpoint_manager(
            str(tmp_path), save_interval_steps=1
        )
        manager.save(
            2,
            args=train_eval.ocp.args.StandardSave(
                compiled.persistable_state(state)
            ),
            force=True,
        )
        manager.wait_until_finished()
        compiled_other, _, _ = _mock_setup(
            plan=planner.resolve_preset("dp_zero2")
        )
        with pytest.raises(Exception):
            train_eval.restore_or_init_state(
                manager, compiled_other, jax.random.PRNGKey(0), batch
            )
        manager.close()


class Test3DPlan:
    """The regime that did not exist pre-PR: DP x SP x PP with the
    weight update sharded across BOTH replica axes."""

    def _setup_3d(self, weight_update_axes=None):
        plan = dataclasses.replace(
            planner.resolve_preset("dp_sp_pp"), param_min_shard_size=0
        )
        if weight_update_axes is not None:
            plan = dataclasses.replace(
                plan, weight_update_axes=weight_update_axes,
                name=plan.name + "_datawu",
            )
        mesh = plan.build_mesh()
        model = _transformer(
            mesh, pipeline_stages=2, pipeline_microbatches=2
        )
        compiled = train_eval.CompiledModel(
            model, donate_state=False, plan=plan
        )
        batch = _transformer_batch(model)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        return plan, compiled, state, batch

    # ~12s: the 3D train-step compile just to see one finite loss; the
    # layout assertions stay fast below (collective-schedule pin runs
    # the same _setup_3d audit surface) and the math contract rides the
    # slow loss-parity twin.
    @pytest.mark.slow
    def test_one_step_runs_with_generalized_weight_update(self):
        plan, compiled, state, batch = self._setup_3d()
        audit = planner.audit_state_layout(plan, compiled.mesh, state)
        assert not audit["mismatches"]
        # Opt leaves genuinely shard over the data x sequence PRODUCT
        # (group 4), not data alone — the generalization.
        specs = {
            str(leaf.sharding.spec)
            for _, leaf in jax.tree_util.tree_leaves_with_path(
                state.opt_state
            )
            if hasattr(leaf, "sharding")
        }
        assert any("('data', 'sequence')" in s for s in specs), specs
        assert any("'pipe'" in s for s in specs), specs
        state, metrics = _run_steps(compiled, state, batch, 1)
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    def test_collective_schedule_attributes_all_three_axes(self):
        plan, _, _, _ = self._setup_3d()
        schedule = plan.collective_schedule(_transformer_model_spec())
        axes = {axis for entry in schedule for axis in entry["axes"]}
        assert {"data", "sequence", "pipe"} <= axes
        for entry in schedule:
            assert entry["bytes_per_device_step"] is not None
            assert entry["bytes_per_device_step"] > 0

    @pytest.mark.slow
    def test_loss_parity_with_data_axis_weight_update_twin(self):
        """Multi-step 3D training with the generalized ('data',
        'sequence') weight update matches the ('data',)-sharded twin to
        float tolerance — the sharding is a layout change, not a math
        change."""
        _, compiled, state, batch = self._setup_3d()
        _, compiled_t, state_t, _ = self._setup_3d(
            weight_update_axes=(mesh_lib.DATA_AXIS,)
        )
        losses, losses_t = [], []
        rng = jax.random.PRNGKey(1)
        for _ in range(6):
            state, m = compiled.train_step(
                state, compiled.shard_batch(batch), rng
            )
            losses.append(float(jax.device_get(m["loss"])))
            state_t, m_t = compiled_t.train_step(
                state_t, compiled_t.shard_batch(batch), rng
            )
            losses_t.append(float(jax.device_get(m_t["loss"])))
        assert losses[-1] < losses[0]  # it actually learns
        np.testing.assert_allclose(losses, losses_t, atol=1e-4)


class TestMemoryEstimate:
    def test_zero2_shrinks_opt_estimate(self):
        spec = _big_synthetic_spec()
        dp = planner.resolve_preset("dp")
        zero2 = dataclasses.replace(
            planner.resolve_preset("dp_zero2"), param_min_shard_size=0
        )
        mem_dp = planner.estimate_memory(spec, dp)
        mem_z2 = planner.estimate_memory(spec, zero2)
        assert mem_z2["opt_state"] == mem_dp["opt_state"] // N
        assert mem_dp["total"] > 0

    def test_quant_estimate_uses_flat_layout(self):
        spec = _mock_model_spec()
        quant = planner.resolve_preset("dp_zero2_int8")
        mem = planner.estimate_memory(spec, quant)
        # Per-device flat shard: ~2 moments + residuals on n/8 elements.
        assert mem["opt_state"] < 8 * 4 * spec.n_params

    def test_tp_estimate_shards_params_and_mirrors(self):
        """sharded_params plans divide the param/opt footprint by the
        factor param_sharding actually achieves on each leaf — the
        spec-level twin of the placed rule."""
        spec = _big_synthetic_spec()
        dp = planner.resolve_preset("dp")
        tp = dataclasses.replace(
            planner.ShardingPlan(name="dp4_tp2", data=4, fsdp=2),
            param_min_shard_size=0,
        )
        mem_dp = planner.estimate_memory(spec, dp)
        mem_tp = planner.estimate_memory(spec, tp)
        assert mem_tp["params"] == mem_dp["params"] // 2
        assert mem_tp["opt_state"] == mem_dp["opt_state"] // 2


class TestWidenedFactorization:
    """The PR's search-space widening: the fsdp (tensor-parallel) axis
    joins the enumeration, and ulysses attention composes inside the
    pipeline shard_map (the old 'ring mode only' rejection is gone)."""

    def test_tp_points_enumerated_and_attributed(self):
        result = planner.plan(
            _big_synthetic_spec(), planner.Topology(num_devices=N)
        )
        names = {e["plan"]["name"]: e for e in result.table}
        entry = names["dp4_sp1_pp1_tp2"]
        assert entry["feasible"], entry["reasons"]
        assert entry["plan"]["fsdp"] == 2
        assert entry["plan"]["regime"] == "sharded_params"
        # The fsdp axis is attributed in the comm estimate and the
        # collective schedule.
        assert entry["comm"]["fsdp"] > 0
        plan = planner.ShardingPlan.from_json(entry["plan"])
        schedule = plan.collective_schedule(_big_synthetic_spec())
        sites = {e["site"] for e in schedule}
        assert "fsdp_param_gather" in sites
        # TP pays strictly more wire than pure DP on every composition
        # reachable here: the pure-DP winner is unchanged.
        assert result.best.name == "dp8_sp1_pp1"

    def test_tp_rejected_when_no_leaf_shards(self):
        """The mock's tiny leaves fall below param_min_shard_size: every
        tp point is infeasible with the reason recorded, not silently
        scored as if params sharded."""
        result = planner.plan(
            _mock_model_spec(), planner.Topology(num_devices=N)
        )
        tp_entries = [e for e in result.table if e["plan"]["fsdp"] > 1]
        assert tp_entries
        assert all(not e["feasible"] for e in tp_entries)
        # Where tp is the only composition question (pp=1), the recorded
        # reason is the leaf probe; tp x pp points lead with the
        # composition rejection instead.
        solo_tp = [e for e in tp_entries if e["plan"]["pipe"] == 1]
        assert solo_tp
        for entry in solo_tp:
            assert any("no param leaf" in r for r in entry["reasons"]), (
                entry["reasons"]
            )

    def test_tp_disallowed_by_constraint(self):
        result = planner.plan(
            _big_synthetic_spec(),
            planner.Topology(num_devices=N),
            constraints=planner.Constraints(allow_tp=False),
        )
        for entry in result.table:
            if entry["plan"]["fsdp"] > 1:
                assert "tensor parallelism disallowed" in entry["reasons"]

    def test_tp_pp_composition_rejected_with_reason(self):
        result = planner.plan(
            _transformer_model_spec(), planner.Topology(num_devices=N)
        )
        combos = [
            e for e in result.table
            if e["plan"]["fsdp"] > 1 and e["plan"]["pipe"] > 1
        ]
        assert combos
        for entry in combos:
            assert any("tp x pp" in r for r in entry["reasons"])

    def test_ulysses_composes_with_pipeline(self):
        """dp1_sp4_pp2 under ulysses is now a feasible point — PR 13's
        'sp x pp composes in ring mode only' rejection is retired — while
        the heads-divisibility gate still holds."""
        result = planner.plan(
            _transformer_model_spec(),
            planner.Topology(num_devices=N),
            constraints=planner.Constraints(
                sequence_parallel_mode="ulysses"
            ),
        )
        names = {e["plan"]["name"]: e for e in result.table}
        entry = names["dp1_sp4_pp2"]
        assert entry["feasible"], entry["reasons"]
        assert entry["plan"]["sequence_parallel_mode"] == "ulysses"
        # heads=4 cannot split 8 ways: the gate is intact.
        sp8 = names["dp1_sp8_pp1"]
        assert not sp8["feasible"]
        assert any("heads" in r for r in sp8["reasons"])

    def test_plan_json_roundtrip_every_table_entry(self):
        result = planner.plan(
            _big_synthetic_spec(), planner.Topology(num_devices=N)
        )
        for entry in result.table:
            plan = planner.ShardingPlan.from_json(entry["plan"])
            assert plan.to_json() == entry["plan"]

    def test_plan_json_unknown_field_is_loud(self):
        result = planner.plan(
            _big_synthetic_spec(), planner.Topology(num_devices=N)
        )
        doc = dict(result.best.to_json())
        doc["warp_factor"] = 9
        with pytest.raises(ValueError, match="warp_factor"):
            planner.ShardingPlan.from_json(doc)


class TestMeasuredRerank:
    """Tier 2: the compile-and-measure re-rank over the analytic
    shortlist (the mock's single feasible point keeps this cheap)."""

    def test_rerank_measures_and_records(self):
        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        generator = MockInputGenerator(batch_size=16, seed=0)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        spec = planner.ModelSpec.from_model(model, batch)
        result = planner.plan(spec, planner.Topology(num_devices=N))
        before = train_eval.plan_probe_compile_count()
        reranked, stats = planner.measured_rerank(
            model, batch, result, shortlist=2, steps=1
        )
        paid = train_eval.plan_probe_compile_count() - before
        assert paid == stats["shortlist"] >= 1
        assert stats["winner"] == reranked.best.name
        probed = [
            e for e in reranked.table if e.get("measured") is not None
        ]
        assert len(probed) == stats["shortlist"]
        for entry in probed:
            measured = entry["measured"]
            assert measured["step_time_ms"] > 0
            assert measured["steps_timed"] >= 1
            assert measured["analytic_rank"] >= 0
            assert measured["memory_fit"]
            # The analytic-vs-measured memory audit rides the entry
            # whenever the backend exposes memory_analysis().
            if measured.get("memory_per_device_bytes"):
                err = measured["analytic_memory_error"]
                assert err["ratio"] > 0

    def test_rerank_survives_nothing_measuring(self):
        """When every shortlisted plan skips (a model that cannot run
        any of them), the analytic winner stands."""
        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        generator = MockInputGenerator(batch_size=16, seed=0)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        spec = planner.ModelSpec.from_model(model, batch)
        result = planner.plan(spec, planner.Topology(num_devices=N))
        # A memory budget of one byte fails every measured fit.
        reranked, stats = planner.measured_rerank(
            model, batch, result, shortlist=1, steps=1, memory_budget=1
        )
        measured = [
            e for e in reranked.table if e.get("measured") is not None
        ]
        assert measured
        if measured[0]["measured"].get("memory_per_device_bytes"):
            # Budget gate engaged: the analytic winner stands.
            assert not measured[0]["measured"]["memory_fit"]
            assert "winner" not in stats
            assert reranked.best.name == result.best.name


class TestWidenedParity:
    """Loss-parity twins for the two previously-unreachable plan points
    (the PR's twin discipline): each is a layout change, not a math
    change. The twin shares the exact parameter structure — the
    pipelined model inits per-stage from split rngs, so a non-pipelined
    'twin' would start from different weights."""

    def _run_losses(self, plan, model_kwargs=None, steps=3):
        mesh = plan.build_mesh()
        model = _transformer(mesh, **(model_kwargs or {}))
        compiled = train_eval.CompiledModel(
            model, donate_state=False, plan=plan
        )
        batch = _transformer_batch(model)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        losses = []
        rng = jax.random.PRNGKey(7)
        for _ in range(steps):
            state, metrics = compiled.train_step(
                state, compiled.shard_batch(batch), rng
            )
            losses.append(float(jax.device_get(metrics["loss"])))
        return losses

    @pytest.mark.slow
    def test_ulysses_in_pipe_matches_ring_in_pipe_twin(self):
        def plan_for(mode):
            return dataclasses.replace(
                planner.ShardingPlan(
                    name=f"sp4_{mode}_pp2", sequence=4, pipe=2,
                    sequence_parallel_mode=mode,
                ),
                param_min_shard_size=0,
            )

        losses_u = self._run_losses(
            plan_for("ulysses"),
            dict(pipeline_stages=2, sequence_parallel_mode="ulysses"),
        )
        losses_r = self._run_losses(
            plan_for("ring"),
            dict(pipeline_stages=2, sequence_parallel_mode="ring"),
        )
        np.testing.assert_allclose(losses_u, losses_r, atol=1e-4)

    @pytest.mark.slow
    def test_tp_matches_dp_twin(self):
        tp = dataclasses.replace(
            planner.ShardingPlan(name="dp4_tp2", data=4, fsdp=2),
            param_min_shard_size=0,
        )
        dp = dataclasses.replace(
            planner.ShardingPlan(name="dp8", data=8),
            param_min_shard_size=0,
        )
        np.testing.assert_allclose(
            self._run_losses(tp), self._run_losses(dp), atol=1e-4
        )
