"""Policy / CEM / env-loop / collect-eval tests.

Numeric CEM convergence mirrors the reference's cross_entropy tests; the
CEM-over-critic path is driven end-to-end through a real exported critic
(action tiling contract); run_env + collect_eval_loop run against a toy env.
"""

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.data.tfrecord import read_tfrecords
from tensor2robot_tpu.export import DefaultExportGenerator, save_exported_model
from tensor2robot_tpu.models.base_models import CriticModel, tile_actions_for_cem
from tensor2robot_tpu.policies import (
    CEMPolicy,
    OUExploreRegressionPolicy,
    PerEpisodeSwitchPolicy,
    Policy,
    RegressionPolicy,
    ScheduledExplorationRegressionPolicy,
    SequentialRegressionPolicy,
)
from tensor2robot_tpu.predictors import ExportedSavedModelPredictor
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.research.run_env import Transition, run_env
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.continuous_collect_eval import collect_eval_loop
from tensor2robot_tpu.utils.cross_entropy import CrossEntropyMethod, cem_maximize
from tensor2robot_tpu.utils.writer import TFRecordReplayWriter


class TestCrossEntropyMethod:
    def test_converges_to_quadratic_max(self):
        target = np.array([0.3, -0.6])

        def objective(samples):
            return -np.sum((samples - target) ** 2, axis=-1)

        best, score = cem_maximize(
            objective,
            initial_mean=np.zeros(2),
            initial_stddev=np.ones(2),
            num_samples=256,
            num_iterations=10,
            seed=0,
        )
        np.testing.assert_allclose(best, target, atol=0.05)
        assert score > -0.01

    def test_early_termination(self):
        calls = []

        def objective(samples):
            calls.append(1)
            return -np.sum(samples**2, axis=-1)

        cem = CrossEntropyMethod(
            num_samples=64, num_iterations=50,
            early_termination_stddev=0.5, seed=0,
        )
        cem.run(objective, np.zeros(2), np.ones(2) * 0.1)
        assert len(calls) < 50

    def test_rejects_bad_objective_shape(self):
        cem = CrossEntropyMethod(num_samples=8, seed=0)
        with pytest.raises(ValueError, match="scores"):
            cem.run(lambda s: np.zeros((3,)), np.zeros(1), np.ones(1))


# -- a tiny critic whose q is computable in closed form -----------------------

_POP = 32  # CEM population == exported action_batch_size


class _QuadraticCriticNetwork(nn.Module):
    """q = -(action - mean(state))^2 with a dummy param so init works."""

    @nn.compact
    def __call__(self, features, mode: str):
        bias = self.param("bias", nn.initializers.zeros, (1,))
        state = features["state"]["obs"]
        action = features["action"]["a"]
        if action.ndim == 3:  # predict-mode population [b, n, 1] -> megabatch
            state, action = tile_actions_for_cem(
                TensorSpecStruct({"obs": state}), action
            )
            state = state["obs"]
        target = state.mean(axis=-1, keepdims=True)
        q = -((action - target) ** 2).sum(axis=-1) + bias[0]
        out = TensorSpecStruct()
        out["q_predicted"] = q
        return out


class _QuadraticCritic(CriticModel):
    def create_network(self):
        return _QuadraticCriticNetwork()

    def get_state_specification(self):
        spec = TensorSpecStruct()
        spec["obs"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="obs")
        return spec

    def get_action_specification(self):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="a")
        return spec


@pytest.fixture(scope="module")
def critic_predictor(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("critic_export"))
    model = _QuadraticCritic(device_type="cpu", action_batch_size=_POP)
    compiled = CompiledModel(model, donate_state=False)
    generator = DefaultExportGenerator()
    generator.set_specification_from_model(model)
    example = generator.create_example_features()
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        TensorSpecStruct({k: np.zeros(v.shape, v.dtype) for k, v in example.items()}),
    )
    save_exported_model(
        root,
        variables=variables,
        feature_spec=generator.serving_input_spec(),
        global_step=1,
        predict_fn=generator.create_serving_fn(compiled, variables),
        example_features=example,
    )
    predictor = ExportedSavedModelPredictor(export_dir=root)
    assert predictor.restore()
    return predictor


class TestCEMPolicy:
    def test_cem_finds_argmax_action(self, critic_predictor):
        policy = CEMPolicy(
            critic_predictor,
            action_size=1,
            cem_samples=_POP,
            cem_iterations=5,
            seed=0,
        )
        # Optimal action = mean(state) = 0.5.
        state = {"state/obs": np.array([0.2, 0.8], np.float32)}
        action = policy.SelectAction(state)
        np.testing.assert_allclose(action, [0.5], atol=0.1)

    def test_sample_action_interface(self, critic_predictor):
        policy = CEMPolicy(
            critic_predictor, action_size=1, cem_samples=_POP, seed=0
        )
        action, debug = policy.sample_action(
            {"state/obs": np.zeros(2, np.float32)}, explore_prob=1.0
        )
        assert action.shape == (1,)
        assert isinstance(debug, dict)


class TestJaxCEM:
    def test_converges_to_quadratic_max_under_jit(self):
        from tensor2robot_tpu.ops import cem as cem_ops

        def objective(samples):  # max at 0.3
            return -jnp.sum((samples - 0.3) ** 2, axis=-1)

        run = jax.jit(
            lambda key: cem_ops.cross_entropy_maximize(
                objective,
                jnp.zeros((2,), jnp.float32),
                jnp.ones((2,), jnp.float32),
                key,
                num_samples=64,
                num_iterations=8,
                elite_fraction=0.1,
                low=-1.0,
                high=1.0,
            )
        )
        mean, stddev, best, best_q = run(jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(best), [0.3, 0.3], atol=0.05)
        assert float(best_q) > -0.01
        assert np.all(np.asarray(stddev) < 0.5)  # proposal tightened

    def test_best_tracks_across_iterations(self):
        """best_score is monotone over the run: it must be >= the score of
        the final mean (the all-iterations argmax contract)."""
        from tensor2robot_tpu.ops import cem as cem_ops

        def objective(samples):
            return -jnp.sum(samples ** 2, axis=-1)

        mean, _, best, best_q = cem_ops.cross_entropy_maximize(
            objective,
            jnp.full((3,), 0.9, jnp.float32),
            jnp.full((3,), 0.5, jnp.float32),
            jax.random.PRNGKey(1),
            num_samples=32,
            num_iterations=4,
        )
        final_mean_q = float(objective(mean[None, :])[0])
        assert float(best_q) >= final_mean_q - 1e-6


class TestJitCEMPolicy:
    def test_jit_cem_finds_argmax_action(self, critic_predictor):
        from tensor2robot_tpu.policies import JitCEMPolicy

        policy = JitCEMPolicy(
            critic_predictor,
            action_size=1,
            cem_samples=_POP,
            cem_iterations=5,
            seed=0,
        )
        state = {"state/obs": np.array([0.2, 0.8], np.float32)}
        action = policy.SelectAction(state)
        np.testing.assert_allclose(action, [0.5], atol=0.1)
        # The jitted selector was actually built and used (no fallback).
        assert policy._jit_select is not None
        assert policy._jit_source is critic_predictor.loaded_model
        # Repeat calls reuse the compiled program and stay in-bounds.
        rng = np.random.RandomState(1)
        for _ in range(3):
            action = policy.SelectAction(
                {"state/obs": rng.uniform(-1, 1, 2).astype(np.float32)}
            )
            assert -1.0 <= float(action[0]) <= 1.0

    def test_jit_cem_falls_back_without_stablehlo(self):
        """A predictor with no loaded_model surface uses the numpy CEM."""
        from tensor2robot_tpu.policies import JitCEMPolicy

        class FakePredictor:
            def get_feature_specification(self):
                spec = TensorSpecStruct()
                spec["state/obs"] = ExtendedTensorSpec(
                    shape=(2,), dtype=np.float32, name="obs"
                )
                spec["action/a"] = ExtendedTensorSpec(
                    shape=(1,), dtype=np.float32, name="a"
                )
                return spec

            def predict(self, batch):
                action = np.asarray(batch["action/a"])[0]
                state = np.asarray(batch["state/obs"])[0]
                target = state.mean(axis=-1, keepdims=True)
                return {
                    "q_predicted": -((action - target) ** 2).sum(axis=-1)
                }

            def restore(self, is_async=False):
                return True

        policy = JitCEMPolicy(
            FakePredictor(), action_size=1, cem_samples=_POP,
            cem_iterations=5, seed=0,
        )
        action = policy.SelectAction({"state/obs": np.array([0.4, 0.6], np.float32)})
        np.testing.assert_allclose(action, [0.5], atol=0.1)
        assert policy._jit_select is None  # fell back to the numpy engine


class _TwoLeafCriticNetwork(nn.Module):
    """q = -(a - s0)^2 - (b - s1)^2 over a TWO-leaf action spec."""

    @nn.compact
    def __call__(self, features, mode: str):
        bias = self.param("bias", nn.initializers.zeros, (1,))
        state = features["state"]["obs"]
        a, b = features["action"]["a"], features["action"]["b"]
        if a.ndim == 3:  # predict-mode population: megabatch like the ref
            state_struct, action = tile_actions_for_cem(
                TensorSpecStruct({"obs": state}),
                jnp.concatenate([a, b], axis=-1),
            )
            state = state_struct["obs"]
            a, b = action[..., :2], action[..., 2:]
        q = (
            -((a - state[..., :1]) ** 2).sum(axis=-1)
            - ((b - state[..., 1:]) ** 2).sum(axis=-1)
            + bias[0]
        )
        out = TensorSpecStruct()
        out["q_predicted"] = q
        return out


class _TwoLeafCritic(CriticModel):
    def create_network(self):
        return _TwoLeafCriticNetwork()

    def get_state_specification(self):
        spec = TensorSpecStruct()
        spec["obs"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="obs")
        return spec

    def get_action_specification(self):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="a")
        spec["b"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="b")
        return spec


def _export_two_leaf_critic(root: str, quantize: bool = False):
    """Exports a _TwoLeafCritic and returns a restored predictor (the one
    recipe the plain fixture and the quantized-composition test share)."""
    model = _TwoLeafCritic(device_type="cpu", action_batch_size=_POP)
    compiled = CompiledModel(model, donate_state=False)
    generator = DefaultExportGenerator()
    generator.set_specification_from_model(model)
    example = generator.create_example_features()
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        TensorSpecStruct({k: np.zeros(v.shape, v.dtype) for k, v in example.items()}),
    )
    save_exported_model(
        root,
        variables=variables,
        feature_spec=generator.serving_input_spec(),
        global_step=1,
        predict_fn=generator.create_serving_fn(
            compiled, variables, quantize_weights=quantize
        ),
        example_features=example,
        quantize_weights=quantize,
    )
    predictor = ExportedSavedModelPredictor(export_dir=root)
    assert predictor.restore()
    return predictor


@pytest.fixture(scope="module")
def two_leaf_predictor(tmp_path_factory):
    return _export_two_leaf_critic(
        str(tmp_path_factory.mktemp("two_leaf_export"))
    )


class TestMultiLeafActionCEM:
    """Multi-part action specs (the QT-Opt shape: several named action
    components) optimized as one flat CEM vector, split per leaf in spec
    order by the objective — in BOTH engines.

    History: the two jit-engine tests here were seed failures from the
    seed round onward. Root cause (measured, not engine-specific): at
    this geometry (32 samples -> 3 elites, 8 iterations, 3-dim action)
    BOTH engines missed atol=0.12 on ~25% of seeds — std over 3 elite
    points is a noisy underestimate, so the proposal collapses around an
    early suboptimal mean and no later sample can reach the optimum;
    the numpy tests simply drew lucky seeds while the jit tests' PRNG
    stream drew unlucky ones. Fixed in the ENGINES (smoothed elite
    refit, ops/cem.py + utils/cross_entropy.py), which drops the miss
    rate to <1% of seeds for both."""

    def _assert_optimum(self, policy):
        # Optimum: a == (s0, s0), b == s1 -> flat [s0, s0, s1] ... the
        # network scores a against s0 broadcast and b against s1.
        state = {"state/obs": np.array([0.4, -0.3], np.float32)}
        action = policy.SelectAction(state)
        assert action.shape == (3,)
        np.testing.assert_allclose(action[:2], [0.4, 0.4], atol=0.12)
        np.testing.assert_allclose(action[2:], [-0.3], atol=0.12)

    def test_numpy_engine(self, two_leaf_predictor):
        self._assert_optimum(
            CEMPolicy(
                two_leaf_predictor, action_size=3, cem_samples=_POP,
                cem_iterations=8, seed=0,
            )
        )

    def test_jit_engine(self, two_leaf_predictor):
        from tensor2robot_tpu.policies import JitCEMPolicy

        policy = JitCEMPolicy(
            two_leaf_predictor, action_size=3, cem_samples=_POP,
            cem_iterations=8, seed=0,
        )
        self._assert_optimum(policy)
        assert policy._jit_select is not None  # really took the jit path

    def test_jit_engine_over_quantized_export(self, tmp_path):
        """Composition: the jitted CEM traces through a weights-as-args
        int8 artifact (the robot-fleet deployment shape: small download,
        fused selection)."""
        from tensor2robot_tpu.policies import JitCEMPolicy

        predictor = _export_two_leaf_critic(
            str(tmp_path / "q_export"), quantize=True
        )
        assert predictor.loaded_model.metadata["stablehlo_weights_in_args"]
        policy = JitCEMPolicy(
            predictor, action_size=3, cem_samples=_POP,
            cem_iterations=8, seed=0,
        )
        self._assert_optimum(policy)
        assert policy._jit_select is not None

    def test_action_size_mismatch_rejected(self, two_leaf_predictor):
        policy = CEMPolicy(
            two_leaf_predictor, action_size=5, cem_samples=_POP, seed=0
        )
        with pytest.raises(ValueError, match="sum to 3"):
            policy.SelectAction({"state/obs": np.zeros(2, np.float32)})


# -- regression policies over a fake predictor --------------------------------


class TestCEMBounds:
    def test_cem_respects_asymmetric_bounds(self):
        # Objective favors the upper edge of [0, 1]; with mean seeded at the
        # box center and clipped sampling, CEM must find it.
        from tensor2robot_tpu.utils.cross_entropy import CrossEntropyMethod

        def sample_clipped(mean, stddev, n, rng):
            s = rng.normal(mean[None], stddev[None], (n,) + mean.shape)
            return np.clip(s, 0.0, 1.0)

        cem = CrossEntropyMethod(
            sample_fn=sample_clipped, num_samples=128, num_iterations=5, seed=0
        )
        objective = lambda a: -np.sum((a - 0.9) ** 2, axis=-1)
        mean, _, best, _ = cem.run(
            objective, np.full((3,), 0.5), np.full((3,), 0.5)
        )
        np.testing.assert_allclose(best, 0.9, atol=0.1)
        assert np.all(mean >= 0.0) and np.all(mean <= 1.0)


class _FakeRegressionPredictor(AbstractPredictor):
    """Action = obs[:1] * 2, counts restores."""

    def __init__(self):
        self.restores = 0
        self._step = 0

    def predict(self, features):
        x = np.asarray(features["x"])
        if x.ndim == 3:  # [b, time, d] sequential variant: use newest frame
            x = x[:, -1]
        return {"inference_output": x[:, :1] * 2.0}

    def get_feature_specification(self):
        spec = TensorSpecStruct()
        spec["x"] = ExtendedTensorSpec(shape=(3,), dtype=np.float32, name="x")
        return spec

    def restore(self, is_async: bool = False):
        self.restores += 1
        self._step += 10
        return True

    def init_randomly(self):
        self._step = 0

    @property
    def model_version(self):
        return self._step

    @property
    def global_step(self):
        return self._step

    @property
    def model_path(self):
        return None


class TestRegressionPolicies:
    def test_regression_policy_bare_array_obs(self):
        policy = RegressionPolicy(_FakeRegressionPredictor())
        action = policy.SelectAction(np.array([1.5, 0.0, 0.0], np.float32))
        np.testing.assert_allclose(action, [3.0])

    def test_sequential_policy_stacks_history(self):
        policy = SequentialRegressionPolicy(
            _FakeRegressionPredictor(), history_length=3
        )
        policy.reset()
        for value in (1.0, 2.0, 3.0):
            action = policy.SelectAction(np.array([value, 0, 0], np.float32))
        np.testing.assert_allclose(action, [6.0])  # newest frame * 2

    def test_ou_explore_adds_noise_only_when_exploring(self):
        policy = OUExploreRegressionPolicy(_FakeRegressionPredictor())
        policy.seed(0)
        obs = np.array([1.0, 0, 0], np.float32)
        greedy, _ = policy.sample_action(obs, explore_prob=0.0)
        np.testing.assert_allclose(greedy, [2.0])
        noisy, debug = policy.sample_action(obs, explore_prob=1.0)
        assert not np.allclose(noisy, [2.0])
        assert "ou_noise" in debug

    def test_scheduled_exploration_decays(self):
        predictor = _FakeRegressionPredictor()
        policy = ScheduledExplorationRegressionPolicy(
            predictor, initial_stddev=0.5, final_stddev=0.0, decay_steps=20
        )
        assert policy.current_stddev() == pytest.approx(0.5)
        predictor.restore()  # step 10
        assert policy.current_stddev() == pytest.approx(0.25)
        predictor.restore()  # step 20
        assert policy.current_stddev() == pytest.approx(0.0)
        predictor.restore()  # step 30: clamped
        assert policy.current_stddev() == pytest.approx(0.0)

    def test_per_episode_switch(self):
        greedy = RegressionPolicy(_FakeRegressionPredictor())
        explore = OUExploreRegressionPolicy(_FakeRegressionPredictor())
        switch = PerEpisodeSwitchPolicy(explore, greedy)
        switch.seed(0)
        switch.reset(explore_prob=0.0)
        assert switch.active_policy is greedy
        switch.reset(explore_prob=1.0)
        assert switch.active_policy is explore

    def test_per_episode_switch_constructor_prob_survives_bare_reset(self):
        # run_env calls reset() with no args; the constructor-owned
        # explore_prob must drive the switch (reference policies.py:335-346).
        greedy = RegressionPolicy(_FakeRegressionPredictor())
        explore = OUExploreRegressionPolicy(_FakeRegressionPredictor())
        switch = PerEpisodeSwitchPolicy(explore, greedy, explore_prob=1.0)
        switch.seed(0)
        switch.reset()
        assert switch.active_policy is explore


# -- env loop + collect/eval --------------------------------------------------


class _ToyEnv:
    """1-D chase: obs = [pos, target, 0]; reward = -|pos - target|."""

    def __init__(self, horizon=5):
        self._horizon = horizon
        self._t = 0
        self._pos = 0.0

    def reset(self):
        self._t, self._pos = 0, 0.0
        return np.array([self._pos, 1.0, 0.0], np.float32)

    def step(self, action):
        self._pos += float(np.asarray(action).reshape(-1)[0]) * 0.1
        self._t += 1
        obs = np.array([self._pos, 1.0, 0.0], np.float32)
        reward = -abs(self._pos - 1.0)
        return obs, reward, self._t >= self._horizon, {}


def _transition_record(t: Transition) -> bytes:
    from tensor2robot_tpu.data.encoder import encode_example

    spec = TensorSpecStruct()
    spec["obs"] = ExtendedTensorSpec(shape=(3,), dtype=np.float32, name="obs")
    spec["reward"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="reward")
    return encode_example(
        spec, {"obs": t.obs, "reward": np.array([t.reward], np.float32)}
    )


class TestRunEnv:
    def test_episodes_and_replay_shards(self, tmp_path):
        policy = RegressionPolicy(_FakeRegressionPredictor())
        writer = TFRecordReplayWriter()
        rewards = run_env(
            _ToyEnv(),
            policy,
            num_episodes=2,
            replay_writer=writer,
            replay_path=str(tmp_path / "shard"),
            transition_to_record_fn=_transition_record,
        )
        assert len(rewards) == 2
        shards = [f for f in os.listdir(tmp_path) if f.endswith(".tfrecord")]
        assert len(shards) == 1
        records = list(read_tfrecords(str(tmp_path / shards[0])))
        assert len(records) == 10  # 2 episodes x 5 steps

    def test_max_episode_steps(self):
        policy = RegressionPolicy(_FakeRegressionPredictor())
        rewards = run_env(
            _ToyEnv(horizon=100), policy, num_episodes=1, max_episode_steps=3
        )
        assert len(rewards) == 1

    def test_run_tfagents_env_matches_gym_path(self):
        """The TimeStep adapter drives the same loop to the same rewards."""
        import dataclasses

        from tensor2robot_tpu.research.run_env import run_tfagents_env

        @dataclasses.dataclass
        class _TimeStep:
            observation: np.ndarray
            reward: float
            last: bool

            def is_last(self):
                return self.last

        class _TfAgentsToyEnv:
            """_ToyEnv re-skinned behind the TF-Agents TimeStep protocol."""

            def __init__(self):
                self._env = _ToyEnv()

            def reset(self):
                return _TimeStep(self._env.reset(), None, False)

            def step(self, action):
                obs, reward, done, _ = self._env.step(action)
                return _TimeStep(obs, reward, done)

        policy = RegressionPolicy(_FakeRegressionPredictor())
        tfa_rewards = run_tfagents_env(
            _TfAgentsToyEnv(), policy, num_episodes=2
        )
        gym_rewards = run_env(_ToyEnv(), policy, num_episodes=2)
        assert tfa_rewards == gym_rewards


class TestCollectEvalLoop:
    def test_loop_runs_and_stops_at_max_steps(self, tmp_path):
        policy = RegressionPolicy(_FakeRegressionPredictor())
        calls = []

        def run_agent_fn(env, policy, num_episodes, output_dir, global_step):
            calls.append(
                (os.path.basename(output_dir), num_episodes, global_step)
            )
            run_env(env, policy, num_episodes=num_episodes)

        final = collect_eval_loop(
            root_dir=str(tmp_path),
            policy=policy,
            run_agent_fn=run_agent_fn,
            collect_env=_ToyEnv(),
            eval_env=_ToyEnv(),
            num_collect=1,
            num_eval=1,
            max_steps=10,  # fake predictor hits step 10 on first restore
            idle_sleep_secs=0.0,
        )
        assert final == 10
        assert ("policy_collect", 1, 10) in calls
        assert ("policy_eval", 1, 10) in calls
