"""serving/policies.py: the multi-policy resident set.

One replica, N policies (ROADMAP item 2): requests name their policy,
misses take a counted cold-load path or a typed refusal, and the
resident set stays under a memory budget by LRU-evicting idle policies
— with reloads producing BITWISE-identical replies (the artifact
store's hash-verified reconstruction seen from the serving side).
Most tests drive `MultiPolicyServer` in-process over the jax-free mock
loader; the fleet test runs the same catalog through real replica
processes behind the FleetRouter and asserts the placement surface
(resident sets, eviction/cold-load counters) rides the health
snapshots into the router's own snapshot.
"""

import time

import numpy as np
import pytest

from tensor2robot_tpu.serving import (
    FleetRouter,
    MultiPolicyServer,
    PolicyError,
    PolicyEvicted,
    PolicyLoadFailed,
    PolicyUnknown,
    ReplicaSpec,
    multi_policy_mock_factory,
)


@pytest.fixture(autouse=True)
def _lock_sanitizer_armed(locksmith_sanitizer):
    """Every run of this chaos suite doubles as a deadlock hunt: the
    lock sanitizer (testing/locksmith.py) is armed for each test and
    teardown fails on any observed lock-order cycle or hold-budget
    violation (fixture: tests/conftest.py)."""
    yield


_MB = 1 << 20

CATALOG = {
    "pA": {"scale": 2.0, "bias": 1.0, "version": 3, "mem_bytes": _MB},
    "pB": {"scale": -1.0, "bias": 0.5, "version": 4, "mem_bytes": _MB},
    "pC": {"scale": 0.25, "bias": -2.0, "version": 5, "mem_bytes": _MB},
    "pD": {"scale": 10.0, "bias": 0.0, "version": 6, "mem_bytes": _MB},
}


def _server(**kwargs):
    kwargs.setdefault("service_ms", 0.0)
    kwargs.setdefault("cold_load", True)
    return multi_policy_mock_factory(CATALOG, **kwargs)


def _features(value=1.0, n=4):
    return {"x": np.full((n,), value, np.float32)}


def _y(server, policy_id=None, value=1.0):
    future = server.submit(
        _features(value), deadline_ms=10_000, policy_id=policy_id
    )
    return future.result(timeout=10.0).outputs["y"]


def _twin(policy_id, value=1.0, n=4):
    entry = CATALOG[policy_id]
    total = float(np.sum(np.full((n,), value, np.float32).astype(np.float64)))
    return np.float32(total * entry["scale"] + entry["bias"])


class TestResidency:
    def test_submit_routes_to_named_policy_bitwise_vs_twin(self):
        server = _server()
        try:
            for pid in CATALOG:
                got = _y(server, policy_id=pid, value=1.5)
                want = _twin(pid, value=1.5)
                assert got == want and got.tobytes() == want.tobytes(), pid
            # Unnamed submits serve the default (first catalog entry).
            assert _y(server, value=1.5) == _twin("pA", value=1.5)
            assert server.snapshot()["default_policy"] == "pA"
        finally:
            server.stop()

    def test_lru_eviction_under_budget_reload_identical(self):
        server = _server(mem_budget_mb=2)
        try:
            first_a = _y(server, policy_id="pA")
            _y(server, policy_id="pB")
            assert server.resident_policies() == ["pA", "pB"]
            # pA is the least recently used — pC's load evicts it.
            _y(server, policy_id="pC")
            assert server.resident_policies() == ["pB", "pC"]
            snap = server.snapshot()
            assert snap["policy_evictions"] == 1
            assert snap["policy_loads"] == 3
            # Reload after eviction: counted as a cold load, reply
            # bitwise-identical to the pre-eviction reply.
            again_a = _y(server, policy_id="pA")
            assert again_a.tobytes() == first_a.tobytes()
            snap = server.snapshot()
            assert snap["policy_cold_loads"] == 4
            assert snap["policy_evictions"] == 2  # pB went to admit pA
            assert server.resident_policies() == ["pC", "pA"]
        finally:
            server.stop()

    def test_use_bumps_lru_so_hot_policies_survive(self):
        server = _server(mem_budget_mb=2)
        try:
            _y(server, policy_id="pA")
            _y(server, policy_id="pB")
            _y(server, policy_id="pA")  # pA is now most-recent
            _y(server, policy_id="pC")  # evicts pB, not pA
            assert server.resident_policies() == ["pA", "pC"]
        finally:
            server.stop()

    def test_max_resident_cap(self):
        server = _server(max_resident=2)
        try:
            for pid in ("pA", "pB", "pC", "pD"):
                _y(server, policy_id=pid)
            assert server.resident_policies() == ["pC", "pD"]
            assert server.snapshot()["policy_evictions"] == 2
        finally:
            server.stop()

    def test_preload_counts_as_warm_not_cold(self):
        server = _server(preload=("pA", "pB"))
        try:
            snap = server.snapshot()
            assert snap["policy_loads"] == 2
            assert snap["policy_cold_loads"] == 0
            _y(server, policy_id="pC")
            assert server.snapshot()["policy_cold_loads"] == 1
        finally:
            server.stop()


class TestTypedRefusals:
    def test_cold_load_disabled_evicted_vs_unknown(self):
        """With cold loads off the refusal NAMES the cause: a policy
        evicted under the budget is PolicyEvicted (route to a resident
        replica); one never resident here is PolicyUnknown."""
        server = _server(
            cold_load=False, mem_budget_mb=2,
            preload=("pA", "pB", "pC"),  # preloading pC evicts idle pA
        )
        try:
            assert server.resident_policies() == ["pB", "pC"]
            with pytest.raises(PolicyEvicted):
                server.submit(_features(), policy_id="pA")
            with pytest.raises(PolicyUnknown):
                server.submit(_features(), policy_id="pD")
            # Resident policies still serve.
            assert _y(server, policy_id="pB") == _twin("pB")
        finally:
            server.stop()

    def test_uncataloged_policy_and_loader_failure(self):
        server = _server()
        try:
            with pytest.raises(PolicyUnknown):
                server.submit(_features(), policy_id="never-published")
        finally:
            server.stop()

        def broken_loader(policy_id):
            raise OSError(f"store lost {policy_id}")

        broken = MultiPolicyServer(broken_loader, ["pX"])
        try:
            with pytest.raises(PolicyLoadFailed):
                broken.submit(_features(), policy_id="pX")
        finally:
            broken.stop()

    def test_stopped_server_refuses(self):
        server = _server()
        server.stop()
        with pytest.raises(PolicyError):
            server.submit(_features(), policy_id="pA")


class TestSurface:
    def test_snapshot_placement_keys(self):
        server = _server(mem_budget_mb=3, preload=("pA", "pB"))
        try:
            snap = server.snapshot()
            assert snap["multi_policy"] is True
            assert snap["resident_policies"] == ["pA", "pB"]
            assert snap["policy_mem_bytes"] == {"pA": _MB, "pB": _MB}
            assert snap["policy_mem_budget_bytes"] == 3 * _MB
            assert snap["policy_versions"] == {"pA": 3, "pB": 4}
            assert snap["model_version"] == 3  # the default policy's
            assert snap["catalog_size"] == 4
            # The anchor sub-server's health rides along (completed
            # counters, prewarm attribution) — the router's health loop
            # reads ONE merged dict.
            assert "counters" in snap and "prewarm_source" in snap
        finally:
            server.stop()

    def test_hot_swap_targets_one_policy(self):
        server = _server(preload=("pA", "pB"))
        try:
            assert server.policy_version("pA") == 3
            assert server.hot_swap(wait=True, policy_id="pA") is True
            assert server.policy_version("pA") == 4
            assert server.policy_version("pB") == 4  # untouched
            # Non-resident: trivially true — the next cold load picks up
            # whatever the store now holds.
            assert server.hot_swap(wait=True, policy_id="pC") is True
            with pytest.raises(PolicyUnknown):
                server.hot_swap(wait=True, policy_id="nope")
        finally:
            server.stop()


class TestFleetPlacement:
    def test_fleet_serves_catalog_and_router_sees_residency(self):
        """The same catalog through real replica processes: per-policy
        replies bitwise vs the twin formula, the placement surface
        (resident sets + churn counters) visible in router.snapshot(),
        placement-aware dispatch counted, and a per-policy rolling swap
        that only touches the named policy."""
        spec = ReplicaSpec(
            factory=multi_policy_mock_factory,
            factory_kwargs={
                "catalog": CATALOG,
                "service_ms": 0.5,
                "preload": ("pA",),
                "mem_budget_mb": 2,
            },
        )
        router = FleetRouter(
            spec, 2, probe_interval_ms=50.0, backoff_ms=5.0
        ).start(timeout_s=90.0)
        try:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if all(s == "up" for s in router.replica_states()):
                    break
                time.sleep(0.02)
            for pid in ("pA", "pB", "pC"):
                response = router.call(
                    _features(2.0), deadline_ms=20_000, policy_id=pid
                )
                want = _twin(pid, value=2.0)
                assert response.outputs["y"].tobytes() == want.tobytes()
            # Health probes carry residency to the router snapshot.
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                snap = router.snapshot()
                residents = [
                    r.get("resident_policies")
                    for r in snap["replicas"]
                    if r.get("resident_policies")
                ]
                if residents and any(
                    "pB" in r or "pC" in r for r in residents
                ):
                    break
                time.sleep(0.05)
            assert residents, snap["replicas"]
            for r in snap["replicas"]:
                assert r.get("policy_cold_loads") is not None
                assert r.get("policy_evictions") is not None
            # Placement-aware dispatch: a repeat of a resident policy
            # counts a resident dispatch.
            router.call(_features(2.0), deadline_ms=20_000, policy_id="pB")
            counters = router.snapshot()["counters"]
            assert (
                counters.get("policy_resident_dispatches", 0)
                + counters.get("policy_cold_dispatches", 0)
            ) > 0
            # One policy's publish: the fleet swaps only that policy.
            result = router.rolling_swap(
                swap_timeout_s=60.0, policy_id="pB"
            )
            assert result["failed"] is None
            assert result["swapped"]
        finally:
            router.stop()
