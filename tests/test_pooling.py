"""Non-overlapping max pool: forward parity with nn.max_pool, the
scatter-free gradient, and the structural no-SelectAndScatter pin."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.ops.pooling import max_pool_nonoverlap


class TestForwardParity:
    @pytest.mark.parametrize("window", [(3, 3), (2, 2), (4, 4), (5, 5)])
    @pytest.mark.parametrize(
        "shape",
        [(2, 236, 236, 4), (2, 79, 79, 4), (1, 6, 6, 3), (3, 7, 11, 2)],
    )
    def test_matches_nn_max_pool_same(self, window, shape):
        x = jax.random.normal(jax.random.PRNGKey(0), shape)
        got = max_pool_nonoverlap(x, window)
        want = nn.max_pool(x, window, strides=window, padding="SAME")
        assert got.shape == want.shape
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("window", [(3, 3), (2, 2)])
    @pytest.mark.parametrize(
        "shape", [(2, 7, 11, 3), (1, 6, 6, 2), (2, 9, 8, 4)]
    )
    def test_matches_nn_max_pool_valid(self, window, shape):
        x = jax.random.normal(jax.random.PRNGKey(4), shape)
        got = max_pool_nonoverlap(x, window, "VALID")
        want = nn.max_pool(x, window, strides=window, padding="VALID")
        assert got.shape == want.shape
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_bfloat16(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 9, 9, 8), jnp.bfloat16)
        got = max_pool_nonoverlap(x, (3, 3))
        want = nn.max_pool(x, (3, 3), strides=(3, 3), padding="SAME")
        assert got.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(got, np.float32), np.asarray(want, np.float32)
        )


class TestGradient:
    @pytest.mark.parametrize("window", [(3, 3), (2, 2), (4, 4)])
    def test_matches_select_and_scatter_without_ties(self, window):
        # Continuous random input: ties have probability ~0, where the
        # custom VJP must agree exactly with XLA's select-and-scatter.
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 10, 13, 3))

        def loss_custom(x):
            return jnp.sum(max_pool_nonoverlap(x, window) ** 2)

        def loss_xla(x):
            return jnp.sum(
                nn.max_pool(x, window, strides=window, padding="SAME") ** 2
            )

        np.testing.assert_allclose(
            np.asarray(jax.grad(loss_custom)(x)),
            np.asarray(jax.grad(loss_xla)(x)),
            rtol=1e-6,
        )

    def test_gradient_mass_is_preserved(self):
        # Each output's cotangent lands in its window exactly once (split
        # over ties, but summing to the original) — including windows that
        # straddle the SAME padding.
        x = jnp.zeros((1, 7, 7, 1))  # all ties everywhere

        def loss(x):
            return jnp.sum(max_pool_nonoverlap(x, (3, 3)) * 2.0)

        gx = jax.grad(loss)(x)
        np.testing.assert_allclose(float(jnp.sum(gx)), 2.0 * 3 * 3, rtol=1e-6)

    def test_ties_split_equally(self):
        x = jnp.array([[1.0, 1.0], [0.0, 1.0]]).reshape(1, 2, 2, 1)
        gx = jax.grad(lambda x: jnp.sum(max_pool_nonoverlap(x, (2, 2))))(x)
        np.testing.assert_allclose(
            np.asarray(gx).reshape(2, 2),
            np.array([[1 / 3, 1 / 3], [0.0, 1 / 3]]),
            rtol=1e-6,
        )

    def test_valid_gradient_matches_xla_and_zeroes_remainder(self):
        # VALID drops the trailing remainder; those inputs must get zero
        # gradient, and covered inputs must match select-and-scatter on
        # tie-free data.
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 7, 11, 3))

        def loss_custom(x):
            return jnp.sum(max_pool_nonoverlap(x, (3, 3), "VALID") ** 2)

        def loss_xla(x):
            return jnp.sum(
                nn.max_pool(x, (3, 3), strides=(3, 3), padding="VALID") ** 2
            )

        g_custom = np.asarray(jax.grad(loss_custom)(x))
        g_xla = np.asarray(jax.grad(loss_xla)(x))
        np.testing.assert_allclose(g_custom, g_xla, rtol=1e-6)
        assert np.all(g_custom[:, 6:, :, :] == 0)
        assert np.all(g_custom[:, :, 9:, :] == 0)

    def test_grad_dtype_follows_input(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (1, 6, 6, 2), jnp.bfloat16)
        gx = jax.grad(
            lambda x: jnp.sum(max_pool_nonoverlap(x, (2, 2)).astype(jnp.float32))
        )(x)
        assert gx.dtype == jnp.bfloat16


class TestStructural:
    def test_backward_has_no_select_and_scatter(self):
        """The whole point: the pool gradient must not lower to XLA
        SelectAndScatter (the round-3 profile's top non-gather op)."""

        def loss(x):
            return jnp.sum(max_pool_nonoverlap(x, (3, 3)))

        txt = (
            jax.jit(jax.grad(loss))
            .lower(jnp.zeros((2, 236, 236, 64), jnp.bfloat16))
            .compile()
            .as_text()
        )
        assert "select-and-scatter" not in txt.lower()

    def test_grasping44_train_grad_has_no_select_and_scatter(self):
        """Every pool in the Grasping44 tower is non-overlapping; pin that
        the full network gradient stays scatter-free."""
        from tensor2robot_tpu.research.qtopt.networks import Grasping44

        model = Grasping44(num_convs=(1, 1, 1))
        images = jnp.zeros((2, 96, 96, 3), jnp.bfloat16)
        params = jnp.zeros((2, 10), jnp.float32)
        variables = model.init(
            jax.random.PRNGKey(0), images, params, is_training=True
        )

        def loss(v):
            logits, _ = model.apply(
                v, images, params, is_training=True, mutable=["batch_stats"]
            )[0]
            return jnp.sum(logits)

        txt = (
            jax.jit(jax.grad(loss))
            .lower(variables)
            .compile()
            .as_text()
        )
        assert "select-and-scatter" not in txt.lower()


class TestBatchNormDtype:
    def test_tower_activations_stay_bf16(self):
        """BN in compute dtype: with bf16 images no f32 copy of a tower
        activation is produced (the r3 bandwidth finding) — end_points
        carry the compute dtype, while the loss-bearing logits stay f32."""
        from tensor2robot_tpu.research.qtopt.networks import Grasping44

        model = Grasping44(num_convs=(1, 1, 1))
        images = jnp.zeros((2, 96, 96, 3), jnp.bfloat16)
        params = jnp.zeros((2, 10), jnp.float32)
        variables = model.init(
            jax.random.PRNGKey(0), images, params, is_training=True
        )
        (logits, end_points), _ = model.apply(
            variables, images, params, is_training=True,
            mutable=["batch_stats"],
        )
        assert end_points["pool2"].dtype == jnp.bfloat16
        assert end_points["vsum"].dtype == jnp.bfloat16
        assert end_points["final_conv"].dtype == jnp.bfloat16
        assert end_points["fcgrasp"].dtype == jnp.bfloat16
        assert logits.dtype == jnp.float32
        # Running statistics must still accumulate in f32.
        stats = jax.tree_util.tree_leaves(variables["batch_stats"])
        assert all(s.dtype == jnp.float32 for s in stats)


class TestBackendDispatch:
    """max_pool picks the backward per backend; forward is identical."""

    def test_auto_is_scatterfree_off_tpu(self, monkeypatch):
        from tensor2robot_tpu.ops import pooling

        if jax.default_backend() == "tpu":
            pytest.skip("auto resolves to native on a TPU backend")
        monkeypatch.delenv("T2R_POOL_BACKWARD", raising=False)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 12, 12, 3))
        # On the CPU test backend the auto path must be the custom VJP:
        # forward-mode autodiff through it raises (custom_vjp), which is
        # exactly how we can tell the paths apart without reading HLO.
        with pytest.raises(TypeError):
            jax.jvp(lambda x: pooling.max_pool(x, (2, 2)), (x,), (x,))

    def test_forced_native_has_no_custom_vjp(self, monkeypatch):
        from tensor2robot_tpu.ops import pooling

        monkeypatch.setenv("T2R_POOL_BACKWARD", "native")
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 12, 12, 3))
        # Native reduce_window supports forward mode - and matches the
        # scatter-free forward bit-for-bit.
        y, _ = jax.jvp(lambda x: pooling.max_pool(x, (2, 2)), (x,), (x,))
        np.testing.assert_array_equal(
            y, max_pool_nonoverlap(x, (2, 2))
        )

    @pytest.mark.parametrize("mode", ["native", "scatterfree"])
    def test_grads_agree_without_ties(self, monkeypatch, mode):
        from tensor2robot_tpu.ops import pooling

        monkeypatch.setenv("T2R_POOL_BACKWARD", mode)
        # Distinct values in every window => no subgradient tie-breaking
        # ambiguity, so both backwards must agree exactly.
        x = (
            jnp.arange(2 * 12 * 12 * 3, dtype=jnp.float32)
            .reshape(2, 12, 12, 3)
        ) * 0.37
        gx = jax.grad(lambda x: jnp.sum(pooling.max_pool(x, (3, 3)) ** 2))(x)
        want = jax.grad(
            lambda x: jnp.sum(max_pool_nonoverlap(x, (3, 3)) ** 2)
        )(x)
        np.testing.assert_allclose(gx, want, rtol=1e-6)

    def test_unknown_mode_fails_fast(self, monkeypatch):
        from tensor2robot_tpu.ops import pooling

        monkeypatch.setenv("T2R_POOL_BACKWARD", "scatter-free")
        with pytest.raises(ValueError, match="T2R_POOL_BACKWARD"):
            pooling.max_pool(jnp.zeros((1, 4, 4, 1)), (2, 2))
