"""PoseEnv end-to-end testbed tests (reference
research/pose_env/pose_env_models_test.py + pose_env_test.py) and the
dql_grasping_lib module helpers."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import config as cfg
from tensor2robot_tpu.research import pose_env
from tensor2robot_tpu.research.dql_grasping_lib import tf_modules
from tensor2robot_tpu.research.run_env import run_env
from tensor2robot_tpu.specs import TensorSpecStruct, make_random_numpy
from tensor2robot_tpu.utils.writer import TFRecordReplayWriter


class TestPoseToyEnv:
    def test_episode_contract(self):
        env = pose_env.PoseToyEnv(seed=0)
        obs = env.reset()
        assert obs.shape == (64, 64, 3) and obs.dtype == np.uint8
        action = np.zeros(2)
        new_obs, reward, done, debug = env.step(action)
        assert done is True
        assert reward <= 0.0
        assert debug["target_pose"].shape == (2,)
        # Perfect guess gets ~zero penalty.
        _, best_reward, _, _ = env.step(debug["target_pose"])
        assert best_reward == pytest.approx(0.0, abs=1e-5)

    def test_image_depends_on_pose_and_task(self):
        env = pose_env.PoseToyEnv(seed=0)
        obs1 = env.reset()
        env.set_new_pose()
        obs2 = env.reset()
        assert not np.array_equal(obs1, obs2)
        env.reset_task()
        obs3 = env.reset()
        assert not np.array_equal(obs2, obs3)

    def test_hidden_drift_offsets_labels(self):
        env = pose_env.PoseToyEnv(seed=0, hidden_drift=True)
        env.reset()
        _, _, _, debug = env.step(np.zeros(2))
        drift = debug["target_pose"] - env._rendered_pose[:2]
        np.testing.assert_allclose(drift, env._hidden_drift_xy, atol=1e-6)

    def test_golden_trace(self):
        """Fixed-seed rollouts replay the committed golden trace
        bit-exactly (tests/golden/pose_env_golden_trace.npz, regenerated
        only via tools/make_pose_env_golden.py). Pins the analytic
        renderer/reward/task sampling that replaces the reference's
        PyBullet env (reference pose_env.py:52) against silent drift."""
        from tools.make_pose_env_golden import GOLDEN_PATH, rollout

        golden = np.load(GOLDEN_PATH)
        trace = rollout()
        np.testing.assert_array_equal(
            trace["observations"], golden["observations"]
        )
        np.testing.assert_array_equal(trace["actions"], golden["actions"])
        np.testing.assert_array_equal(trace["rewards"], golden["rewards"])
        np.testing.assert_array_equal(
            trace["target_poses"], golden["target_poses"]
        )

    def test_random_policy(self):
        policy = pose_env.PoseEnvRandomPolicy(seed=0)
        action, debug = policy.sample_action(None, 0.0)
        assert action.shape == (2,)
        assert np.all(np.abs(action) <= 1.0)
        assert policy.global_step == 0


class TestTfModules:
    def test_tile_to_match_context(self):
        net = jnp.ones((2, 3))
        context = jnp.ones((2, 4, 8))
        tiled = tf_modules.tile_to_match_context(net, context)
        assert tiled.shape == (2, 4, 3)

    def test_add_context_broadcasts(self):
        net = jnp.zeros((6, 5, 5, 8))
        context = jnp.ones((6, 8))
        out = tf_modules.add_context(net, context)
        assert out.shape == (6, 5, 5, 8)
        np.testing.assert_allclose(out[:, 2, 3, :], 1.0)

    def test_add_context_validates(self):
        with pytest.raises(ValueError, match="rows"):
            tf_modules.add_context(jnp.zeros((4, 5, 5, 8)), jnp.ones((6, 8)))
        with pytest.raises(ValueError, match="Channel"):
            tf_modules.add_context(jnp.zeros((6, 5, 5, 4)), jnp.ones((6, 8)))


class TestPoseEnvModels:
    def test_regression_model_forward_and_loss(self):
        model = pose_env.PoseEnvRegressionModel(device_type="cpu")
        features = TensorSpecStruct()
        features["state"] = np.random.RandomState(0).rand(
            2, 64, 64, 3
        ).astype(np.float32)
        labels = TensorSpecStruct()
        labels["target_pose"] = np.zeros((2, 2), np.float32)
        labels["reward"] = np.ones((2, 1), np.float32)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(variables, features, "train")
        assert outputs["inference_output"].shape == (2, 2)
        loss, _ = model.model_train_fn(features, labels, outputs, "train")
        assert np.isfinite(float(loss))
        # Zero reward weight => zero loss (the MAML dummy-episode trick).
        labels["reward"] = np.zeros((2, 1), np.float32)
        loss0, _ = model.model_train_fn(features, labels, outputs, "train")
        assert float(loss0) == pytest.approx(0.0)

    def test_regression_preprocessor_uint8_to_float(self):
        model = pose_env.PoseEnvRegressionModel(device_type="cpu")
        pre = model.preprocessor
        in_spec = pre.get_in_feature_specification("train")
        assert in_spec["state"].dtype == np.uint8
        features = make_random_numpy(in_spec, batch_size=2)
        out, _ = pre.preprocess(features, None, mode="eval")
        assert out["state"].dtype == jnp.float32
        assert float(jnp.max(out["state"])) <= 1.0

    def test_mc_model_forward_train_and_tiled_predict(self):
        model = pose_env.PoseEnvContinuousMCModel(
            device_type="cpu", action_batch_size=5
        )
        features = TensorSpecStruct()
        features["state/image"] = np.random.RandomState(0).rand(
            2, 64, 64, 3
        ).astype(np.float32)
        features["action/pose"] = np.zeros((2, 2), np.float32)
        labels = TensorSpecStruct()
        labels["reward"] = np.zeros((2,), np.float32)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(variables, features, "train")
        assert outputs["q_predicted"].shape == (2,)
        loss, _ = model.model_train_fn(features, labels, outputs, "train")
        assert np.isfinite(float(loss))

        # CEM-tiled: [B, N, 2] actions -> [B, N] Q values.
        tiled = TensorSpecStruct()
        tiled["state/image"] = features["state/image"]
        tiled["action/pose"] = np.zeros((2, 5, 2), np.float32)
        outputs, _ = model.inference_network_fn(variables, tiled, "predict")
        assert outputs["q_predicted"].shape == (2, 5)

    def test_pack_features_feeds_network(self):
        model = pose_env.PoseEnvContinuousMCModel(device_type="cpu")
        packed = model.pack_features(
            np.zeros((64, 64, 3), np.uint8), None, 0, np.zeros((7, 2))
        )
        assert packed["state/image"].shape == (1, 64, 64, 3)
        assert packed["action/pose"].shape == (1, 7, 2)
        # The packed layout must run through the model's own network.
        features = TensorSpecStruct()
        features["state/image"] = packed["state/image"].astype(np.float32)
        features["action/pose"] = packed["action/pose"].astype(np.float32)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "predict"
        )
        assert outputs["q_predicted"].shape == (1, 7)

    def test_random_policy_collect_loop_interface(self, tmp_path):
        # The shipped run_random_collect config path: collect_eval_loop
        # calls restore()/init_randomly() on the random policy.
        from tensor2robot_tpu.utils.continuous_collect_eval import (
            collect_eval_loop,
        )

        policy = pose_env.PoseEnvRandomPolicy(seed=0)
        final = collect_eval_loop(
            root_dir=str(tmp_path),
            policy=policy,
            run_agent_fn=lambda env, policy, num_episodes, output_dir,
            global_step: None,
            collect_env=pose_env.PoseToyEnv(seed=0),
            num_collect=1,
            max_steps=0,
            max_cycles=1,
        )
        assert final == 0


class TestMamlPackFeatures:
    def make_model(self):
        base = pose_env.PoseEnvRegressionModel(device_type="cpu")
        return pose_env.PoseEnvRegressionModelMAML(
            base_model=base, num_inner_loop_steps=1
        )

    def test_pack_with_demo(self):
        model = self.make_model()
        state = np.zeros((64, 64, 3), np.uint8)
        episode = [(state, np.ones(2, np.float32), 1.0, state, True, {})]
        packed = model.pack_features(state, [episode], 0)
        assert packed["inference/features/state/0"].shape == (1, 64, 64, 3)
        assert packed["condition/features/state/0"].shape == (1, 64, 64, 3)
        # Reward 1 -> mapped to 2r-1 = 1.
        np.testing.assert_allclose(
            packed["condition/labels/reward/0"], [[1.0]]
        )

    def test_pack_without_demo_uses_zero_weight(self):
        model = self.make_model()
        state = np.zeros((64, 64, 3), np.uint8)
        packed = model.pack_features(state, [], 0)
        np.testing.assert_allclose(
            packed["condition/labels/reward/0"], [[0.0]]
        )


class TestEndToEnd:
    """The rebuild of the reference acceptance path: random-collect into
    TFRecords -> train the regression model from the shipped gin config
    (reference pose_env_models_test.py + train_eval_test_utils)."""

    def _collect(self, tmp_path, episodes=48):
        env = pose_env.PoseToyEnv(seed=1)
        policy = pose_env.PoseEnvRandomPolicy(seed=2)
        writer = TFRecordReplayWriter()
        run_env(
            env,
            policy,
            num_episodes=episodes,
            episode_to_transitions_fn=lambda ep: (
                pose_env.episode_to_transitions_pose_toy(
                    ep, binary_success_threshold=-1.5
                )
            ),
            replay_writer=writer,
            output_dir=str(tmp_path / "collect"),
        )
        shards = glob.glob(str(tmp_path / "collect" / "*.tfrecord"))
        assert shards
        return shards

    def test_collect_then_train_from_gin_config(self, tmp_path):
        shards = self._collect(tmp_path)
        config_dir = os.path.join(
            os.path.dirname(pose_env.__file__), "configs"
        )
        cfg.clear_config()
        try:
            cfg.parse_config_files_and_bindings(
                [os.path.join(config_dir, "run_train_reg.gin")],
                [
                    f"TRAIN_DATA = {shards!r}",
                    f"EVAL_DATA = {shards!r}",
                    "train_eval_model.max_train_steps = 3",
                    "train_eval_model.eval_steps = 2",
                    "train_input_generator/DefaultRecordInputGenerator.batch_size = 4",
                    "eval_input_generator/DefaultRecordInputGenerator.batch_size = 4",
                    "PoseEnvRegressionModel.device_type = 'cpu'",
                    f"train_eval_model.model_dir = {str(tmp_path / 'run')!r}",
                ],
            )
            train_eval_model = cfg.get_configurable("train_eval_model")
            metrics = train_eval_model()
            assert np.isfinite(metrics["loss"])
            assert os.path.isdir(tmp_path / "run" / "checkpoints")
        finally:
            cfg.clear_config()


    # ~27s: full shipped-config MAML train run.
    @pytest.mark.slow
    def test_maml_gin_config_trains(self, tmp_path):
        """Executes the shipped MAML config (every shipped gin config must
        run — reference train_eval_test_utils.test_train_eval_gin), with
        random spec-conforming data standing in for meta-example shards
        exactly as the reference MAML tests did (fixture random_train)."""
        config_dir = os.path.join(
            os.path.dirname(pose_env.__file__), "configs"
        )
        cfg.clear_config()
        try:
            cfg.parse_config_files_and_bindings(
                [os.path.join(config_dir, "run_train_reg_maml.gin")],
                [
                    "train_eval_model.input_generator_train ="
                    " @train_rand/DefaultRandomInputGenerator()",
                    "train_eval_model.input_generator_eval ="
                    " @eval_rand/DefaultRandomInputGenerator()",
                    "train_rand/DefaultRandomInputGenerator.batch_size = 2",
                    "eval_rand/DefaultRandomInputGenerator.batch_size = 2",
                    "train_eval_model.max_train_steps = 2",
                    "train_eval_model.eval_steps = 1",
                    "PoseEnvRegressionModel.device_type = 'cpu'",
                    f"train_eval_model.model_dir = {str(tmp_path / 'run')!r}",
                ],
            )
            train_eval_model = cfg.get_configurable("train_eval_model")
            metrics = train_eval_model()
            assert np.isfinite(metrics["loss"])
        finally:
            cfg.clear_config()


class TestReferenceContractParity:
    """The artifact quantifying behavior vs the PyBullet reference
    (/root/reference/research/pose_env/pose_env.py:52-178): the PyBullet
    renderer is replaced by a numpy rasterizer, so pixel-level parity is
    out of scope by design; everything a TRAINING PIPELINE observes —
    spaces, reward law, episode structure, seeding — is asserted here."""

    def test_observation_action_reward_contract(self):
        env = pose_env.PoseToyEnv(seed=1)
        obs = env.reset()
        # Observation: 64x64x3 uint8 image (reference render size).
        assert obs.shape == (64, 64, 3) and obs.dtype == np.uint8
        action = np.array([0.25, -0.5], np.float32)
        obs2, reward, done, info = env.step(action)
        # One-step episodes, target exposed for supervised collection.
        assert done is True
        target = np.asarray(info["target_pose"], np.float32)
        assert target.shape == (2,)
        # Reward law: exact negative euclidean distance to the target.
        np.testing.assert_allclose(
            reward, -np.linalg.norm(action - target), rtol=1e-6
        )
        # Pose domain: planar positions within the unit box.
        assert np.all(target >= -1.0) and np.all(target <= 1.0)

    def test_optimal_action_maximizes_reward(self):
        env = pose_env.PoseToyEnv(seed=3)
        env.reset()
        _, r_opt, _, info = env.step(np.asarray(info_target(env)))
        env2 = pose_env.PoseToyEnv(seed=3)
        env2.reset()
        _, r_bad, _, _ = env2.step(np.array([1.0, 1.0], np.float32))
        assert r_opt == 0.0 or r_opt > r_bad
        assert r_opt >= -1e-6  # acting at the target is the optimum

    def test_seeded_determinism(self):
        a = pose_env.PoseToyEnv(seed=7)
        b = pose_env.PoseToyEnv(seed=7)
        np.testing.assert_array_equal(a.reset(), b.reset())
        act = np.array([0.1, 0.2], np.float32)
        ra = a.step(act)[1]
        rb = b.step(act)[1]
        assert ra == rb


def info_target(env):
    """The env's current target pose (peeking like the reference's tests
    did via the returned info dict)."""
    _, _, _, info = env.step(np.zeros(2, np.float32))
    return info["target_pose"]
